"""dist_async kvstore test (ref: tests/nightly/dist_async_kvstore.py).

Asserts the ASYNC semantics that distinguish it from dist_sync:
a worker's push is merged by the server immediately and a pull right
after sees it WITHOUT waiting for other workers (no barrier). A
file-based handshake makes the interleaving deterministic:

  worker 0: push(+1) -> pull -> must see ONLY its own push -> marker
  worker 1: wait for marker -> push(+2) -> pull -> sees both pushes
  both:     final barrier -> pull -> eventual sum
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import dist  # noqa: E402

dist.init()

import numpy as np  # noqa: E402

from mxnet_tpu import kvstore, nd  # noqa: E402

kv = kvstore.create("dist_async")
rank, size = kv.rank, kv.num_workers
assert size == 2, f"this test is written for 2 workers, got {size}"
marker = os.path.join(os.environ.get("MXTPU_TEST_TMPDIR", "/tmp"),
                      f"dist_async_marker_{os.environ['DMLC_PS_ROOT_PORT']}")

kv.init("w", nd.zeros((4,)))
kv.barrier()  # only to make init-before-push deterministic

if rank == 0:
    kv.push("w", [nd.ones((4,))])
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    # no barrier happened: worker 1 has not pushed yet (it waits on the
    # marker), so the server value is exactly our own contribution
    assert np.allclose(out.asnumpy(), 1.0), out.asnumpy()
    with open(marker, "w") as f:
        f.write("go")
else:
    for _ in range(200):
        if os.path.exists(marker):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("worker 0 never wrote the marker")
    kv.push("w", [nd.ones((4,)) * 2])
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    # server already merged worker 0's earlier push
    assert np.allclose(out.asnumpy(), 3.0), out.asnumpy()

kv.barrier()
final = nd.zeros((4,))
kv.pull("w", out=final)
assert np.allclose(final.asnumpy(), 3.0), final.asnumpy()

# server-side optimizer: each push applies SGD immediately on the server
# (ref: kvstore_dist_server.h DataHandleDefault async branch)
import mxnet_tpu as mx  # noqa: E402

kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
kv.barrier()
kv.push("w", [nd.ones((4,)) * (0.1 * (rank + 1))])
kv.barrier()
final2 = nd.zeros((4,))
kv.pull("w", out=final2)
# w = 3 - 1.0*(0.1 + 0.2)
assert np.allclose(final2.asnumpy(), 2.7, atol=1e-5), final2.asnumpy()
print(f"worker {rank}/{size}: dist_async kvstore OK (per-push merge, "
      f"no barrier, server-side optimizer)")
