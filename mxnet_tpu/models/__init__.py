"""Model families for the five BASELINE workloads (LeNet/ResNet live in
gluon.model_zoo.vision; BERT/Transformer/DeepAR here)."""
from .bert import BERTModel, bert_base, bert_large, bert_tiny  # noqa: F401
from .transformer import (TransformerModel, transformer_big,  # noqa: F401
                          transformer_base, transformer_tiny)
from .deepar import DeepARNetwork, deepar  # noqa: F401
