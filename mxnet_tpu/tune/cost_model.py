"""Fitted candidate-ranking model: spend real trials only where it counts.

Live trials are the ground truth but each one costs a measurement
window (and possibly recompiles).  The cost model is the cheap filter
in front of them, in the spirit of the learned TPU performance model
(arXiv 2008.01040) scaled down to a knob surface: featurize a
candidate config, predict its score, and let the tuner measure only
the top few.

Two information sources, combined:

* **Analytic seed** — the whole-step executable's ``cost_analysis()``
  FLOP/byte counts (surfaced by HealthMonitor as ``flops_per_step``)
  plus the measured phase breakdown (input wait vs compute vs
  collective vs optimizer ms).  Before any trial has run, the seed
  gives a direction: dispatch-overhead knobs (bucket size, fused-group
  size) matter when the optimizer/collective phases dominate; pipeline
  knobs matter when input wait dominates.
* **Measured fit** — every observed ``(config, score)`` pair refits a
  ridge regression over log-scaled knob features (value, value²,
  reciprocal, pairwise cross terms).  The reciprocal term is what lets
  a quadratic-ish model capture 1/v-shaped dispatch-overhead knobs;
  cross terms capture bucket-size × group-size style interaction.

With fewer observations than features the fit is ridge-regularised
toward the analytic prior's direction, so ranking degrades gracefully
to "the seed's guess" instead of to noise.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["CostModel", "check_monotonic_agreement"]


class CostModel:
    """Rank candidate configs; measure only the winners.

    Parameters
    ----------
    registry : KnobRegistry
        Defines the feature space (one block per numeric knob, one
        index feature per choice knob).
    phase_hint : dict, optional
        A HealthMonitor window (``mon.tick()`` dict or the ``health``
        section): ``flops_per_step`` + phase ``*_ms`` keys seed the
        prior.
    ridge : float
        L2 regularisation strength for the fit.
    """

    def __init__(self, registry, phase_hint=None, ridge=1e-3):
        self.registry = registry
        self.ridge = float(ridge)
        self._X = []      # feature rows
        self._y = []      # observed scores
        self._w = None    # fitted weights (lazily refit)
        self._names = list(registry.names())
        self._prior = self._seed_prior(phase_hint or {})

    # -- featurization -------------------------------------------------------

    def _unit(self, knob, value):
        """Map one knob value to [0, 1] on a log scale (linear for
        choice/bool), so every feature block is comparable."""
        if knob.kind == "choice":
            dom = list(knob.domain)
            return dom.index(value) / max(1, len(dom) - 1)
        if knob.kind == "bool":
            return 1.0 if value else 0.0
        lo, hi = knob.bounds
        lo, hi = max(lo, 1e-9), max(hi, 1e-9)
        v = min(max(float(value), lo), hi)
        if hi / lo < 4.0:          # narrow range: linear is fine
            return (v - lo) / (hi - lo) if hi > lo else 0.0
        return float(np.log(v / lo) / np.log(hi / lo))

    def features(self, config):
        """Feature vector for one full config: per knob ``[u, u²,
        1/(u+eps)]`` plus pairwise ``u_i·u_j`` cross terms and a bias
        term."""
        us = []
        for name in self._names:
            knob = self.registry.get(name)
            value = config.get(name, knob.default)
            us.append(self._unit(knob, value))
        feats = [1.0]
        for u in us:
            feats.extend((u, u * u, 1.0 / (u + 0.25)))
        for i in range(len(us)):
            for j in range(i + 1, len(us)):
                feats.append(us[i] * us[j])
        return np.asarray(feats, dtype=np.float64)

    # -- analytic seed -------------------------------------------------------

    def _seed_prior(self, hint):
        """Per-knob direction weights from the phase breakdown: which
        phase a knob attacks decides how much headroom moving it up
        its range plausibly buys.  Returned as a weight vector over
        the linear feature slots (everything else zero)."""
        phase_of = {
            "kvstore_bucket_mb": "collective_ms",
            "aggregate_num": "optimizer_ms",
            "zero_shard": "optimizer_ms",
            "pipeline_prefetch": "input_wait_ms",
            "pipeline_map_inflight": "input_wait_ms",
        }
        total = sum(float(hint.get(k, 0.0)) for k in
                    ("input_wait_ms", "h2d_ms", "compute_ms",
                     "collective_ms", "optimizer_ms", "compile_ms"))
        n = len(self._names)
        dim = 1 + 3 * n + n * (n - 1) // 2
        w = np.zeros(dim, dtype=np.float64)
        if total <= 0:
            return w
        for i, name in enumerate(self._names):
            phase = phase_of.get(name)
            if phase is None:
                continue
            share = float(hint.get(phase, 0.0)) / total
            # linear slot of knob i: deeper prefetch / bigger buckets
            # help in proportion to the phase they hide
            w[1 + 3 * i] = share
        return w

    # -- fitting -------------------------------------------------------------

    def observe(self, config, score):
        """Feed one measured ``(config, score)`` pair (the tuner calls
        this for every real trial, baseline included)."""
        self._X.append(self.features(config))
        self._y.append(float(score))
        self._w = None      # refit lazily on next predict

    def _fit(self):
        X = np.vstack(self._X)
        y = np.asarray(self._y, dtype=np.float64)
        # center scores so the ridge pull-to-zero acts on deltas, and
        # anchor the solution toward the analytic prior direction
        mean = y.mean()
        A = X.T @ X + self.ridge * np.eye(X.shape[1])
        b = X.T @ (y - mean) + self.ridge * self._prior
        w = np.linalg.solve(A, b)
        return w, mean

    def predict(self, config):
        """Predicted score (same units as the objective once ≥2 trials
        are observed; before that, prior-direction pseudo-score)."""
        f = self.features(config)
        if len(self._X) >= 2:
            if self._w is None:
                self._w = self._fit()
            w, mean = self._w
            return float(f @ w + mean)
        return float(f @ self._prior)

    def rank(self, candidates):
        """Sort candidate configs best-predicted-first.  Ties break by
        original order, so with zero signal the ranking is the
        caller's ordering (deterministic)."""
        if not candidates:
            return []
        scored = [(self.predict(c), -i, c)
                  for i, c in enumerate(candidates)]
        scored.sort(key=lambda t: (t[0], t[1]), reverse=True)
        from . import trials as _trials
        _trials._counters["candidates_ranked"] += len(candidates)
        return [c for _s, _i, c in scored]

    def n_observed(self):
        return len(self._y)

    def __repr__(self):
        return (f"CostModel({len(self._names)} knobs, "
                f"{len(self._y)} observations)")


def check_monotonic_agreement(model, configs, scores):
    """Test helper: fraction of candidate pairs whose predicted order
    matches the measured order (1.0 = perfect rank agreement)."""
    if len(configs) != len(scores) or len(configs) < 2:
        raise MXNetError("need >=2 (config, score) pairs")
    preds = [model.predict(c) for c in configs]
    agree = total = 0
    for i in range(len(configs)):
        for j in range(i + 1, len(configs)):
            if scores[i] == scores[j]:
                continue
            total += 1
            if (preds[i] - preds[j]) * (scores[i] - scores[j]) > 0:
                agree += 1
    return agree / max(1, total)
