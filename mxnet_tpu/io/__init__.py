"""IO subsystem (ref: src/io/ + python/mxnet/io/)."""
from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, MNISTIter,  # noqa: F401
                 CSVIter, LibSVMIter, ImageRecordIter, PrefetchingIter,
                 ResizeIter)
from . import recordio  # noqa: F401
