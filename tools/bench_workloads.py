"""Secondary workload benchmarks on the current backend (TPU by default).

The driver's headline bench (bench.py) is ResNet-50; this tool covers
the other BASELINE-class workloads and the custom kernels, one JSON
line per subcommand (ref: example/image-classification/
benchmark_score.py + tools/bandwidth/measure.py roles):

  python tools/bench_workloads.py bert         # BERT-base MLM train step
  python tools/bench_workloads.py transformer  # Transformer-big WMT14 step
  python tools/bench_workloads.py deepar       # DeepAR forecasting step
  python tools/bench_workloads.py attention    # pallas flash vs XLA sdpa
  python tools/bench_workloads.py rnn          # pallas LSTM vs lax.scan
  python tools/bench_workloads.py all
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _setup_jax():
    import jax

    # per-platform cache dirs: the axon tunnel compiles remotely and its
    # XLA:CPU AOT artifacts carry that host's machine features — loading
    # them locally risks SIGILL/slow paths (same split as bench.py)
    plat = jax.devices()[0].platform
    cache = ".jax_cache_cpu" if plat == "cpu" else ".jax_cache"
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(REPO, cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    return jax


def _peak_flops(dev):
    sys.path.insert(0, REPO)
    from bench import _peak_flops as pf

    return pf(dev.device_kind) if dev.platform == "tpu" else None


def _bench_trainer(jax, trainer, x, y, steps, tokens_per_step, metric,
                   extra, analytic_flops=None):
    """Shared harness: warmup, best-of-3 bulk-scan timing, FLOPs via
    cost analysis, chip-aggregated MFU, one JSON line. `extra` keys
    override the defaults (e.g. a different "unit").
    `analytic_flops`: per-step fallback when the HLO cost analysis
    can't see the work (lax.scan bodies — the LSTM recurrence — report
    ~0 flops), so scan-dominated models still get an MFU."""
    trainer.step(x, y).wait_to_read()
    trainer.step_many(x, y, n_steps=steps).asnumpy()  # compile scan
    dt = None
    for _ in range(3):
        t0 = time.perf_counter()
        losses = trainer.step_many(x, y, n_steps=steps)
        losses.asnumpy()
        w = time.perf_counter() - t0
        dt = w if dt is None or w < dt else dt

    dev = jax.devices()[0]
    # shared cost machinery with bench.py: compiled post-fusion cost
    # analysis on TPU (real HBM traffic -> roofline bound), HLO-level
    # lowering off-TPU
    from bench import _roofline_bound, _step_cost

    flops, nbytes = _step_cost(trainer, x, y,
                               allow_compile=(dev.platform != "cpu"))
    if (not flops or flops < 1e6) and analytic_flops:
        flops = analytic_flops
    # cost_analysis FLOPs cover the GLOBAL batch over the dp mesh, so
    # peak must aggregate every chip the step ran on (as bench.py does)
    chip_peak = _peak_flops(dev)
    n_chips = len(trainer.mesh.devices.flat)
    peak = chip_peak * n_chips if chip_peak else None
    mfu = (flops * steps / dt / peak) if (flops and peak) else None
    print(json.dumps(dict({
        "metric": metric, "value": round(steps * tokens_per_step / dt),
        "unit": "tokens/sec", "mfu": round(mfu, 4) if mfu else None,
        "roofline_mfu_bound": _roofline_bound(flops, nbytes, dev),
        "device_kind": dev.device_kind, "platform": dev.platform,
        "final_loss": round(float(losses.asnumpy()[-1]), 4)}, **extra)))


class _Identity:
    """Loss adapter for nets whose forward already returns the loss."""

    def __call__(self, out, _):
        return out


def bench_bert(bs=None, seq_len=128, steps=20):
    """BERT-base MLM+NSP training step (BASELINE config #3)."""
    jax = _setup_jax()
    bs = bs if bs is not None else (
        64 if jax.devices()[0].platform == "tpu" else 32)
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.models import bert as bert_mod
    from mxnet_tpu.parallel import data_parallel

    sys.path.insert(0, os.path.join(REPO, "examples", "bert"))
    sys.path.insert(0, os.path.join(REPO, "examples"))
    from pretrain_bert import BERTForPretrain, synthetic_batch

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    vocab = 30522
    model = bert_mod.bert_base(vocab_size=vocab)
    net = BERTForPretrain(model, vocab)
    net.initialize(mx.init.Xavier())

    trainer = data_parallel.DataParallelTrainer(
        net, _Identity(), "adamw", {"learning_rate": 1e-4, "wd": 0.01},
        compute_dtype="bfloat16")
    x = synthetic_batch(rng, bs, seq_len, vocab)
    y = np.zeros((bs,), np.float32)  # unused by the loss head
    _bench_trainer(jax, trainer, x, y, steps, bs * seq_len,
                   "bert_base_mlm_throughput",
                   {"batch_size": bs, "seq_len": seq_len})


def bench_transformer(bs=None, seq_len=None, steps=20, model="big"):
    """Transformer-{base,big} WMT14-style train step (BASELINE #4).

    TPU default bs 64 x seq 64 (preflight: static tier 4.9 GB of
    16 GB, so utilization not memory binds); CPU stays tiny."""
    jax = _setup_jax()
    on_tpu = jax.devices()[0].platform == "tpu"
    bs = bs if bs is not None else (64 if on_tpu else 32)
    seq_len = seq_len if seq_len is not None else (64 if on_tpu else 32)
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.models import transformer as tfm
    from mxnet_tpu.parallel import data_parallel

    sys.path.insert(0, os.path.join(REPO, "examples", "nmt"))
    sys.path.insert(0, os.path.join(REPO, "examples"))
    from train_transformer import (LabelSmoothedCE, Seq2SeqTrainNet,
                                   synthetic_pairs)

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    vocab = 32000
    net = Seq2SeqTrainNet(getattr(tfm, f"transformer_{model}")(vocab,
                                                               vocab))
    net.initialize(mx.init.Xavier())
    trainer = data_parallel.DataParallelTrainer(
        net, LabelSmoothedCE(), "adam",
        {"learning_rate": 3e-4, "beta2": 0.98},
        compute_dtype="bfloat16")
    src, tgt_in, tgt_out = synthetic_pairs(rng, bs, seq_len, vocab)
    _bench_trainer(jax, trainer, (src, tgt_in), tgt_out, steps,
                   bs * seq_len,
                   f"transformer_{model}_train_throughput",
                   {"batch_size": bs, "seq_len": seq_len})


def bench_deepar(bs=64, context_length=72, prediction_length=24,
                 steps=20, num_cells=40, num_layers=2):
    """DeepAR probabilistic-forecasting train step (BASELINE #5)."""
    jax = _setup_jax()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.parallel import data_parallel

    sys.path.insert(0, os.path.join(REPO, "examples", "forecasting"))
    sys.path.insert(0, os.path.join(REPO, "examples"))
    from train_deepar import synthetic_series

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = models.deepar(num_cells, num_layers)
    net.initialize(mx.init.Xavier())
    trainer = data_parallel.DataParallelTrainer(
        net, _Identity(), "adam", {"learning_rate": 1e-3})
    T = context_length + prediction_length
    x = synthetic_series(rng, bs, T).astype(np.float32)
    y = np.zeros((bs,), np.float32)  # unused by the NLL head
    # scan bodies report ~0 flops to the HLO cost analysis; analytic
    # LSTM count instead: per step/sample/layer one (4H,in)+(4H,H)
    # GEMM pair (2 flops/MAC), training ~= 3x forward
    H = num_cells
    in_sizes = [x.shape[-1] if x.ndim == 3 else 1] + \
        [H] * (num_layers - 1)
    fwd = sum(2 * 4 * H * (i + H) for i in in_sizes) * T * bs
    _bench_trainer(jax, trainer, x, y, steps, bs * T,
                   "deepar_train_throughput",
                   {"batch_size": bs, "series_length": T,
                    "unit": "series points/sec"},
                   analytic_flops=3.0 * fwd)


def bench_attention(bs=8, heads=16, seq=2048, hd=64, iters=20):
    """Pallas flash attention vs the XLA reference sdpa (fwd+bwd)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    rng = np.random.RandomState(0)
    shape = (bs, heads, seq, hd)
    q, k, v = (jnp.asarray(rng.randn(*shape).astype(np.float32),
                           jnp.bfloat16) for _ in range(3))

    def time_fn(f):
        g = jax.jit(jax.grad(lambda q, k, v:
                             jnp.sum(f(q, k, v).astype(jnp.float32)),
                             argnums=(0, 1, 2)))
        g(q, k, v)[0].block_until_ready()  # compile
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = g(q, k, v)
            out[0].block_until_ready()
            w = (time.perf_counter() - t0) / iters
            best = w if best is None or w < best else best
        return best

    t_flash = time_fn(lambda q, k, v: fa.flash_attention(q, k, v,
                                                         causal=True))
    t_ref = time_fn(lambda q, k, v: sdpa_reference(q, k, v, causal=True))
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "flash_attention_fwdbwd_ms",
        "value": round(t_flash * 1e3, 3), "unit": "ms",
        "xla_reference_ms": round(t_ref * 1e3, 3),
        "speedup_vs_xla": round(t_ref / t_flash, 3),
        "shape": list(shape), "causal": True,
        "device_kind": dev.device_kind, "platform": dev.platform}))


def bench_rnn(bs=64, seq=256, input_size=512, hidden=512, iters=10):
    """Fused Pallas LSTM vs the lax.scan path (fwd only, inference)."""
    jax = _setup_jax()
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import rnn as rnn_ops

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(seq, bs, input_size).astype(np.float32))
    params = jnp.asarray(rng.randn(
        rnn_ops.rnn_param_size(1, input_size, hidden, "lstm"))
        .astype(np.float32) * 0.05)
    h0 = jnp.zeros((1, bs, hidden), jnp.float32)
    c0 = jnp.zeros((1, bs, hidden), jnp.float32)

    def time_mode(use_pallas):
        os.environ["MXTPU_RNN_IMPL"] = "pallas" if use_pallas else "scan"
        fn = jax.jit(lambda x, p, h, c: rnn_ops._k_rnn(
            x, p, h, c, state_size=hidden, num_layers=1,
            mode="lstm", state_outputs=True)[0])
        fn(x, params, h0, c0).block_until_ready()
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(x, params, h0, c0)
            out.block_until_ready()
            w = (time.perf_counter() - t0) / iters
            best = w if best is None or w < best else best
        return best

    try:
        t_pallas = time_mode(True)
        t_scan = time_mode(False)
    finally:
        os.environ.pop("MXTPU_RNN_IMPL", None)
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "lstm_fwd_ms", "value": round(t_pallas * 1e3, 3),
        "unit": "ms", "lax_scan_ms": round(t_scan * 1e3, 3),
        "speedup_vs_scan": round(t_scan / t_pallas, 3),
        "shape": [seq, bs, input_size], "hidden": hidden,
        "device_kind": dev.device_kind, "platform": dev.platform}))


def bench_convfuse(bs=128, image=224, steps=20):
    """ResNet-50 NHWC bf16 train step, standard XLA path vs the
    MXTPU_CONV_EPILOGUE=pallas fused conv1x1+BN+ReLU path (VERDICT r2
    #2: the epilogue fusion the roofline analysis calls for).  Emits
    one JSON line per mode; the A/B delta is the fusion's measured
    value on this chip."""
    import os

    jax = _setup_jax()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import data_parallel

    x = np.random.RandomState(0).rand(bs, image, image, 3) \
        .astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, bs).astype(np.float32)
    prev_epilogue = os.environ.get("MXTPU_CONV_EPILOGUE")
    try:
        for mode in ("xla", "pallas"):
            os.environ["MXTPU_CONV_EPILOGUE"] = \
                "" if mode == "xla" else "pallas"
            from mxnet_tpu.gluon.model_zoo import vision

            mx.random.seed(0)
            net = vision.resnet50_v1(layout="NHWC")
            net.initialize(mx.init.Xavier())
            trainer = data_parallel.DataParallelTrainer(
                net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                {"learning_rate": 0.1, "momentum": 0.9},
                compute_dtype="bfloat16")
            _bench_trainer(jax, trainer, x, y, steps, bs,
                           f"resnet50_convfuse_{mode}",
                           {"unit": "images/sec", "batch_size": bs,
                            "image_size": image, "conv_epilogue": mode})
    finally:
        if prev_epilogue is None:
            os.environ.pop("MXTPU_CONV_EPILOGUE", None)
        else:
            os.environ["MXTPU_CONV_EPILOGUE"] = prev_epilogue


def bench_quantized(bs=64, image=224, steps=20, network="resnet50_v1"):
    """INT8 vs fp32 inference throughput on a model-zoo CNN — the
    fork's specialty workload (ref: the ykim362 fork's MKL-DNN INT8
    quantization tier; here int8 rides lax.dot_general int8 kernels,
    SURVEY §2.2 quantization row).  Exports the gluon net to
    symbol+params, quantizes FC/Conv to int8 via
    contrib.quantization.quantize_model, and times executor forward
    for both graphs.  Emits one JSON line per precision; the A/B delta
    is the int8 speedup on this chip."""
    import tempfile
    import time as _time

    jax = _setup_jax()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import symbol as sym_mod
    from mxnet_tpu.contrib import quantization as qz
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = getattr(vision, network)()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x_np = np.random.RandomState(0).rand(bs, 3, image, image) \
        .astype(np.float32)
    net(nd.array(x_np[:2]))  # build params
    tmp = tempfile.mkdtemp(prefix="mxtpu_qbench_")
    prefix = os.path.join(tmp, "net")
    net.export(prefix)
    symbol = sym_mod.load(prefix + "-symbol.json")
    payload = nd.load(prefix + "-0000.params")
    arg_params = {k[4:]: v for k, v in payload.items()
                  if k.startswith("arg:")}
    aux_params = {k[4:]: v for k, v in payload.items()
                  if k.startswith("aux:")}

    qsym, qargs, qaux = qz.quantize_model(
        symbol, arg_params, aux_params, calib_mode="naive",
        calib_data=x_np[: min(bs, 8)])

    dev = jax.devices()[0]
    x = nd.array(x_np)
    for mode, s, a, aux in (("fp32", symbol, arg_params, aux_params),
                            ("int8", qsym, qargs, qaux)):
        ex = s.bind(mx.current_context(), dict(a, data=x),
                    grad_req="null", aux_states=dict(aux))
        ex.forward(is_train=False)[0].wait_to_read()  # compile
        best = None
        for _ in range(3):
            t0 = _time.perf_counter()
            for _ in range(steps):
                out = ex.forward(is_train=False)[0]
            out.wait_to_read()
            w = (_time.perf_counter() - t0) / steps
            best = w if best is None or w < best else best
        print(json.dumps({
            "metric": f"{network}_infer_{mode}",
            "value": round(bs / best, 2), "unit": "images/sec",
            "batch_size": bs, "image_size": image, "network": network,
            "device_kind": dev.device_kind, "platform": dev.platform}))


def bench_io(n_images=2048, size=256, batch_size=128, data_shape=96,
             threads=None):
    """Decode throughput through the native pipeline: JPEG .rec ->
    src/recordio.cc decode/augment threads -> batches (VERDICT r2 #3;
    ref: iter_image_recordio_2.cc, SURVEY §3.5 ~10k img/s target for
    the ResNet-50 hot loop).  Generates a synthetic JPEG dataset in a
    temp dir, then measures steady-state img/s for the native C++
    pipeline and the pure-Python fallback."""
    import shutil
    import tempfile

    import numpy as np

    from mxnet_tpu.io import ImageRecordIter, recordio

    threads = threads or (os.cpu_count() or 4)
    tmp = tempfile.mkdtemp(prefix="mxtpu_iobench_")
    rec = os.path.join(tmp, "bench.rec")
    idx = os.path.join(tmp, "bench.idx")
    try:
        rng = np.random.RandomState(0)
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        # realistic JPEG entropy: smooth gradients + noise, not white
        # noise (which decodes unusually slowly) or flat color (fast)
        base = rng.rand(size, size, 3) * 255
        for i in range(n_images):
            img = np.clip(base + rng.rand(size, size, 3) * 64 - 32,
                          0, 255).astype(np.uint8)
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i % 1000), i, 0), img,
                quality=85))
        w.close()

        for use_native in (True, False):
            it = ImageRecordIter(
                path_imgrec=rec, data_shape=(3, data_shape, data_shape),
                batch_size=batch_size, shuffle=True, rand_crop=True,
                rand_mirror=True, preprocess_threads=threads,
                use_native=use_native)
            n = sum(b.data[0].shape[0] for b in it)  # warm epoch
            it.reset()
            t0 = time.perf_counter()
            n = sum(b.data[0].shape[0] for b in it)
            dt = time.perf_counter() - t0
            print(json.dumps({
                "metric": "imagerecorditer_decode_throughput",
                "value": round(n / dt, 1), "unit": "images/sec",
                "pipeline": "native" if use_native else "python",
                "n_images": n, "src_size": size,
                "data_shape": data_shape, "batch_size": batch_size,
                "threads": threads}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("which", choices=["bert", "transformer", "deepar",
                                     "attention", "rnn", "convfuse",
                                     "quantized", "io", "all"])
    p.add_argument("--batch-size", type=int, default=None,
                   help="override the per-benchmark default batch size")
    p.add_argument("--model", default="big", choices=["base", "big"],
                   help="transformer variant (transformer subcommand)")
    p.add_argument("--network", default="resnet50_v1",
                   help="model-zoo CNN for the quantized A/B")
    p.add_argument("--image-size", type=int, default=224,
                   help="input resolution for the quantized A/B")
    p.add_argument("--steps", type=int, default=20,
                   help="timed steps for the quantized A/B")
    args = p.parse_args()
    bs_kw = {"bs": args.batch_size} if args.batch_size else {}
    if args.which in ("bert", "all"):
        bench_bert(**bs_kw)
    if args.which in ("transformer", "all"):
        bench_transformer(model=args.model, **bs_kw)
    if args.which in ("deepar", "all"):
        bench_deepar(**bs_kw)
    if args.which in ("attention", "all"):
        bench_attention(**bs_kw)
    if args.which in ("rnn", "all"):
        bench_rnn(**bs_kw)
    if args.which in ("convfuse", "all"):
        bench_convfuse(**bs_kw)
    if args.which in ("quantized", "all"):
        bench_quantized(network=args.network, image=args.image_size,
                        steps=args.steps, **bs_kw)
    if args.which in ("io", "all"):
        bench_io(batch_size=args.batch_size or 128)


if __name__ == "__main__":
    main()
