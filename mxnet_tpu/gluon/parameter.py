"""Gluon Parameter / ParameterDict.

Ref: python/mxnet/gluon/parameter.py — Parameter with deferred shape
init, per-context replicas, grad_req; ParameterDict with prefix
namespacing, shared params, save/load.

TPU-native notes: a Parameter holds one NDArray per context; the
single-context case (the common one — SPMD replication happens at the
pjit/kvstore layer, not by materializing copies) is just a one-entry
map.  Deferred init completes when a layer fills the 0-dims from its
first input.  During hybrid tracing ``data()`` returns the traced
stand-in set by the CachedOp (see gluon/block.py).
"""
from __future__ import annotations

import threading

import numpy as np

from .. import initializer as init_mod
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import NDArray

_trace_state = threading.local()


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.stype = stype
        self.grad_stype = grad_stype
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._data = None  # {Context: NDArray}
        self._grad_map = None  # {Context: NDArray}
        self._deferred_init = None  # (init, ctx_list, default_init)
        self._traced_value = None  # set by CachedOp during graph capture

    # -- shape with merge-of-unknowns (MXNet uses 0 for unknown dims) ------

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        assert len(self._shape) == len(new_shape) and all(
            s == 0 or s == n for s, n in zip(self._shape, new_shape)), (
            f"cannot update shape {self._shape} -> {new_shape} for {self.name}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), req
        self._grad_req = req
        if req == "null":
            self._grad_map = None
        elif self._data is not None and self._grad_map is None:
            self._init_grad()

    # -- init ---------------------------------------------------------------

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = list(ctx)
        if self._shape is None or any(s <= 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name}: unknown shape "
                f"{self._shape} and allow_deferred_init=False")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        data = _nd_mod.zeros(self._shape, dtype=self.dtype, ctx=ctx[0])
        desc = init_mod.InitDesc(self.name,
                                 getattr(self, "_init_attrs", None))
        initializer(desc, data)
        self._data = {c: (data if c == ctx[0] else data.copyto(c))
                      for c in ctx}
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if self._shape is None or any(s <= 0 for s in self._shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}")
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        self._grad_map = {}
        for c, d in self._data.items():
            g = _nd_mod.zeros(d.shape, dtype=d.dtype, ctx=c)
            self._grad_map[c] = g
            d._grad = g
            d._grad_req = self._grad_req
            d._in_graph = True

    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name} has not been initialized yet "
                    "(deferred); run a forward pass first")
            raise MXNetError(
                f"Parameter {self.name} has not been initialized. "
                "Call .initialize() first")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"Parameter {self.name} not initialized on {ctx}; "
                f"it lives on {list(self._data)}")

    # -- access -------------------------------------------------------------

    def data(self, ctx=None):
        if self._traced_value is not None:
            return self._traced_value
        self._check_initialized()
        if ctx is None:
            return next(iter(self._data.values()))
        self._check_initialized(ctx)
        return self._data[ctx]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None):
        if self._grad_map is None:
            raise MXNetError(
                f"Parameter {self.name} has no gradient (grad_req="
                f"{self._grad_req!r} or uninitialized)")
        # grads are re-bound by backward(); refresh from data holders
        for c, d in self._data.items():
            self._grad_map[c] = d._grad
        if ctx is None:
            return next(iter(self._grad_map.values()))
        return self._grad_map[ctx]

    def list_grad(self):
        self._check_initialized()
        return [self.grad(c) for c in self._data]

    def list_ctx(self):
        self._check_initialized()
        return list(self._data)

    def zero_grad(self):
        if self._grad_map is None:
            return
        for c, d in self._data.items():
            g = _nd_mod.zeros(d.shape, dtype=d.dtype, ctx=c)
            d._grad = g
            self._grad_map[c] = g

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is not None:
                _, ctx, default_init = self._deferred_init
                self._finish_init(None, ctx, default_init)
            else:
                raise MXNetError(
                    f"Parameter {self.name}: set_data before initialize()")
        for c in list(self._data):
            new = data.copyto(c) if isinstance(data, NDArray) else \
                _nd_mod.array(data, ctx=c)
            grad_req = self._grad_req
            self._data[c] = new
            if grad_req != "null":
                g = _nd_mod.zeros(new.shape, dtype=new.dtype, ctx=c)
                new._grad = g
                new._grad_req = grad_req
                new._in_graph = True
                if self._grad_map is not None:
                    self._grad_map[c] = g

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._check_initialized()
        cur = next(iter(self._data.values()))
        self._data = {c: cur.copyto(c) for c in ctx}
        if self._grad_req != "null":
            self._init_grad()

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is None:
            return
        for c in list(self._data):
            self._data[c] = self._data[c].astype(dtype)
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        from ..symbol import symbol as _sym

        return _sym.var(self.name, shape=self.shape, dtype=self.dtype)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-differentiable constant parameter (ref: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd_mod.array(np.asarray(value))
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Constant(0.0))
        self.init = _ConstInit(value)


class _ConstInit(init_mod.Initializer):
    def __init__(self, value):
        super().__init__()
        self.value = value

    def init_array(self, name, arr):
        arr[:] = self.value


class ParameterDict:
    """Ordered name->Parameter mapping with prefix + sharing
    (ref: gluon.ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            # merge shape hints
            if kwargs.get("shape") is not None and param.shape is not None:
                param.shape = tuple(
                    k if s == 0 else s
                    for s, k in zip(param.shape, kwargs["shape"]))
            return param
        if self._shared is not None and full in self._shared._params:
            self._params[full] = self._shared._params[full]
            return self._params[full]
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, fname, strip_prefix=""):
        out = {}
        for name, p in self.items():
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) \
                else name
            out[key] = p.data()
        _nd_mod.save(fname, out)

    def load(self, fname, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = _nd_mod.load(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                p.shape = loaded[name].shape
                if p._data is None:
                    p.initialize(ctx=ctx or [cpu()])
                    if p._deferred_init is not None:
                        p._finish_deferred_init()
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"Parameter {name} missing in file {fname}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in {fname}: {extra}")

    # -- mapping protocol ---------------------------------------------------

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __getitem__(self, k):
        return self._params[k]

    def __contains__(self, k):
        return k in self._params

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        lines = "\n".join(f"  {p}" for p in self.values())
        return f"ParameterDict '{self._prefix}' (\n{lines}\n)"
