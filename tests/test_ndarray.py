"""NDArray core tests (ref: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation_basic():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert np.allclose(e.asnumpy(), [0, 2, 4, 6, 8])


def test_float64_np_input_downcast():
    a = nd.array(np.random.rand(3, 3))  # float64 numpy in
    assert a.dtype == np.float32


def test_arith():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    y = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((x + y).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((y - x).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((x * y).asnumpy(), [[10, 40], [90, 160]])
    assert np.allclose((y / x).asnumpy(), [[10, 10], [10, 10]])
    assert np.allclose((x + 1).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((1 + x).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((2 - x).asnumpy(), [[1, 0], [-1, -2]])
    assert np.allclose((x ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-x).asnumpy(), [[-1, -2], [-3, -4]])
    assert np.allclose(abs(nd.array([-1.0, 2.0])).asnumpy(), [1, 2])


def test_inplace_arith():
    x = nd.ones((2, 2))
    x += 1
    assert np.allclose(x.asnumpy(), 2)
    x *= 3
    assert np.allclose(x.asnumpy(), 6)


def test_comparison():
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([2.0, 2.0, 2.0])
    assert np.allclose((x > y).asnumpy(), [0, 0, 1])
    assert np.allclose((x == 2).asnumpy(), [0, 1, 0])


def test_matmul_dot():
    a = nd.array(np.arange(6).reshape(2, 3))
    b = nd.array(np.arange(12).reshape(3, 4))
    c = nd.dot(a, b)
    assert c.shape == (2, 4)
    assert np.allclose(c.asnumpy(),
                       np.arange(6).reshape(2, 3) @ np.arange(12).reshape(3, 4))


def test_reshape_transpose():
    x = nd.arange(0, 24).reshape(2, 3, 4)
    assert x.reshape(6, 4).shape == (6, 4)
    assert x.reshape((-1,)).shape == (24,)
    assert x.reshape(0, -1).shape == (2, 12)  # MXNet 0 = copy dim
    assert x.transpose().shape == (4, 3, 2)
    assert x.transpose(0, 2, 1).shape == (2, 4, 3)
    assert x.T.shape == (4, 3, 2)
    assert x.flatten().shape == (2, 12)


def test_reductions():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert np.isclose(x.sum().asscalar(), 66)
    assert np.allclose(x.sum(axis=0).asnumpy(), [12, 15, 18, 21])
    assert np.allclose(x.mean(axis=1).asnumpy(), [1.5, 5.5, 9.5])
    assert x.sum(axis=1, keepdims=True).shape == (3, 1)
    assert np.isclose(x.max().asscalar(), 11)
    assert np.isclose(x.min().asscalar(), 0)
    assert np.isclose(x.norm().asscalar(), np.sqrt((np.arange(12) ** 2).sum()))
    assert np.allclose(x.argmax(axis=1).asnumpy(), [3, 3, 3])


def test_indexing():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(4, 6))
    assert np.allclose(x[1].asnumpy(), np.arange(6) + 6)
    assert np.allclose(x[1:3].asnumpy(),
                       np.arange(24).reshape(4, 6)[1:3])
    assert np.isclose(x[2, 3].asscalar(), 15)
    assert np.allclose(x[:, 2].asnumpy(), [2, 8, 14, 20])
    # advanced indexing with array
    idx = nd.array([0, 2], dtype="int32")
    assert np.allclose(x[idx].asnumpy(), np.arange(24).reshape(4, 6)[[0, 2]])


def test_setitem():
    x = nd.zeros((3, 3))
    x[1] = 5.0
    assert np.allclose(x.asnumpy()[1], 5)
    x[0, 2] = 1.0
    assert np.isclose(x.asnumpy()[0, 2], 1)
    x[:, 0] = nd.array([7.0, 8.0, 9.0])
    assert np.allclose(x.asnumpy()[:, 0], [7, 8, 9])


def test_astype_copy():
    x = nd.array([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == np.int32
    z = x.copy()
    z += 1
    assert np.allclose(x.asnumpy(), [1.5, 2.5])


def test_context():
    x = nd.zeros((2, 2), ctx=mx.cpu())
    assert x.context.device_type in ("cpu", "xla")
    y = x.as_in_context(mx.xla(0))
    assert y.shape == (2, 2)
    y2 = x.copyto(mx.xla(1))
    assert y2.context.device_id == 1


def test_wait_async():
    x = nd.ones((100, 100))
    y = nd.dot(x, x)
    y.wait_to_read()
    nd.waitall()
    assert np.isclose(y.asnumpy()[0, 0], 100)


def test_save_load_dtype_round_trip(tmp_path):
    """Every supported dtype survives the .params container exactly —
    including bfloat16, the TPU-native compute/checkpoint dtype the
    reference never had (ref: NDArray::Save/Load dtype preservation)."""
    rng = np.random.RandomState(0)
    arrs = {
        "f32": nd.array(rng.rand(3, 2).astype(np.float32)),
        "f16": nd.array(rng.rand(4).astype(np.float16)),
        "bf16": nd.ones((2, 2), dtype="bfloat16") * 1.5,
        "u8": nd.array(np.arange(5, dtype=np.uint8)),
        "i8": nd.array(np.arange(-3, 3, dtype=np.int8)),
        "i32": nd.array(np.arange(4, dtype=np.int32)),
    }
    path = str(tmp_path / "dtypes.params")
    nd.save(path, arrs)
    back = nd.load(path)
    assert set(back) == set(arrs)
    for k, orig in arrs.items():
        got = back[k]
        assert str(got.dtype) == str(orig.dtype), (k, got.dtype)
        assert got.shape == orig.shape
        assert np.array_equal(got.asnumpy().astype(np.float64),
                              orig.asnumpy().astype(np.float64)), k


def test_save_load_list_dict(tmp_path):
    f = str(tmp_path / "t.params")
    a, b = nd.ones((2, 2)), nd.arange(0, 4)
    nd.save(f, [a, b])
    la, lb = nd.load(f)
    assert np.allclose(la.asnumpy(), 1) and np.allclose(lb.asnumpy(), [0, 1, 2, 3])
    nd.save(f, {"arg:w": a, "aux:m": b})
    d = nd.load(f)
    assert set(d) == {"arg:w", "aux:m"}


def test_load_truncated_params_is_loud(tmp_path):
    """Regression: a short read (writer killed mid-save) must raise a
    clear corrupt/truncated MXNetError, not a raw struct/EOF error."""
    import pytest

    import mxnet_tpu as mx

    f = str(tmp_path / "t.params")
    nd.save(f, {"w": nd.ones((64, 64)), "b": nd.ones((64,))})
    whole = open(f, "rb").read()
    for cut in (len(whole) - 7,   # inside the last tensor
                40,               # inside the manifest
                10):              # inside the manifest-length header
        with open(f, "wb") as fh:
            fh.write(whole[:cut])
        with pytest.raises(mx.MXNetError, match="corrupt or truncated"):
            nd.load(f)
    with open(f, "wb") as fh:     # wrong container entirely
        fh.write(b"garbage-not-a-params-file")
    with pytest.raises(mx.MXNetError, match="bad magic"):
        nd.load(f)


def test_concat_stack_split():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(nd.arange(0, 12).reshape(2, 6), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)


def test_broadcast_ops():
    x = nd.ones((2, 1, 3))
    y = nd.ones((1, 4, 3))
    assert nd.broadcast_add(x, y).shape == (2, 4, 3)
    assert nd.broadcast_to(nd.ones((1, 3)), shape=(5, 3)).shape == (5, 3)
    assert nd.broadcast_axis(nd.ones((1, 3)), axis=0, size=4).shape == (4, 3)


def test_take_pick_onehot_where():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    t = nd.take(x, nd.array([0, 2], dtype="int32"), axis=0)
    assert t.shape == (2, 4)
    p = nd.pick(x, nd.array([0, 1, 2]), axis=1)
    assert np.allclose(p.asnumpy(), [0, 5, 10])
    oh = nd.one_hot(nd.array([0, 2], dtype="int32"), depth=4)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0, 0], [0, 0, 1, 0]])
    w = nd.where(nd.array([1.0, 0.0]), nd.array([1.0, 1.0]), nd.array([2.0, 2.0]))
    assert np.allclose(w.asnumpy(), [1, 2])


def test_engine_naive_mode():
    mx.engine.set_engine_type("NaiveEngine")
    try:
        x = nd.ones((4, 4)) * 3
        assert np.allclose(x.asnumpy(), 3)
    finally:
        mx.engine.set_engine_type("ThreadedEngine")


def test_iter_len():
    x = nd.arange(0, 6).reshape(3, 2)
    rows = list(x)
    assert len(x) == 3 and len(rows) == 3
    assert np.allclose(rows[2].asnumpy(), [4, 5])


def test_histogram():
    """(hist, edges) numpy parity incl. explicit edges
    (ref: mx.nd.histogram)."""
    x = nd.array(np.array([0.1, 0.4, 0.4, 2.5, 3.9], np.float32))
    h, e = nd.histogram(x, bins=4, range=(0.0, 4.0))
    np.testing.assert_array_equal(h.asnumpy(), [3, 0, 1, 1])
    np.testing.assert_allclose(e.asnumpy(), [0, 1, 2, 3, 4])
    edges = nd.array(np.array([0.0, 0.5, 4.0], np.float32))
    h2, e2 = nd.histogram(x, bins=edges)
    np.testing.assert_array_equal(h2.asnumpy(), [3, 2])
    np.testing.assert_allclose(e2.asnumpy(), edges.asnumpy())
    # default range spans the data
    h3, e3 = nd.histogram(x, bins=2)
    assert h3.asnumpy().sum() == 5
