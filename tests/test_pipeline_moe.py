"""Pipeline (pp) and expert (ep) parallelism — oracle equivalence on
the virtual 8-device mesh (capability upgrades beyond the reference;
SURVEY §2.3 marks both ABSENT upstream)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import mesh as mesh_mod
from mxnet_tpu.parallel.moe import MoEBlock, moe_ffn
from mxnet_tpu.parallel.pipeline import pipeline_apply

P, D = 4, 8


def _stage(params, xb):
    W, b = params
    return jax.nn.relu(xb @ W + b)


def _pipeline_fixture():
    mesh = mesh_mod.make_mesh({"pp": P}, devices=jax.devices()[:P])
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(P, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(P, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))
    return mesh, Ws, bs, x


def _sequential(Ws, bs, x):
    for i in range(P):
        x = jax.nn.relu(x @ Ws[i] + bs[i])
    return x


def test_pipeline_matches_sequential():
    mesh, Ws, bs, x = _pipeline_fixture()
    out = pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=4)
    assert np.allclose(np.asarray(out), np.asarray(_sequential(Ws, bs, x)),
                       atol=1e-5)
    # more microbatches than stages (smaller bubble) must also match
    out8 = pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=8)
    assert np.allclose(np.asarray(out8), np.asarray(out), atol=1e-5)


def test_pipeline_gradients_match():
    mesh, Ws, bs, x = _pipeline_fixture()

    def loss_pp(Ws, bs):
        return (pipeline_apply(_stage, (Ws, bs), x, mesh,
                               n_micro=4) ** 2).mean()

    def loss_seq(Ws, bs):
        return (_sequential(Ws, bs, x) ** 2).mean()

    g = jax.grad(loss_pp, argnums=(0, 1))(Ws, bs)
    gref = jax.grad(loss_seq, argnums=(0, 1))(Ws, bs)
    for a, b in zip(g, gref):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_validates_microbatching():
    mesh, Ws, bs, x = _pipeline_fixture()
    with pytest.raises(MXNetError):
        pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=3)  # 8 % 3


def test_moe_sharded_matches_dense_oracle():
    mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=1)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    y, aux = jax.jit(lambda v: moe_ffn(v, *blk.params(), mesh=mesh))(x)
    # dense per-token oracle: each kept token = gate * expert_ffn(token)
    probs = jax.nn.softmax(x @ blk.router_w, -1)
    e = jnp.argmax(probs, -1)
    gate = jnp.max(probs, -1)
    onehot = jax.nn.one_hot(e, 4, dtype=jnp.int32)
    pos = (jnp.cumsum(onehot, 0) * onehot - 1).max(-1)
    C = max(1, int(1.25 * 32 / 4))
    keep = np.asarray(pos < C)
    ref = []
    for i in range(32):
        ei = int(e[i])
        h = jax.nn.relu(x[i] @ blk.w1[ei] + blk.b1[ei])
        ref.append((h @ blk.w2[ei] + blk.b2[ei]) * gate[i] * keep[i])
    assert np.allclose(np.asarray(y), np.asarray(jnp.stack(ref)),
                       atol=1e-4)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow():
    """With capacity_factor << 1 most tokens overflow and pass zeros."""
    blk = MoEBlock(num_experts=2, d_model=4, d_hidden=8, seed=0)
    x = jnp.asarray(np.random.RandomState(1).randn(64, 4)
                    .astype(np.float32))
    y, _ = moe_ffn(x, *blk.params(), capacity_factor=0.05)
    routed = (jnp.abs(y).sum(-1) > 1e-6).sum()
    assert int(routed) <= 2 * max(1, int(0.05 * 64 / 2))


def test_moe_gradients_finite_and_balanced_loss():
    mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=2)
    x = jnp.asarray(np.random.RandomState(2).randn(32, 8)
                    .astype(np.float32))

    def loss(params):
        y, aux = moe_ffn(x, *params, mesh=mesh)
        return (y ** 2).mean() + 0.01 * aux

    g = jax.grad(loss)(blk.params())
    for leaf in g:
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
    # router must receive gradient (through gate and aux loss)
    assert np.abs(np.asarray(g[0])).max() > 0


def test_gluon_moe_block_trains():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    mx.random.seed(0)
    moe = gluon.contrib.nn.MoEFFN(num_experts=4, d_model=8, d_hidden=16)
    moe.initialize(mx.init.Xavier())
    moe.hybridize()
    x = nd.random.uniform(shape=(32, 8))
    target = nd.array(np.sin(x.asnumpy() * 2))
    tr = gluon.Trainer(moe.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    losses = []
    for _ in range(30):
        with autograd.record():
            y, aux = moe(x)
            loss = ((y - target) ** 2).mean() + 0.01 * aux.sum()
        loss.backward()
        tr.step(1)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    with pytest.raises(ValueError):
        gluon.contrib.nn.MoEFFN(num_experts=1, d_model=4, d_hidden=4)


def test_moe_accepts_sequence_input():
    """(batch, seq, d_model) transformer activations flatten through
    the token axis and come back in shape."""
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=4)
    x3 = jnp.asarray(np.random.RandomState(3).randn(2, 16, 8)
                     .astype(np.float32))
    y3, aux = moe_ffn(x3, *blk.params())
    assert y3.shape == (2, 16, 8)
    y2, _ = moe_ffn(x3.reshape(32, 8), *blk.params())
    assert np.allclose(np.asarray(y3).reshape(32, 8), np.asarray(y2),
                       atol=1e-6)


# ---------------------------------------------------------------------------
# r3: PP/EP product surface (VERDICT r2 #4)


def test_pipeline_lm_matches_reference_all_axes():
    """PipelineLMTrainer's first-step loss must equal the single-device
    oracle on every axis combination: pure pp, pure tp, pure dp, and
    the combined 3D mesh (non-uniform stages: embed on stage 0, head
    on the last stage, real lax.cond branches)."""
    import jax

    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    V, D, L, F, H, S = 64, 32, 4, 64, 4, 16
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (8, S))
    tgts = np.roll(toks, -1, axis=1)
    devs = jax.devices()
    cases = [({"dp": 1, "tp": 1, "pp": 2}, 2, 2),
             ({"dp": 1, "tp": 2, "pp": 1}, 2, 1),
             ({"dp": 2, "tp": 1, "pp": 1}, 2, 1),
             ({"dp": 1, "tp": 1, "pp": 4}, 4, 4),
             ({"dp": 2, "tp": 2, "pp": 2}, 8, 2),
             # sequence parallelism (Ulysses all_to_all inside the
             # blocks), alone and composed into the full 4D mesh
             ({"dp": 1, "sp": 2, "tp": 1, "pp": 1}, 2, 1),
             ({"dp": 1, "sp": 4, "tp": 1, "pp": 1}, 4, 1),
             ({"dp": 1, "sp": 2, "tp": 2, "pp": 2}, 8, 2),
             ({"dp": 2, "sp": 2, "tp": 1, "pp": 2}, 8, 2)]
    for shape, n_dev, stages in cases:
        params = plm.init_pipeline_lm(V, D, L, F, H, S,
                                      n_stages=stages, seed=0)
        ref = float(plm.reference_lm_loss(
            params, np.asarray(toks), np.asarray(tgts), H))
        mesh = mesh_mod.make_mesh(shape, devices=devs[:n_dev])
        tr = plm.PipelineLMTrainer(params, mesh, n_heads=H, n_micro=2,
                                   lr=1e-3)
        got = tr.step(toks, tgts)
        assert abs(ref - got) < 2e-4, (shape, ref, got)


def test_pipeline_lm_trains_on_3d_mesh():
    """A transformer LM trains under dp x tp x pp on the 8-device mesh
    (the VERDICT r2 #4 done-criterion)."""
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    V, D, L, F, H, S = 64, 32, 4, 64, 4, 16
    params = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=2, seed=0)
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    tr = plm.PipelineLMTrainer(params, mesh, n_heads=H, n_micro=2,
                               lr=3e-3)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (8, S))
    tgts = np.roll(toks, -1, axis=1)
    losses = [tr.step(toks, tgts) for _ in range(13)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses
    # stacking/mesh mismatch is a loud error, not silently-skipped layers
    import mxnet_tpu as mx
    bad = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=4, seed=0)
    with pytest.raises(mx.MXNetError, match="n_stages"):
        plm.PipelineLMTrainer(bad, mesh, n_heads=H)
    # heads must divide tp*sp for the Ulysses head split
    mesh4 = mesh_mod.make_mesh({"dp": 1, "sp": 2, "tp": 2, "pp": 2})
    p2 = plm.init_pipeline_lm(V, D, L, F, 2, S, n_stages=2, seed=0)
    with pytest.raises(mx.MXNetError, match="tp\\*sp"):
        plm.PipelineLMTrainer(p2, mesh4, n_heads=2)


def test_pipeline_lm_trains_on_4d_mesh():
    """dp x sp x tp x pp simultaneously: the long-context axis
    (Ulysses sequence parallelism) composes with the other three."""
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    V, D, L, F, H, S = 64, 32, 4, 64, 4, 16
    params = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=2, seed=0)
    mesh = mesh_mod.make_mesh({"dp": 1, "sp": 2, "tp": 2, "pp": 2})
    tr = plm.PipelineLMTrainer(params, mesh, n_heads=H, n_micro=2,
                               lr=3e-3)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (8, S))
    tgts = np.roll(toks, -1, axis=1)
    losses = [tr.step(toks, tgts) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.75, losses


def test_moe_top2_oracle_and_ep():
    """Top-2 GShard routing: renormalized pair gates, first-choice
    capacity priority; with generous capacity it must equal the dense
    two-expert mixture, sharded or not."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import mesh as mesh_mod, moe

    blk = moe.MoEBlock(4, 16, 32, seed=1)
    x = jnp.asarray(np.random.RandomState(0).rand(64, 16)
                    .astype(np.float32))
    router_w, w1, b1, w2, b2 = blk.params()
    got, _ = moe.moe_ffn(x, *blk.params(), top_k=2,
                         capacity_factor=100.0)
    probs = jax.nn.softmax(x @ router_w, -1)
    g, e = jax.lax.top_k(probs, 2)
    g = g / g.sum(-1, keepdims=True)
    want = []
    for i in range(x.shape[0]):
        acc = 0
        for j in range(2):
            ei = int(e[i, j])
            h = jax.nn.relu(x[i] @ w1[ei] + b1[ei])
            acc = acc + g[i, j] * (h @ w2[ei] + b2[ei])
        want.append(acc)
    want = jnp.stack(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    got_ep, _ = moe.moe_ffn(x, *blk.params(), mesh=mesh, top_k=2,
                            capacity_factor=100.0)
    np.testing.assert_allclose(np.asarray(got_ep), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_top2_capacity_priority():
    """Over-capacity: every token's FIRST choice wins a slot before any
    second choice (GShard priority), so with capacity exactly S/E the
    primary routes survive and most secondaries drop."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import moe

    S, M, E = 16, 8, 4
    blk = moe.MoEBlock(E, M, 16, seed=3)
    x = jnp.asarray(np.random.RandomState(2).rand(S, M)
                    .astype(np.float32))
    # top_k=2 with capacity_factor=0.5 -> C = S/E: room for the
    # primaries only (if perfectly balanced)
    y, aux = moe.moe_ffn(x, *blk.params(), top_k=2,
                         capacity_factor=0.5)
    assert np.isfinite(np.asarray(y)).all()
    # must differ from the full-capacity result (secondaries dropped)
    y_full, _ = moe.moe_ffn(x, *blk.params(), top_k=2,
                            capacity_factor=100.0)
    assert not np.allclose(np.asarray(y), np.asarray(y_full))


def test_pipeline_lm_checkpoint_resume(tmp_path):
    """Kill-and-resume on the 4D trainer: save mid-run, rebuild a fresh
    trainer from a DIFFERENT init, load, and the continued loss curve
    must match the unbroken run exactly."""
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    V, D, L, F, H, S = 64, 32, 4, 64, 4, 16
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (8, S))
    tgts = np.roll(toks, -1, axis=1)

    params = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=2, seed=0)
    tr = plm.PipelineLMTrainer(params, mesh, n_heads=H, n_micro=2,
                               lr=3e-3)
    for _ in range(3):
        tr.step(toks, tgts)
    ck = str(tmp_path / "plm.npz")
    tr.save_states(ck)
    unbroken = [tr.step(toks, tgts) for _ in range(2)]

    other = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=2, seed=9)
    tr2 = plm.PipelineLMTrainer(other, mesh, n_heads=H, n_micro=2,
                                lr=3e-3)
    tr2.load_states(ck)
    resumed = [tr2.step(toks, tgts) for _ in range(2)]
    np.testing.assert_allclose(resumed, unbroken, rtol=1e-6)
    # wrong-shape checkpoint is a loud error
    import mxnet_tpu as mx
    small = plm.init_pipeline_lm(V, 16, L, F, H, S, n_stages=2, seed=0)
    tr3 = plm.PipelineLMTrainer(small, mesh, n_heads=H, n_micro=2)
    with pytest.raises(mx.MXNetError, match="shape"):
        tr3.load_states(ck)


def test_pipeline_causal_attention_flash_parity(interpret_pallas,
                                                monkeypatch):
    """_causal_attention's TPU route (Pallas flash, no (S,S) matrix in
    HBM) must match the XLA reference — checked in interpret mode with
    the backend probe forced to the TPU branch, and with a spy proving
    the kernel ACTUALLY ran (a silent fallback would make this
    naive-vs-naive)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas import flash_attention as fa_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    rng = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rng.rand(2, 2, 128, 64).astype(np.float32))
               for _ in range(3))
    calls = []
    orig = fa_mod._flash_sdpa

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(fa_mod, "_flash_sdpa", spy)
    monkeypatch.setenv("MXTPU_DISABLE_PALLAS", "1")
    naive = plm._causal_attention(q, k, v)
    assert not calls  # reference side really was the reference
    monkeypatch.delenv("MXTPU_DISABLE_PALLAS")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    flash = plm._causal_attention(q, k, v)
    assert calls, "flash kernel never ran (silent fallback)"
    np.testing.assert_allclose(np.asarray(flash), np.asarray(naive),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_lm_remat_matches():
    """remat=True (jax.checkpoint around each block) trades FLOPs for
    memory: the first-step loss is identical, and the trajectory stays
    within recompute rounding (recomputed activations fuse differently
    at f32, so later steps drift at the 1e-3 level, not more)."""
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    V, D, L, F, H, S = 64, 32, 4, 64, 4, 16
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2, "pp": 2})
    rng = np.random.RandomState(0)
    toks = rng.randint(0, V, (8, S))
    tgts = np.roll(toks, -1, axis=1)
    runs = {}
    for remat in (False, True):
        params = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=2,
                                      seed=0)
        tr = plm.PipelineLMTrainer(params, mesh, n_heads=H, n_micro=2,
                                   lr=3e-3, remat=remat)
        runs[remat] = [tr.step(toks, tgts) for _ in range(4)]
    np.testing.assert_allclose(runs[True][0], runs[False][0], rtol=1e-6)
    np.testing.assert_allclose(runs[True], runs[False], rtol=5e-3)
    assert runs[True][-1] < runs[True][0]
    # remat must actually be IN the graph (a dropped kwarg would leave
    # this test vacuously green): the jaxpr carries a remat/checkpoint
    # eqn only for the remat=True build
    import jax

    from mxnet_tpu.parallel.pipeline_lm import _stage

    params = plm.init_pipeline_lm(V, D, L, F, H, S, n_stages=1, seed=0)
    local = {k: v[0] for k, v in params["blocks"].items()}

    def has_remat(remat):
        jaxpr = jax.make_jaxpr(
            lambda b, h: _stage(b, h, n_heads_local=H, tp_axis=None,
                                tp=1, remat=remat))(
            local, np.zeros((2, S, D), np.float32))
        return "remat" in str(jaxpr) or "checkpoint" in str(jaxpr)

    assert has_remat(True) and not has_remat(False)


def test_moe_expert_parallel_trainer_parity():
    """EP as trainer-level product surface: DataParallelTrainer with
    gluon_moe_param_spec_fn shards MoEFFN's expert-stacked params over
    'ep' and the loss trajectory matches the unsharded run exactly."""
    import os
    import sys

    import jax

    _ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel import data_parallel
    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel.moe import gluon_moe_param_spec_fn

    sys.path.insert(0, os.path.join(_ROOT, "examples"))
    sys.path.insert(0, os.path.join(_ROOT, "examples", "moe"))
    from train_moe_lm import MoETransformerLM, synthetic_batch

    class LMWithAux:
        def __init__(self):
            self.sce = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)

        def __call__(self, out, label):
            logits, aux = out
            return nd.mean(self.sce(logits, label)) + 0.01 * aux.sum()

    rng = np.random.RandomState(0)
    x, y = synthetic_batch(rng, 16, 16, 64)
    losses = {}
    for ep in (1, 2):
        mx.random.seed(0)
        np.random.seed(0)
        net = MoETransformerLM(64, n_experts=4)
        net.initialize(mx.init.Xavier())
        mesh = mesh_mod.make_mesh({"dp": 2, "ep": ep},
                                  devices=jax.devices()[:2 * ep])
        tr = data_parallel.DataParallelTrainer(
            net, LMWithAux(), "adam", {"learning_rate": 3e-3},
            mesh=mesh, param_spec_fn=gluon_moe_param_spec_fn(mesh))
        losses[ep] = [float(tr.step(x, y).asnumpy()) for _ in range(3)]
        if ep == 2:  # experts really sharded, not silently replicated
            specs = [str(s.spec) for (n, _), s in
                     zip(tr._named, tr._param_shardings)
                     if "moeffn" in n and "router" not in n]
            assert specs and all("ep" in s for s in specs), specs
    np.testing.assert_allclose(losses[1], losses[2], rtol=1e-4)
