"""Multi-axis spmd mesh gate for `make verify` (docs/parallelism.md).

On the virtual 8-device mesh shaped (dp=4, mp=2): 30 post-warmup whole
steps under a decaying LR schedule must run as ONE counted device
dispatch each with ZERO post-warmup XLA compiles and the spmd path
engaged on every step (spmd_steps == steps, no fallbacks); the ZeRO
optimizer state must measure under 1/4 of its full bytes on any single
device (the 1/(dp·mp) sharding contract, bias replication included);
5-step weights must be allclose to the single-device whole-step
reference (GSPMD reassociates the batch/matmul reductions — allclose,
not bit-equal, is the cross-path contract); and an elastic
(dp=4,mp=2) → (dp=2,mp=2) restore must adopt params AND optimizer
state bit-exactly.  CPU backend: deterministic and fast on any host.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# A/B arms (spmd vs single-device) — exported knobs would collapse them
for _var in ("MXTPU_MESH_SHAPE", "MXNET_MESH_SHAPE",
             "MXTPU_WHOLE_STEP", "MXNET_WHOLE_STEP",
             "MXTPU_ZERO_SHARD", "MXNET_ZERO_SHARD",
             "MXTPU_PP_MICROBATCHES", "MXNET_PP_MICROBATCHES",
             "MXTPU_OPTIMIZER_AGGREGATION_SIZE",
             "MXNET_OPTIMIZER_AGGREGATION_SIZE"):
    os.environ.pop(_var, None)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # XLA_FLAGS above already provides the 8-device mesh

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import _imperative, gluon, lr_scheduler, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon import trainer as trainer_mod  # noqa: E402

N_LAYERS, UNITS, WARMUP, STEPS = 4, 16, 3, 30


def loss_fn(out, y):
    return (out - y) ** 2


def build(mesh_shape=None):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(N_LAYERS):
        # 16 units: divisible by mp=2 (dim-0 column split) AND by the
        # dp=4 state axis, so every momentum buffer shards both ways
        net.add(nn.Dense(UNITS, in_units=UNITS, activation="tanh"))
    net.initialize(mx.init.Xavier(), ctx=mx.xla(0))
    kwargs = {"learning_rate": 0.1, "momentum": 0.9,
              "lr_scheduler": lr_scheduler.FactorScheduler(
                  step=5, factor=0.95, base_lr=0.1)}
    trainer = gluon.Trainer(net.collect_params(), "sgd", kwargs,
                            whole_step=True if mesh_shape is None
                            else None,
                            mesh_shape=mesh_shape,
                            zero_shard=mesh_shape is not None)
    x = np.random.rand(8, UNITS).astype(np.float32)
    y = np.random.rand(8, UNITS).astype(np.float32)
    return net, trainer, x, y


def host_blob(blob):
    import pickle

    from mxnet_tpu.checkpoint import manager as _mgr

    return pickle.loads(pickle.dumps(_mgr._fetch(_mgr._capture(blob))))


def states(tr):
    out = []
    for st in tr._states:
        entry = next(iter(st.values())) if st else None
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(s.asnumpy() for s in entry))
        else:
            out.append((entry.asnumpy(),))
    return out


def main():
    net, trainer, x, y = build("dp=4,mp=2")
    for _ in range(WARMUP):
        trainer.whole_step(net, loss_fn, x, y)
    nd.waitall()
    lr0 = trainer.learning_rate
    trainer_mod.reset_trainer_step_stats()
    c0 = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    for _ in range(STEPS):
        trainer.whole_step(net, loss_fn, x, y)
    nd.waitall()
    compiles = _imperative.compiled_executable_count() - c0
    dispatches = _imperative.device_dispatch_count() - d0
    stats = trainer_mod.trainer_step_stats()
    assert compiles == 0, \
        f"spmd whole step recompiled: {compiles} new executables in " \
        f"{STEPS} post-warmup steps (lr must ride as a traced scalar)"
    assert dispatches == STEPS, \
        f"{dispatches} device dispatches for {STEPS} spmd steps — " \
        "eager work is leaking into the compiled step loop"
    assert stats["spmd_steps"] == STEPS and \
        stats["whole_step_fallbacks"] == 0, \
        f"spmd path did not engage: {stats}"
    assert trainer.learning_rate < lr0, \
        f"LR schedule did not decay ({lr0} -> {trainer.learning_rate})"

    # measured per-device optimizer-state bytes: 1/(dp*mp) for the
    # (16,16) momenta, 1/mp for biases -> well under 1/4 of full
    comp = trainer._whole_step_compiler
    per_dev = comp.state_bytes_per_device()
    full = sum(int(np.prod(s.shape)) * s.dtype.itemsize
               for gsts in comp._gstates for s in gsts)
    assert 0 < per_dev < full / 4, \
        f"optimizer state not mesh-sharded: {per_dev} bytes on device " \
        f"0 vs {full} full"

    # 5-step allclose parity vs the single-device whole step
    net_m, tr_m, xm, ym = build("dp=4,mp=2")
    net_s, tr_s, xs_, ys_ = build(None)
    for _ in range(5):
        tr_m.whole_step(net_m, loss_fn, xm, ym)
        tr_s.whole_step(net_s, loss_fn, xs_, ys_)
    nd.waitall()
    for (pm, ps) in zip(net_m._ordered_params(), net_s._ordered_params()):
        a, b = pm[1].data().asnumpy(), ps[1].data().asnumpy()
        if not np.allclose(a, b, atol=1e-5):
            raise AssertionError(
                f"spmd/single-device divergence at {pm[0]}: max diff "
                f"{float(np.abs(a - b).max())}")

    # elastic: restore the (dp=4,mp=2) snapshot at (dp=2,mp=2) — full
    # arrays in the blob make the reshape a bit-exact remap
    blob = host_blob(tr_m.states_dict())
    assert blob["mesh_shape"] == "dp=4,mp=2"
    params0 = [p.data().asnumpy() for _, p in net_m._ordered_params()]
    net_e, tr_e, xe, ye = build("dp=2,mp=2")
    for (_, p), w in zip(net_e._ordered_params(), params0):
        p.set_data(mx.nd.array(w))
    tr_e.load_states_dict(blob)
    for st_e, st_m in zip(states(tr_e), states(tr_m)):
        for ea, ma in zip(st_e, st_m):
            if not np.array_equal(ea, ma):
                raise AssertionError("elastic mesh restore not bit-exact")
    tr_e.whole_step(net_e, loss_fn, xe, ye)  # and it steps at dp=2
    nd.waitall()
    assert trainer_mod.trainer_step_stats()["whole_step_fallbacks"] \
        == 0, "resized mesh fell back to the eager path"

    print(f"SPMD_SMOKE_OK steps={STEPS} mesh=dp=4,mp=2 "
          f"post_warmup_compiles={compiles} "
          f"dispatches_per_step={dispatches / STEPS:.2f} "
          f"spmd_steps={stats['spmd_steps']} "
          f"state_bytes_device0={per_dev} (full {full}) "
          f"elastic=dp=2,mp=2 adopted bit-exact "
          f"lr {lr0:.4f}->{trainer.learning_rate:.4f}")


if __name__ == "__main__":
    main()
