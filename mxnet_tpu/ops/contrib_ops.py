"""Contrib / long-tail operators: CTC, detection boxes, ROIAlign, AMP
helpers, misc math.

Ref: src/operator/contrib/ (ctc_loss.cc, roi_align.cc, bounding_box.cc,
amp_cast.cc, allclose_op.cc, index_copy.cc, gradient_multiplier_op.cc,
quadratic_op.cc, fft/), src/operator/nn/moments.cc and optimizer_op.cc
(lamb_update_phase1/2) — each re-emitted as XLA HLO through jnp/lax.
Sequential recurrences (CTC's alpha recursion) ride lax.scan so the
whole loss lowers into one fused XLA while-loop instead of a Python
loop; detection NMS uses a fori_loop greedy mask (compiler-friendly
control flow, no dynamic shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG = -1e30


# ---------------------------------------------------------------------------
# CTC loss (ref: src/operator/contrib/ctc_loss.cc; cuDNN/warp-ctc in the
# reference — here the standard log-space alpha recursion under lax.scan)

def _k_ctc_loss(data, label, data_lengths=None, label_lengths=None, *,
                use_data_lengths=False, use_label_lengths=False,
                blank_label="first"):
    """data (T, N, C) unnormalized activations; label (N, L) padded.

    blank_label='first': blank id 0, labels 1..C-1, padding 0.
    blank_label='last': blank id C-1, labels 0..C-2, padding -1.
    Returns per-example loss (N,).
    """
    T, N, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    lab = label.astype(jnp.int32)
    if blank_label == "first":
        blank = 0
        pad_mask = lab > 0
    else:
        blank = C - 1
        pad_mask = lab >= 0
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32).reshape(N)
    else:
        lab_len = pad_mask.astype(jnp.int32).sum(axis=1)
    if use_data_lengths and data_lengths is not None:
        dat_len = data_lengths.astype(jnp.int32).reshape(N)
    else:
        dat_len = jnp.full((N,), T, jnp.int32)

    # expanded sequence z: (N, S) with S = 2L+1: blank, l1, blank, ...
    S = 2 * L + 1
    z = jnp.full((N, S), blank, jnp.int32)
    safe_lab = jnp.where(pad_mask, lab, blank)
    z = z.at[:, 1::2].set(safe_lab)
    s_idx = jnp.arange(S)[None, :]                      # (1, S)
    s_valid = s_idx < (2 * lab_len + 1)[:, None]        # (N, S)
    # skip-transition allowed where z_s is a label and z_s != z_{s-2}
    z_m2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    can_skip = (z != blank) & (z != z_m2) & (s_idx >= 2)

    batch = jnp.arange(N)

    def emit(t):
        # logp[t, n, z[n, s]] -> (N, S)
        return logp[t][batch[:, None], z]

    alpha0 = jnp.full((N, S), _NEG, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(logp[0][:, blank])
    first_lab = jnp.where(lab_len > 0, z[:, 1], blank)
    alpha0 = alpha0.at[:, 1].set(jnp.where(
        lab_len > 0, logp[0][batch, first_lab], _NEG))
    alpha0 = jnp.where(s_valid, alpha0, _NEG)

    def step(alpha, t):
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                       constant_values=_NEG)[:, :S]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                       constant_values=_NEG)[:, :S]
        stay = jnp.logaddexp(alpha, a_m1)
        merged = jnp.where(can_skip, jnp.logaddexp(stay, a_m2), stay)
        new = merged + emit(t)
        new = jnp.where(s_valid, new, _NEG)
        # past this example's length: carry alpha through unchanged
        alive = (t < dat_len)[:, None]
        new = jnp.where(alive, new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # final: logaddexp of positions 2*len and 2*len-1
    end = 2 * lab_len
    a_end = alpha[batch, end]
    a_end1 = jnp.where(end - 1 >= 0, alpha[batch,
                                           jnp.maximum(end - 1, 0)], _NEG)
    ll = jnp.logaddexp(a_end, a_end1)
    return -ll


register("CTCLoss", _k_ctc_loss,
         arg_names=("data", "label", "data_lengths", "label_lengths"),
         aliases=("ctc_loss", "_contrib_ctc_loss", "_contrib_CTCLoss"),
         doc=_k_ctc_loss.__doc__)


# ---------------------------------------------------------------------------
# Detection boxes (ref: src/operator/contrib/bounding_box.cc)

def _corner(box, fmt):
    if fmt == "center":
        x, y, w, h = (box[..., i] for i in range(4))
        return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                         axis=-1)
    return box


def _pair_iou(a, b):
    """IoU of (..., Na, 4) corner boxes vs (..., Nb, 4) -> (..., Na, Nb)."""
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def _k_box_iou(lhs, rhs, *, format="corner"):
    """Pairwise IoU: lhs (..., N, 4), rhs (..., M, 4) -> (..., N, M)."""
    return _pair_iou(_corner(lhs, format), _corner(rhs, format))


def _to_center(box):
    x1, y1, x2, y2 = (box[..., i] for i in range(4))
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                     axis=-1)


def _k_box_nms(data, *, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
               coord_start=2, score_index=1, id_index=-1,
               background_id=-1, force_suppress=False, in_format="corner",
               out_format="corner"):
    """Greedy NMS (ref bounding_box.cc): data (..., N, K) with score at
    `score_index`, coords at `coord_start:coord_start+4`.  Suppressed or
    invalid entries are wiped to -1 across the whole row (reference
    semantics — consumers filter on any column != -1); surviving rows
    get their coords emitted in `out_format`."""
    orig_shape = data.shape
    flat = data.reshape((-1,) + orig_shape[-2:])   # (B, N, K)
    B, N, K = flat.shape

    def one(batch):
        scores = batch[:, score_index]
        boxes = _corner(batch[:, coord_start:coord_start + 4], in_format)
        ids = batch[:, id_index] if id_index >= 0 else jnp.zeros(N)
        valid = scores > valid_thresh
        if background_id >= 0 and id_index >= 0:
            valid &= ids != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        if topk > 0:
            in_topk = jnp.arange(N) < topk
        else:
            in_topk = jnp.ones(N, bool)
        iou = _pair_iou(boxes[order], boxes[order])
        same_class = (ids[order][:, None] == ids[order][None, :]) \
            if (id_index >= 0 and not force_suppress) \
            else jnp.ones((N, N), bool)
        valid_o = valid[order] & in_topk

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & same_class[i] & \
                (jnp.arange(N) > i) & keep[i] & valid_o[i]
            return keep & ~sup

        keep = lax.fori_loop(0, N, body, valid_o)[jnp.argsort(order)]
        out = batch
        if out_format != in_format:
            coords = _corner(batch[:, coord_start:coord_start + 4],
                             in_format)            # now corner
            if out_format == "center":
                coords = _to_center(coords)
            out = out.at[:, coord_start:coord_start + 4].set(coords)
        return jnp.where(keep[:, None], out, -1.0)

    out = jax.vmap(one)(flat)
    return out.reshape(orig_shape)


def _k_box_decode(data, anchors, *, std0=1.0, std1=1.0, std2=1.0,
                  std3=1.0, clip=-1.0, format="corner"):
    """Decode center-offset deltas against anchors back to corner boxes
    (ref: src/operator/contrib/bounding_box.cc BoxDecode).

    data (B, N, 4) deltas; anchors (1, N, 4) in `format`; output corner
    (B, N, 4). clip > 0 bounds dw/dh exponents."""
    a = _to_center(_corner(anchors, format))
    ax, ay, aw, ah = (a[..., i] for i in range(4))
    dx, dy, dw, dh = (data[..., i] for i in range(4))
    cx = dx * std0 * aw + ax
    cy = dy * std1 * ah + ay
    dw = dw * std2
    dh = dh * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _k_box_encode(samples, matches, anchors, refs, means, stds):
    """Encode matched ground-truth boxes as normalized center-offset
    regression targets (ref: bounding_box.cc BoxEncode).

    samples (B, N) {+1 pos, else ignore}; matches (B, N) ref indices;
    anchors (B, N, 4) corner; refs (B, M, 4) corner; means/stds (4,).
    Returns (targets (B, N, 4), masks (B, N, 4))."""
    m = matches.astype(jnp.int32)
    matched = jnp.take_along_axis(refs, m[..., None].repeat(4, -1),
                                  axis=1)
    a = _to_center(anchors)
    g = _to_center(matched)
    ax, ay, aw, ah = (a[..., i] for i in range(4))
    gx, gy, gw, gh = (g[..., i] for i in range(4))
    t = jnp.stack([(gx - ax) / jnp.maximum(aw, 1e-12),
                   (gy - ay) / jnp.maximum(ah, 1e-12),
                   jnp.log(jnp.maximum(gw, 1e-12)
                           / jnp.maximum(aw, 1e-12)),
                   jnp.log(jnp.maximum(gh, 1e-12)
                           / jnp.maximum(ah, 1e-12))], axis=-1)
    t = (t - means.reshape(1, 1, 4)) / stds.reshape(1, 1, 4)
    mask = (samples > 0.5)[..., None].astype(t.dtype)
    return t * mask, jnp.broadcast_to(mask, t.shape)


def _k_adaptive_avg_pool2d(data, *, output_size=1):
    """NCHW adaptive average pooling (ref: contrib/adaptive_avg_pooling.cc):
    each output cell averages its floor/ceil input region, torch-style."""
    if isinstance(output_size, int):
        oh = ow = int(output_size)
    elif len(output_size) == 1:  # 1-elem shape means square (ref)
        oh = ow = int(output_size[0])
    else:
        oh, ow = (int(v) for v in output_size)
    n, c, h, w = data.shape
    rows = []
    for i in range(oh):
        h0, h1 = (i * h) // oh, -((-(i + 1) * h) // oh)  # floor, ceil
        cols = []
        for j in range(ow):
            w0, w1 = (j * w) // ow, -((-(j + 1) * w) // ow)
            cols.append(jnp.mean(data[:, :, h0:h1, w0:w1], axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


def _k_index_array(data, *, axes=None):
    """Index coordinates of every element: shape data.shape + (len(axes),)
    (ref: contrib/index_array.cc)."""
    shape = data.shape
    sel = tuple(range(len(shape))) if axes is None else \
        tuple(int(a) % len(shape) for a in axes)  # negatives supported
    comps = [jax.lax.broadcasted_iota(jnp.int32, shape, ax) for ax in sel]
    return jnp.stack(comps, axis=-1)


register("_contrib_box_decode", _k_box_decode,
         arg_names=("data", "anchors"), aliases=("box_decode",),
         nondiff=True, doc=_k_box_decode.__doc__)
register("_contrib_box_encode", _k_box_encode,
         arg_names=("samples", "matches", "anchors", "refs", "means",
                    "stds"),
         num_outputs=2, nondiff=True, doc=_k_box_encode.__doc__)
register("_contrib_AdaptiveAvgPooling2D", _k_adaptive_avg_pool2d,
         arg_names=("data",), aliases=("adaptive_avg_pool2d",),
         doc=_k_adaptive_avg_pool2d.__doc__)
register("_contrib_index_array", _k_index_array, arg_names=("data",),
         aliases=("index_array",), nondiff=True,
         doc=_k_index_array.__doc__)

register("_contrib_box_iou", _k_box_iou, arg_names=("lhs", "rhs"),
         aliases=("box_iou",), nondiff=True, doc=_k_box_iou.__doc__)
register("_contrib_box_nms", _k_box_nms, arg_names=("data",),
         aliases=("box_nms", "_contrib_box_non_maximum_suppression"),
         nondiff=True, doc=_k_box_nms.__doc__)


# ---------------------------------------------------------------------------
# ROIAlign (ref: src/operator/contrib/roi_align.cc)

def _k_roi_align(data, rois, *, pooled_size, spatial_scale=1.0,
                 sample_ratio=-1, position_sensitive=False,
                 aligned=False):
    """data (B, C, H, W), rois (R, 5) [batch_idx, x1, y1, x2, y2] in
    image coords; bilinear average pooling per cell (no quantization —
    the Mask-RCNN fix the reference implements).

    sample_ratio<=0 (the reference's adaptive mode — taps scale with
    the roi size) is approximated with a fixed 2x2 tap grid: per-roi
    tap counts are data-dependent shapes, which XLA cannot compile.

    position_sensitive=True (PSROIAlign, ref roi_align.cc v1.5 + the
    R-FCN papers): input channels C = out_channels*ph*pw, and output
    channel c at cell (iy, ix) pools input channel
    (c*ph + iy)*pw + ix — computed here by pooling every channel with
    the plain ROIAlign grid and then gathering the cell-diagonal."""
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    B, C, H, W = data.shape
    if position_sensitive and C % (ph * pw):
        raise ValueError(
            f"ROIAlign position_sensitive: channels {C} must be a "
            f"multiple of pooled_h*pooled_w = {ph * pw}")
    sr = int(sample_ratio) if int(sample_ratio) > 0 else 2
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale - offset, \
            roi[2] * spatial_scale - offset, \
            roi[3] * spatial_scale - offset, \
            roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bw, bh = rw / pw, rh / ph
        # sample grid: (ph, sr) x (pw, sr) bilinear taps
        ys = y1 + (jnp.arange(ph)[:, None] +
                   (jnp.arange(sr)[None, :] + 0.5) / sr) * bh
        xs = x1 + (jnp.arange(pw)[:, None] +
                   (jnp.arange(sr)[None, :] + 0.5) / sr) * bw
        ys = ys.reshape(-1)  # (ph*sr,)
        xs = xs.reshape(-1)  # (pw*sr,)
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1)
        y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        wy1 = jnp.clip(ys - y0, 0.0, 1.0)
        wx1 = jnp.clip(xs - x0, 0.0, 1.0)
        img = data[bidx]                                   # (C, H, W)
        if position_sensitive:
            # pool ONLY each cell's own channel (d*ph + iy)*pw + ix:
            # corner gathers are indexed per (cell, tap) so no work is
            # spent pooling channels the cell-diagonal would discard
            D = C // (ph * pw)
            imgr = img.reshape(D, ph, pw, H, W)
            yb = y0i.reshape(ph, sr)
            yt = y1i.reshape(ph, sr)
            xb = x0i.reshape(pw, sr)
            xt = x1i.reshape(pw, sr)
            wy = wy1.reshape(ph, sr)
            wx = wx1.reshape(pw, sr)
            cy = jnp.arange(ph)[:, None, None, None]   # cell row
            cx = jnp.arange(pw)[None, :, None, None]   # cell col
            sy = jnp.arange(sr)[None, None, :, None]   # tap row
            sx = jnp.arange(sr)[None, None, None, :]   # tap col
            wyc = wy[cy, sy]
            wxc = wx[cx, sx]
            g = (imgr[:, cy, cx, yb[cy, sy], xb[cx, sx]]
                 * (1 - wyc) * (1 - wxc)
                 + imgr[:, cy, cx, yb[cy, sy], xt[cx, sx]]
                 * (1 - wyc) * wxc
                 + imgr[:, cy, cx, yt[cy, sy], xb[cx, sx]]
                 * wyc * (1 - wxc)
                 + imgr[:, cy, cx, yt[cy, sy], xt[cx, sx]]
                 * wyc * wxc)
            return g.mean(axis=(3, 4))                 # (D, ph, pw)
        # gather 4 corners: (C, ph*sr, pw*sr)
        g = (img[:, y0i[:, None], x0i[None, :]] *
             ((1 - wy1)[:, None] * (1 - wx1)[None, :]) +
             img[:, y0i[:, None], x1i[None, :]] *
             ((1 - wy1)[:, None] * wx1[None, :]) +
             img[:, y1i[:, None], x0i[None, :]] *
             (wy1[:, None] * (1 - wx1)[None, :]) +
             img[:, y1i[:, None], x1i[None, :]] *
             (wy1[:, None] * wx1[None, :]))
        g = g.reshape(C, ph, sr, pw, sr)
        return g.mean(axis=(2, 4))                         # (C, ph, pw)

    return jax.vmap(one_roi)(rois.astype(jnp.float32))


register("_contrib_ROIAlign", _k_roi_align, arg_names=("data", "rois"),
         aliases=("ROIAlign",), doc=_k_roi_align.__doc__)


# ---------------------------------------------------------------------------
# AMP helpers (ref: src/operator/tensor/amp_cast.cc, all_finite.cc)

def _k_amp_cast(data, *, dtype="float16"):
    return data.astype(jnp.dtype(dtype))


def _k_amp_multicast(*arrays, num_outputs=0, cast_narrow=False):
    """Cast all inputs to a common dtype: widest by default, narrowest
    with cast_narrow (ref amp_multicast)."""
    arrays = [a for a in arrays if a is not None]
    widths = [jnp.dtype(a.dtype).itemsize for a in arrays]
    pick = min(range(len(arrays)),
               key=lambda i: widths[i]) if cast_narrow else \
        max(range(len(arrays)), key=lambda i: widths[i])
    target = arrays[pick].dtype
    return tuple(a.astype(target) for a in arrays)


def _k_all_finite(data, *, init_output=True):
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


def _k_multi_all_finite(*arrays, num_arrays=0, init_output=True):
    ok = jnp.asarray(True)
    for a in arrays:
        if a is not None:
            ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.astype(jnp.float32).reshape(1)


register("amp_cast", _k_amp_cast, arg_names=("data",))
register("amp_multicast", _k_amp_multicast, arg_names=(), variadic=True,
         num_outputs=-1, doc=_k_amp_multicast.__doc__)
register("all_finite", _k_all_finite, arg_names=("data",), nondiff=True)
register("multi_all_finite", _k_multi_all_finite, arg_names=(),
         variadic=True, nondiff=True)


# ---------------------------------------------------------------------------
# Misc math / indexing (ref: moments.cc, allclose_op.cc, index_copy.cc,
# quadratic_op.cc, gradient_multiplier_op.cc, fft/)

def _k_moments(data, *, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    mean = data.mean(axis=ax, keepdims=bool(keepdims))
    var = ((data - data.mean(axis=ax, keepdims=True)) ** 2).mean(
        axis=ax, keepdims=bool(keepdims))
    return mean, var


def _k_isfinite(data):
    return jnp.isfinite(data).astype(jnp.float32)


def _k_softmax_cross_entropy(data, label):
    """Total cross entropy over the batch, shape (1,) (ref
    softmax_cross_entropy.cc)."""
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    n = data.shape[0]
    picked = logp[jnp.arange(n), label.astype(jnp.int32)]
    return -picked.sum().reshape(1)


def _k_allclose(a, b, *, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).astype(jnp.float32).reshape(1)


def _k_index_copy(old_tensor, index_vector, new_tensor):
    return old_tensor.at[index_vector.astype(jnp.int32)].set(new_tensor)


def _k_index_add(old_tensor, index_vector, new_tensor):
    return old_tensor.at[index_vector.astype(jnp.int32)].add(new_tensor)


def _k_arange_like(data, *, start=0.0, step=1.0, repeat=1, ctx=None,
                   axis=None):
    if axis is None:
        n = 1
        for d in data.shape:
            n *= d
        out = start + step * (jnp.arange(n) // max(int(repeat), 1))
        return out.reshape(data.shape).astype(data.dtype)
    n = data.shape[axis]
    return (start + step *
            (jnp.arange(n) // max(int(repeat), 1))).astype(data.dtype)


def _k_quadratic(data, *, a=0.0, b=0.0, c=0.0):
    return a * data * data + b * data + c


@jax.custom_vjp
def _gradmult(data, scalar):
    return data


def _gradmult_fwd(data, scalar):
    return data, scalar


def _gradmult_bwd(scalar, g):
    return g * scalar, None


_gradmult.defvjp(_gradmult_fwd, _gradmult_bwd)


def _k_gradientmultiplier(data, *, scalar=1.0):
    """Identity forward; gradient scaled by `scalar` (ref
    gradient_multiplier_op.cc — the GRL trick uses scalar<0)."""
    return _gradmult(data, jnp.asarray(scalar, jnp.float32))


def _k_fft(data, *, compute_size=128):
    """(N, d) real -> (N, 2d) interleaved re/im (ref contrib/fft)."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],))


def _k_ifft(data, *, compute_size=128):
    d = data.shape[-1] // 2
    pair = data.reshape(data.shape[:-1] + (d, 2))
    comp = pair[..., 0] + 1j * pair[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * d


register("moments", _k_moments, arg_names=("data",), num_outputs=2,
         doc=_k_moments.__doc__)
register("isfinite", _k_isfinite, arg_names=("data",), nondiff=True)
register("softmax_cross_entropy", _k_softmax_cross_entropy,
         arg_names=("data", "label"),
         doc=_k_softmax_cross_entropy.__doc__)
register("_contrib_allclose", _k_allclose, arg_names=("a", "b"),
         aliases=("allclose",), nondiff=True)
register("_contrib_index_copy", _k_index_copy,
         arg_names=("old_tensor", "index_vector", "new_tensor"),
         aliases=("index_copy",))
register("_contrib_index_add", _k_index_add,
         arg_names=("old_tensor", "index_vector", "new_tensor"),
         aliases=("index_add",))
register("_contrib_arange_like", _k_arange_like, arg_names=("data",),
         aliases=("arange_like",), nondiff=True)
register("_contrib_quadratic", _k_quadratic, arg_names=("data",),
         aliases=("quadratic",))
register("_contrib_gradientmultiplier", _k_gradientmultiplier,
         arg_names=("data",), aliases=("gradientmultiplier",),
         jit_compile=False, doc=_k_gradientmultiplier.__doc__)
register("_contrib_fft", _k_fft, arg_names=("data",), aliases=("fft",),
         nondiff=True, doc=_k_fft.__doc__)
register("_contrib_ifft", _k_ifft, arg_names=("data",), aliases=("ifft",),
         nondiff=True)


# ---------------------------------------------------------------------------
# Sampling / shuffle (ref: sample_multinomial_op.cc, shuffle_op.cc)

def _k_sample_multinomial(data, key=None, *, shape=(), get_prob=False,
                          dtype="int32"):
    """Draw from batched categoricals: data (..., C) probabilities."""
    n = 1
    shp = (shape,) if isinstance(shape, int) else tuple(shape)
    for d in shp:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-30))
    draws = jax.random.categorical(key, logits, axis=-1,
                                   shape=(max(n, 1),) + data.shape[:-1])
    draws = jnp.moveaxis(draws, 0, -1)
    out_shape = data.shape[:-1] + shp
    draws = draws.reshape(out_shape if shp else data.shape[:-1])
    samples = draws.astype(jnp.dtype(dtype))
    if get_prob:
        logp = jnp.log(jnp.maximum(data, 1e-30))
        picked = jnp.take_along_axis(
            logp, draws.reshape(data.shape[:-1] + (-1,)).astype(jnp.int32),
            axis=-1).reshape(samples.shape)
        return samples, picked
    return samples


def _k_shuffle(data, key=None):
    """Shuffle along the first axis (ref shuffle_op.cc)."""
    return jax.random.permutation(key, data, axis=0)


# differentiable: with get_prob=True the log-likelihood output carries
# gradient back to the probabilities (REINFORCE; ref
# sample_multinomial_op.cc registers a backward for the prob output)
register("sample_multinomial", _k_sample_multinomial, arg_names=("data",),
         needs_rng=True, doc=_k_sample_multinomial.__doc__)
register("_shuffle", _k_shuffle, arg_names=("data",), needs_rng=True,
         nondiff=True, aliases=("shuffle",))


# ---------------------------------------------------------------------------
# LAMB phase ops (ref: optimizer_op.cc lamb_update_phase1/2 — the
# layerwise-adaptive pieces BERT-large training uses)

def _k_lamb_update_phase1(weight, grad, mean, var, *, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, wd=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    return m / (jnp.sqrt(v) + epsilon) + wd * weight, new_mean, new_var


def _k_lamb_update_phase2(weight, g, r1, r2, *, lr=0.01,
                          lower_bound=-1.0, upper_bound=-1.0):
    r1 = r1.reshape(())
    r2 = r2.reshape(())
    if lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


register("lamb_update_phase1", _k_lamb_update_phase1,
         arg_names=("weight", "grad", "mean", "var"), num_outputs=3,
         nondiff=True, mutate_aux=((2, 1), (3, 2)),
         doc=_k_lamb_update_phase1.__doc__)
register("lamb_update_phase2", _k_lamb_update_phase2,
         arg_names=("weight", "g", "r1", "r2"), nondiff=True,
         doc=_k_lamb_update_phase2.__doc__)


# ---------------------------------------------------------------------------
# SSD MultiBox family (ref: src/operator/contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc — the detection-era anchor
# pipeline).  All three are static-shape HLO: anchor generation is pure
# arithmetic, target matching is a vectorized argmax bipartite pass, and
# detection decodes + reuses the greedy fori_loop NMS.

def _k_multibox_prior(data, *, sizes=(1.0,), ratios=(1.0,), steps=(-1.0, -1.0),
                      offsets=(0.5, 0.5), clip=False):
    """Anchor boxes per feature-map cell: data (B, C, H, W) ->
    (1, H*W*(S+R-1), 4) corner boxes in [0,1] coords.

    Reference order (multibox_prior.h): every size at ratios[0] first,
    then sizes[0] with each remaining ratio; widths carry the in_h/in_w
    aspect correction so anchors are square in pixels on non-square
    feature maps."""
    H, W = data.shape[2], data.shape[3]
    if isinstance(sizes, (int, float)):
        sizes = (float(sizes),)
    if isinstance(ratios, (int, float)):
        ratios = (float(ratios),)
    sizes, ratios = tuple(sizes), tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    aspect = H / W
    ws, hs = [], []
    for s in sizes:                       # sizes first, at ratios[0]
        sr = ratios[0] ** 0.5
        ws.append(s * sr * aspect)
        hs.append(s / sr)
    for r in ratios[1:]:                  # then ratios[1:], at sizes[0]
        sr = r ** 0.5
        ws.append(sizes[0] * sr * aspect)
        hs.append(sizes[0] / sr)
    ws = jnp.asarray(ws, jnp.float32)[None, None, :]
    hs = jnp.asarray(hs, jnp.float32)[None, None, :]
    cy_g = cy[:, None, None]
    cx_g = cx[None, :, None]
    x1 = cx_g - ws / 2
    y1 = cy_g - hs / 2
    x2 = cx_g + ws / 2
    y2 = cy_g + hs / 2
    out = jnp.stack(jnp.broadcast_arrays(x1, y1, x2, y2), axis=-1)
    out = out.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _k_multibox_target(anchor, label, cls_pred, *, overlap_threshold=0.5,
                       ignore_label=-1.0, negative_mining_ratio=-1.0,
                       negative_mining_thresh=0.5, minimum_negative_samples=0,
                       variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to ground truth (ref multibox_target.cc).

    anchor (1, N, 4) corners; label (B, M, 5) [cls, x1, y1, x2, y2] with
    cls=-1 padding; cls_pred (B, num_cls+1, N) feeds hard negative
    mining: when negative_mining_ratio > 0, unmatched anchors below
    negative_mining_thresh IoU are ranked by background-class prediction
    loss and only the top ratio*num_pos (>= minimum_negative_samples)
    are labelled background — the rest get ignore_label (ref
    multibox_target.cc mining; rank-vs-traced-scalar keeps shapes
    static).  Returns (box_target (B, N*4), box_mask (B, N*4),
    cls_target (B, N) — 0 background, 1+cls matched, ignore_label
    unmined).
    """
    anc = anchor[0]                                     # (N, 4)
    N = anc.shape[0]

    def one(lab, cpred):
        gt_valid = lab[:, 0] >= 0                       # (M,)
        gt_boxes = lab[:, 1:5]
        iou = _pair_iou(anc, gt_boxes)                  # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)               # (N,)
        best_iou = jnp.max(iou, axis=1)
        # bipartite stage: each gt claims its best anchor
        best_anchor_per_gt = jnp.argmax(iou, axis=0)    # (M,)
        # .max, not .set: a padding gt (valid=False) scattering onto the
        # same anchor as a real gt must not clobber the real claim
        claimed = jnp.zeros(N, bool).at[best_anchor_per_gt].max(
            gt_valid, mode="drop")
        matched = claimed | (best_iou >= overlap_threshold)
        m_gt = best_gt
        gt = gt_boxes[m_gt]                             # (N, 4)
        # encode center-offset targets with variances
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
        gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
        gcx = (gt[:, 0] + gt[:, 2]) / 2
        gcy = (gt[:, 1] + gt[:, 3]) / 2
        tx = (gcx - acx) / aw / variances[0]
        ty = (gcy - acy) / ah / variances[1]
        tw = jnp.log(gw / aw) / variances[2]
        th = jnp.log(gh / ah) / variances[3]
        t = jnp.stack([tx, ty, tw, th], axis=-1)        # (N, 4)
        mask = matched[:, None].astype(jnp.float32) * jnp.ones((1, 4))
        cls_t = jnp.where(matched, lab[m_gt, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining: rank unmatched low-IoU anchors by
            # background prediction loss, keep the hardest k
            logp = jax.nn.log_softmax(cpred.astype(jnp.float32), axis=0)
            neg_loss = -logp[0]                          # bg is class 0
            cand = (~matched) & (best_iou < negative_mining_thresh)
            num_pos = matched.astype(jnp.float32).sum()
            k = jnp.maximum(negative_mining_ratio * num_pos,
                            float(minimum_negative_samples))
            ranked = jnp.argsort(
                jnp.where(cand, neg_loss, -jnp.inf))[::-1]
            rank = jnp.zeros(N).at[ranked].set(
                jnp.arange(N, dtype=jnp.float32))
            kept_neg = cand & (rank < k)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(kept_neg, 0.0,
                                        float(ignore_label)))
        return (t * mask).reshape(-1), mask.reshape(-1), cls_t

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


def _k_multibox_detection(cls_prob, loc_pred, anchor, *, clip=True,
                          threshold=0.01, background_id=0,
                          nms_threshold=0.5, force_suppress=False,
                          variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions to detections (ref multibox_detection.cc):
    cls_prob (B, num_cls+1, N), loc_pred (B, N*4), anchor (1, N, 4) ->
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], -1 rows invalid."""
    anc = anchor[0]
    N = anc.shape[0]

    def one(probs, loc):
        loc = loc.reshape(N, 4)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2,
                           cx + w / 2, cy + h / 2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.concatenate([probs[:background_id],
                              probs[background_id + 1:]], axis=0)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        rows = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[:, None],
             jnp.where(keep, score, -1.0)[:, None], boxes], axis=-1)
        return rows

    rows = jax.vmap(one)(cls_prob, loc_pred)            # (B, N, 6)
    return _k_box_nms(rows, overlap_thresh=nms_threshold,
                      valid_thresh=threshold, topk=nms_topk,
                      coord_start=2, score_index=1, id_index=0,
                      force_suppress=force_suppress)


register("_contrib_MultiBoxPrior", _k_multibox_prior, arg_names=("data",),
         aliases=("MultiBoxPrior",), nondiff=True,
         doc=_k_multibox_prior.__doc__)
register("_contrib_MultiBoxTarget", _k_multibox_target,
         arg_names=("anchor", "label", "cls_pred"), num_outputs=3,
         nondiff=True, doc=_k_multibox_target.__doc__)
register("_contrib_MultiBoxDetection", _k_multibox_detection,
         arg_names=("cls_prob", "loc_pred", "anchor"), nondiff=True,
         doc=_k_multibox_detection.__doc__)


# ---------------------------------------------------------------------------
# MoE feed-forward as a registered op so gluon blocks can use expert
# layers (the sharded-EP path lives in parallel/moe.py; this op is the
# same math with mesh=None — under a DataParallelTrainer the 'ep'
# constraint is applied by sharding the expert-stacked params)

def _k_moe_ffn(data, router_w, w1, b1, w2, b2, *, capacity_factor=1.25,
               top_k=1):
    """MoE FFN, top-1 (Switch) or top-2 (GShard) routing: data (S, M)
    -> (y (S, M), aux (1,)).  See parallel/moe.py for the GShard einsum
    formulation and EP sharding."""
    from ..parallel.moe import moe_ffn

    y, aux = moe_ffn(data, router_w, w1, b1, w2, b2, mesh=None,
                     capacity_factor=capacity_factor, top_k=int(top_k))
    return y, aux.reshape(1)


register("_contrib_MoEFFN", _k_moe_ffn,
         arg_names=("data", "router_w", "w1", "b1", "w2", "b2"),
         num_outputs=2, doc=_k_moe_ffn.__doc__)


def _getnnz_wrapper(data, axis=None, out=None, **kwargs):
    """Custom wrapper: getnnz consumes SPARSE NDArrays, which bypass
    the dense jit dispatch (the reference's FComputeEx path).  Handles
    the standard nd-op conveniences itself: string attrs normalize and
    out= receives the result."""
    from ..ndarray.ops import _norm_attr
    from ..ndarray import sparse as _sparse

    res = _sparse.getnnz(data, axis=_norm_attr(axis))
    if out is not None:
        out._data = res._data
        return out
    return res


register("_contrib_getnnz", _getnnz_wrapper, arg_names=("data",),
         wrapper=_getnnz_wrapper, aliases=("getnnz",), nondiff=True,
         doc="Stored-value count of a sparse array (csr: axis "
             "None/0/1; row_sparse: None). Ref contrib/nnz.cc.")


def _edge_id_wrapper(data, u, v, **kwargs):
    """Custom wrapper (sparse input bypasses dense jit dispatch)."""
    from ..ndarray import sparse as _sparse

    return _sparse.edge_id(data, u, v)


register("_contrib_edge_id", _edge_id_wrapper,
         arg_names=("data", "u", "v"), wrapper=_edge_id_wrapper,
         aliases=("edge_id",), nondiff=True,
         doc="Edge weights of (u,v) pairs in a CSR adjacency matrix; "
             "-1 where no edge. Ref contrib/dgl_graph.cc.")


def _k_sync_batch_norm(data, gamma, beta, moving_mean, moving_var, *,
                       eps=1e-3, momentum=0.9, fix_gamma=True,
                       use_global_stats=False, output_mean_var=False,
                       ndev=1, key=None, axis_name=None, _train=False):
    """Cross-replica BatchNorm (ref: src/operator/contrib/sync_batch_norm
    .cc — per-device stats reduced across the kvstore key ``key`` over
    ``ndev`` devices).

    TPU-native semantics: batch statistics are global over however the
    batch is distributed.  Two regimes cover every parallel path here:

    - GSPMD (DataParallelTrainer): the batch axis is *sharded*, not
      replicated, so the fp32 stats reductions already produce the
      global mean/var — XLA inserts the cross-chip collective.  ``ndev``
      and ``key`` are accepted for API parity and not needed.
    - shard_map/pmap with a named axis (``axis_name=...``): the local
      (mean, E[x^2]) pair is ``lax.pmean``-ed over the axis — the
      explicit analogue of the reference's engine-level reduce.

    The math is _k_batch_norm's (ops/nn.py) with the reference's fixed
    channel axis 1; only the axis_name plumbing differs.
    """
    from .nn import _k_batch_norm

    return _k_batch_norm(data, gamma, beta, moving_mean, moving_var,
                         eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                         use_global_stats=use_global_stats, axis=1,
                         axis_name=axis_name, _train=_train)


register("_contrib_SyncBatchNorm", _k_sync_batch_norm,
         arg_names=("data", "gamma", "beta", "moving_mean", "moving_var"),
         aliases=("SyncBatchNorm",), train_aware=True, num_outputs=3,
         mutate_aux=((3, 1), (4, 2)),
         doc="BatchNorm with cross-replica statistics. Under GSPMD the "
             "sharded-batch reduction is already global; under shard_map "
             "pass axis_name= to pmean the stats. Ref "
             "contrib/sync_batch_norm.cc.")


# ---------------------------------------------------------------------------
# DeformableConvolution (ref: src/operator/contrib/deformable_convolution
# .cc + nn/deformable_im2col.h — Dai et al., Deformable ConvNets).
# The reference builds a deformable im2col buffer with a custom CUDA
# kernel, then GEMMs.  Here the same decomposition targets the MXU:
# vectorized bilinear gathers build the sampled (N,C,kh,kw,Ho,Wo)
# tensor in one fused XLA computation, and the contraction with the
# weight is a single einsum (one MXU matmul per group).  Autodiff
# reproduces the reference's analytic data/offset gradients (the
# bilinear weights are differentiable in the offsets).

def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v), int(v))


def _k_deformable_convolution(data, offset, weight, bias=None, *,
                              kernel, num_filter, stride=(1, 1),
                              dilate=(1, 1), pad=(0, 0), num_group=1,
                              num_deformable_group=1, no_bias=False,
                              workspace=1024, layout="NCHW"):
    """data (N,C,H,W); offset (N, 2*dg*kh*kw, Ho, Wo) with per-group
    channel order (i*kw+j)*2 + {0:dy, 1:dx} (the deformable_im2col
    layout); weight (O, C/num_group, kh, kw)."""
    if layout != "NCHW":
        raise NotImplementedError("DeformableConvolution: NCHW only")
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilate)
    ph_, pw_ = _pair(pad)
    N, C, H, W = data.shape
    Ho = (H + 2 * ph_ - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw_ - (dw * (kw - 1) + 1)) // sw + 1
    dg = int(num_deformable_group)
    G = int(num_group)
    # loud shape checks (the reference prop's InferShape): silent
    # clamped gathers would otherwise return plausible garbage
    if C % dg or C % G or int(num_filter) % G:
        raise ValueError(
            f"DeformableConvolution: channels {C} must divide by "
            f"num_deformable_group {dg} and num_group {G}; num_filter "
            f"{num_filter} must divide by num_group")
    if offset.shape != (N, 2 * dg * kh * kw, Ho, Wo):
        raise ValueError(
            f"DeformableConvolution: offset shape {offset.shape} != "
            f"expected {(N, 2 * dg * kh * kw, Ho, Wo)}")
    if weight.shape != (int(num_filter), C // G, kh, kw):
        raise ValueError(
            f"DeformableConvolution: weight shape {weight.shape} != "
            f"expected {(int(num_filter), C // G, kh, kw)}")
    Cg = C // dg

    off = offset.reshape(N, dg, kh, kw, 2, Ho, Wo).astype(jnp.float32)
    # sampling positions: h = ho*sh - pad + i*dil + dy (dmcn_im2col)
    base_y = (jnp.arange(Ho) * sh - ph_).astype(jnp.float32)
    base_x = (jnp.arange(Wo) * sw - pw_).astype(jnp.float32)
    tap_y = (jnp.arange(kh) * dh).astype(jnp.float32)
    tap_x = (jnp.arange(kw) * dw).astype(jnp.float32)
    # (N, dg, kh, kw, Ho, Wo)
    yy = (base_y[None, None, None, None, :, None]
          + tap_y[None, None, :, None, None, None] + off[..., 0, :, :])
    xx = (base_x[None, None, None, None, None, :]
          + tap_x[None, None, None, :, None, None] + off[..., 1, :, :])

    dat = data.reshape(N, dg, Cg, H, W)

    def sample_one(img, y, x):
        # img (Cg, H, W); y/x (kh, kw, Ho, Wo); zero-padding semantics:
        # out-of-range corners contribute nothing (dmcn_im2col_bilinear)
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy1 = y - y0
        wx1 = x - x0
        out = jnp.zeros((img.shape[0],) + y.shape, img.dtype)
        for cy, wyc in ((y0, 1.0 - wy1), (y0 + 1.0, wy1)):
            for cx, wxc in ((x0, 1.0 - wx1), (x0 + 1.0, wx1)):
                ok = ((cy >= 0) & (cy <= H - 1)
                      & (cx >= 0) & (cx <= W - 1))
                yi = jnp.clip(cy, 0, H - 1).astype(jnp.int32)
                xi = jnp.clip(cx, 0, W - 1).astype(jnp.int32)
                v = img[:, yi, xi]  # (Cg, kh, kw, Ho, Wo)
                out = out + v * (wyc * wxc * ok)[None]
        return out

    # vmap over batch then deformable group
    sampled = jax.vmap(jax.vmap(sample_one))(dat, yy, xx)
    # (N, dg, Cg, kh, kw, Ho, Wo) -> (N, G, C/G, kh, kw, Ho, Wo)
    sampled = sampled.reshape(N, G, C // G, kh, kw, Ho, Wo)
    wg = weight.reshape(G, num_filter // G, C // G, kh, kw)
    out = jnp.einsum("ngcijhw,gocij->ngohw", sampled,
                     wg.astype(sampled.dtype))
    out = out.reshape(N, num_filter, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


register("_contrib_DeformableConvolution", _k_deformable_convolution,
         arg_names=("data", "offset", "weight", "bias"),
         aliases=("DeformableConvolution",),
         doc=_k_deformable_convolution.__doc__)


# ---------------------------------------------------------------------------
# PSROIPooling (ref: src/operator/contrib/psroi_pooling.cc — R-FCN's
# position-sensitive ROI pooling).  The reference loops h,w per output
# cell with dynamic bin bounds; XLA needs static shapes, so each bin
# average is a masked full-plane reduction — one einsum over (H, W)
# with per-cell interval masks, then a position-sensitive channel
# gather.  O(H*W) per cell is the static-shape price; feature maps at
# this stage are small (R-FCN: 7x7 bins over ~63x38).

def _k_psroipooling(data, rois, *, spatial_scale, output_dim,
                    pooled_size, group_size=0):
    """data (N, C, H, W) with C == output_dim*group_size^2; rois (R, 5)
    [batch_idx, x1, y1, x2, y2] image coords.  Returns
    (R, output_dim, pooled_size, pooled_size)."""
    P = int(pooled_size)
    G = int(group_size) or P
    D = int(output_dim)
    N, C, H, W = data.shape
    if C != D * G * G:
        # loud check (the reference prop's InferShape): a clamped
        # channel gather would otherwise return plausible garbage
        raise ValueError(
            f"PSROIPooling: data channels {C} != "
            f"output_dim*group_size^2 = {D}*{G}^2 = {D * G * G}")
    scale = float(spatial_scale)

    phs = jnp.arange(P, dtype=jnp.float32)
    gh = jnp.clip(jnp.floor(phs * G / P), 0, G - 1).astype(jnp.int32)
    chan = ((jnp.arange(D, dtype=jnp.int32)[:, None, None] * G
             + gh[None, :, None]) * G + gh[None, None, :])  # (D, P, P)

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        start_w = jnp.round(roi[1]) * scale
        start_h = jnp.round(roi[2]) * scale
        end_w = (jnp.round(roi[3]) + 1.0) * scale
        end_h = (jnp.round(roi[4]) + 1.0) * scale
        rw = jnp.maximum(end_w - start_w, 0.1)
        rh = jnp.maximum(end_h - start_h, 0.1)
        bin_h, bin_w = rh / P, rw / P

        def _snap(v):
            # XLA may rewrite /P into *(1/P) under jit, perturbing a
            # bin edge that lands exactly on an integer by 1 ulp — and
            # floor/ceil then shift the bin a whole pixel vs eager.
            # Snap near-integer edges first so both paths agree.
            r = jnp.round(v)
            tol = 1e-4 * jnp.maximum(1.0, jnp.abs(v))
            return jnp.where(jnp.abs(v - r) < tol, r, v)

        hstart = jnp.clip(jnp.floor(_snap(phs * bin_h + start_h)), 0, H)
        hend = jnp.clip(
            jnp.ceil(_snap((phs + 1) * bin_h + start_h)), 0, H)
        wstart = jnp.clip(jnp.floor(_snap(phs * bin_w + start_w)), 0, W)
        wend = jnp.clip(
            jnp.ceil(_snap((phs + 1) * bin_w + start_w)), 0, W)
        hmask = ((jnp.arange(H)[None, :] >= hstart[:, None])
                 & (jnp.arange(H)[None, :] < hend[:, None])
                 ).astype(data.dtype)  # (P, H)
        wmask = ((jnp.arange(W)[None, :] >= wstart[:, None])
                 & (jnp.arange(W)[None, :] < wend[:, None])
                 ).astype(data.dtype)  # (P, W)
        sums = jnp.einsum("chw,ph,qw->cpq", data[bidx], hmask, wmask)
        picked = sums[chan,
                      jnp.arange(P)[None, :, None],
                      jnp.arange(P)[None, None, :]]  # (D, P, P)
        area = ((hend - hstart)[:, None] * (wend - wstart)[None, :])
        return jnp.where(area > 0, picked / jnp.maximum(area, 1.0), 0.0)

    return jax.vmap(one)(rois.astype(jnp.float32))


register("_contrib_PSROIPooling", _k_psroipooling,
         arg_names=("data", "rois"), aliases=("PSROIPooling",),
         doc=_k_psroipooling.__doc__)


# ---------------------------------------------------------------------------
# count_sketch (ref: src/operator/contrib/count_sketch.cc — compact
# bilinear pooling's random projection).  The reference scatter-adds
# with a CUDA kernel; XLA's scatter-add (.at[].add) is the native
# equivalent and its VJP is exactly the reference's backward
# (grad_data = s * grad_out[:, h]).

def _k_count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """data (n, in_dim); h (1, in_dim) hash bucket per input feature in
    [0, out_dim); s (1, in_dim) signs (+-1).  Returns (n, out_dim):
    out[i, h[j]] += s[j] * data[i, j].  processing_batch_size is
    accepted for parity (the reference tiles the batch; XLA fuses)."""
    n, d = data.shape
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    return jnp.zeros((n, int(out_dim)), data.dtype).at[:, hh].add(
        data * ss[None, :])


register("_contrib_count_sketch", _k_count_sketch,
         arg_names=("data", "h", "s"), aliases=("count_sketch",),
         doc=_k_count_sketch.__doc__)
