"""`make trace-smoke`: observability gate (docs/observability.md).

One traced run covering all five subsystems with the metrics endpoint
up, asserting:

1. a supervised train loop (pipeline-fed, checkpointing every step)
   plus a serve burst emit spans from trainer / dataPipeline / serve /
   checkpoint / resilience into one exported trace;
2. the exported file is valid Chrome trace-event JSON: every event
   carries the Perfetto-required fields, async request spans have
   balanced b/e per id, and pids are consistent;
3. a fault-plan-injected stall (delay at `train.step` longer than the
   watchdog window) fires the progress watchdog, the supervisor
   recovers, and the flight recorder leaves a loadable
   `flight-<rank>-<ts>.json` post-mortem with reason "watchdog";
4. one `/metrics` scrape parses as Prometheus text and agrees with
   `profiler.dumps()`; `/healthz` answers;
5. disarmed, every telemetry hook IS the module no-op and a hot loop
   shows zero measurable overhead (the fault-point contract).

Runs on the CPU backend so the gate is deterministic and fast anywhere.
"""
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import (autograd, checkpoint, gluon, pipeline,  # noqa: E402
                       profiler, resilience, serve, telemetry)
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.telemetry import tracer  # noqa: E402

FEAT, BS, N = 4, 4, 24
WATCHDOG_SEC = 1.0
STALL_SEC = 2.5


def build_model(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=FEAT, activation="relu"),
            nn.Dense(1, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    # dist_sync + local update keeps kvstore.pushpull (and so the
    # allreduce span) on the step path in one process
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            kvstore="dist_sync", update_on_kvstore=False)
    return net, trainer


def supervised_train(ckdir):
    """Pipeline-fed supervised loop; the armed fault plan stalls one
    `train.step` past the watchdog window, so the run exercises
    watchdog fire -> flight dump -> restart -> resume."""
    rng = np.random.RandomState(0)
    data = [(rng.rand(FEAT).astype(np.float32), np.float32(i % 2))
            for i in range(N)]
    mgr = checkpoint.CheckpointManager(ckdir, keep_n=3)
    sup = resilience.Supervisor(mgr, on_preemption="resume",
                                max_restarts=3,
                                watchdog_sec=WATCHDOG_SEC)

    def train(ctx):
        net, trainer = build_model()
        pipe = (pipeline.Pipeline(data)
                .map(lambda s: (s[0] * 1.0, s[1]))
                .shuffle(8, seed=5)
                .batch(BS, last_batch="discard"))
        start = 0
        if ctx.manager.latest() is not None:
            meta = ctx.manager.restore(params=net, trainer=trainer,
                                       pipeline=pipe)
            start = meta["step"] + 1
        cur = {"step": start - 1}
        ctx.set_preemption_state(lambda: dict(
            step=cur["step"], params=net, trainer=trainer, pipeline=pipe))
        step = start
        for x, y in pipe:
            with autograd.record():
                loss = ((net(x) - y.reshape((-1, 1))) ** 2).sum()
            loss.backward()
            trainer.step(BS)
            cur["step"] = step
            ctx.step_done(step, save=dict(params=net, trainer=trainer,
                                          pipeline=pipe, sync=True))
            step += 1
        return step

    plan = resilience.FaultPlan([
        {"site": "train.step", "action": "delay", "on_hit": 2,
         "delay_s": STALL_SEC},
    ], seed=0)
    resilience.install_plan(plan)
    try:
        steps = sup.run(train)
    finally:
        resilience.clear_plan()
    assert [f["site"] for f in plan.fired()] == ["train.step"], \
        plan.fired()
    return steps


def serve_burst():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False, in_units=FEAT,
                     activation="relu"),
            nn.Dense(2, flatten=False, in_units=8))
    net.initialize(mx.init.Xavier())
    lengths = (4, 8)
    spec = serve.BucketSpec(batch_sizes=(1, 4),
                            example_shape=(None, FEAT), lengths=lengths)
    srv = serve.ModelServer(net, spec, max_queue=64, linger_ms=1.0)
    srv.start()
    rng = np.random.RandomState(1)
    futs = [srv.submit(rng.rand(int(rng.choice(lengths)),
                                FEAT).astype(np.float32))
            for _ in range(20)]
    for f in futs:
        f.result(timeout=300)
    srv.drain()
    # the caller keeps srv alive: its /metrics registration is a
    # weakref, so the scrape below must happen before it is dropped
    return srv, srv.stats()


def validate_trace(path):
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events, "empty trace"
    pids = set()
    opens = {}
    for ev in events:
        for field in ("name", "ph", "pid", "tid"):
            assert field in ev, f"event missing {field}: {ev}"
        if ev["ph"] != "M":
            assert "ts" in ev, f"non-metadata event missing ts: {ev}"
        if ev["ph"] == "X":
            assert ev["dur"] > 0, ev
        if ev["ph"] in ("b", "n", "e"):
            assert "id" in ev and "cat" in ev, ev
            key = (ev["cat"], ev["name"], ev["id"])
            if ev["ph"] == "b":
                opens[key] = opens.get(key, 0) + 1
            elif ev["ph"] == "e":
                assert opens.get(key, 0) > 0, f"e without b: {ev}"
                opens[key] -= 1
        pids.add(ev["pid"])
    assert len(pids) == 1, f"inconsistent pids: {pids}"
    dangling = {k: v for k, v in opens.items() if v}
    assert not dangling, f"unbalanced async spans: {dangling}"
    names = {ev["name"] for ev in events}
    cats = {ev.get("cat") for ev in events}
    # spans from all five subsystems
    for want in ("trainer.step", "allreduce", "fused_update"):
        assert want in names, f"missing trainer span {want}: {sorted(names)}"
    for want in ("pipeline.wait", "pipeline.map", "pipeline.batch"):
        assert want in names, f"missing pipeline span {want}"
    assert "serve.request" in names and any(
        n.startswith("serve.batch.") for n in names), sorted(names)
    assert "checkpoint.save.commit" in names, sorted(names)
    assert "resilience.watchdog" in names and "resilience.retry" in names
    assert {"trainer", "dataPipeline", "serve", "checkpoint",
            "resilience"} <= cats, cats
    thread_names = [ev for ev in events
                    if ev["ph"] == "M" and ev["name"] == "thread_name"]
    assert thread_names, "no thread_name metadata"
    return len(events)


def scrape(port):
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    values = {}
    for line in body.splitlines():
        assert line, "blank line in exposition output"
        if line.startswith("#"):
            parts = line.split()
            assert parts[1] in ("HELP", "TYPE"), line
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value.replace("+Inf", "inf"))
        values[name_part] = float(value) if value != "+Inf" else None
    health = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10).read())
    assert health["status"] == "ok", health
    return values


def main():
    ckdir = tempfile.mkdtemp(prefix="trace-smoke-")
    trace_path = os.path.join(ckdir, "run.trace.json")
    srv = telemetry.start_metrics_server(port=0)
    try:
        with telemetry.trace(trace_path):
            steps = supervised_train(ckdir)
            model_server, stats = serve_burst()
        n_events = validate_trace(trace_path)

        # flight recorder: the injected watchdog fire left a loadable
        # post-mortem next to the checkpoints
        dumps = [f for f in os.listdir(ckdir) if f.startswith("flight-")]
        assert dumps, f"no flight dump in {os.listdir(ckdir)}"
        with open(os.path.join(ckdir, sorted(dumps)[0])) as f:
            flight_doc = json.load(f)
        assert flight_doc["reason"] == "watchdog", flight_doc["reason"]
        assert flight_doc["traceEvents"], "empty flight ring"
        assert "counters" in flight_doc and "extra" in flight_doc

        # metrics endpoint agrees with profiler.dumps()
        sections = json.loads(profiler.dumps())
        vals = scrape(srv.port)
        assert vals["mxtpu_trainer_step_steps"] == \
            sections["trainerStep"]["steps"], (vals, sections)
        assert vals["mxtpu_resilience_watchdog_fires"] == \
            sections["resilience"]["watchdog_fires"] >= 1
        assert vals["mxtpu_data_pipeline_batches"] == \
            sections["dataPipeline"]["batches"]
        assert vals['mxtpu_serve_served{server="0"}'] == \
            stats["served"] == 20
        assert vals["mxtpu_metrics_scrapes_total"] >= 1
        del model_server  # keeps the weak /metrics registration live

        # disarmed overhead: the hooks ARE the no-op again
        assert tracer.span_begin is tracer._noop
        assert tracer.request_begin is tracer._noop
        fire = tracer.span_begin
        t0 = time.perf_counter()
        for _ in range(200_000):
            fire("trainer.step", "trainer")
        dt = time.perf_counter() - t0
        assert dt < 2.0, f"disarmed span hook cost {dt:.3f}s / 200k"

        wd = sections["resilience"]["watchdog_fires"]
        print(f"TRACE_SMOKE_OK steps={steps} trace_events={n_events} "
              f"served={stats['served']} watchdog_fires={wd} "
              f"flight_dumps={len(dumps)} "
              f"scrape_metrics={len(vals)} "
              f"disarmed_overhead_ns={dt / 200_000 * 1e9:.0f}")
        return 0
    finally:
        telemetry.stop_metrics_server()
        shutil.rmtree(ckdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
