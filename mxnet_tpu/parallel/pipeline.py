"""Compatibility shim: the pipeline schedule moved to
``parallel.spmd.schedule``.

The GPipe rotate schedule now lives with the rest of the multi-axis
machinery (mesh specs, ShardingPlan, SpmdStepCompiler) so the 'pp'
axis is programmed through one package.  This module keeps the
original import path working:

- :func:`~mxnet_tpu.parallel.spmd.schedule.pipeline_apply` — the
  stacked-stage rotate schedule (unchanged API);
- new code should also look at
  :func:`~mxnet_tpu.parallel.spmd.schedule.stage_partition` (balanced
  layer→stage ranges) and
  :class:`~mxnet_tpu.parallel.spmd.schedule.PipelineTrainStep` (the
  microbatched TRAINING step as one pjit'd program).

See docs/parallelism.md.
"""
from .spmd.schedule import (_pipeline_sharded, pipeline_apply,  # noqa: F401
                            stage_partition)

__all__ = ["pipeline_apply", "stage_partition"]
