"""Step-fusion gate for `make verify` (see docs/performance.md).

50 fused Trainer.step()s on a multi-param model under a DECAYING LR
schedule must execute with ZERO post-warmup XLA compiles (lr/t/wd/
rescale ride as traced scalars), the fused path must actually engage
(params_fused > 0), and a 5-step fused-vs-sequential A/B must be
bit-identical.  Runs on the CPU backend so the gate is deterministic
and fast on any host.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the gate A/Bs fused vs aggregate_num=1 — an exported aggregation-size
# env var beats the ctor arg and would collapse both arms into one
for _var in ("MXNET_OPTIMIZER_AGGREGATION_SIZE",
             "MXTPU_OPTIMIZER_AGGREGATION_SIZE"):
    os.environ.pop(_var, None)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import _imperative, autograd, gluon, lr_scheduler, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon import trainer as trainer_mod  # noqa: E402

N_LAYERS, UNITS, WARMUP, STEPS = 15, 16, 5, 50


def build(aggregate_num=None):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(N_LAYERS):
        net.add(nn.Dense(UNITS, in_units=UNITS))
    net.initialize(mx.init.Xavier())
    kwargs = {"learning_rate": 0.1, "momentum": 0.9,
              "lr_scheduler": lr_scheduler.FactorScheduler(
                  step=5, factor=0.95, base_lr=0.1)}
    if aggregate_num is not None:
        kwargs["aggregate_num"] = aggregate_num
    trainer = gluon.Trainer(net.collect_params(), "sgd", kwargs)
    x = nd.array(np.random.rand(4, UNITS).astype(np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    return net, trainer


def main():
    net, trainer = build()
    for _ in range(WARMUP):
        trainer.step(1)
    nd.waitall()
    lr0 = trainer.learning_rate
    trainer_mod.reset_trainer_step_stats()
    c0 = _imperative.compiled_executable_count()
    for _ in range(STEPS):
        trainer.step(1)
    nd.waitall()
    compiles = _imperative.compiled_executable_count() - c0
    stats = trainer_mod.trainer_step_stats()
    assert compiles == 0, \
        f"step fusion recompiled: {compiles} new executables in " \
        f"{STEPS} post-warmup steps (lr schedule must ride as a " \
        "traced scalar)"
    assert trainer.learning_rate < lr0, \
        f"LR schedule did not decay ({lr0} -> {trainer.learning_rate})"
    assert stats["params_fused"] == STEPS * 2 * N_LAYERS, \
        f"fused path did not engage: {stats}"

    # 5-step bit parity: fused (default) vs aggregate_num=1 sequential
    net_seq, trainer_seq = build(aggregate_num=1)
    for _ in range(5):
        trainer_seq.step(1)
    net_fused, trainer_fused = build()
    for _ in range(5):
        trainer_fused.step(1)
    for a, b in zip(net_fused.collect_params().values(),
                    net_seq.collect_params().values()):
        if not np.array_equal(a.data().asnumpy(), b.data().asnumpy()):
            raise AssertionError(
                f"fused/sequential weight divergence on {a.name}")

    print(f"STEP_FUSION_SMOKE_OK steps={STEPS} "
          f"post_warmup_compiles={compiles} "
          f"dispatches_per_step={stats['dispatches_per_step']} "
          f"params_fused={stats['params_fused']} "
          f"lr {lr0:.4f}->{trainer.learning_rate:.4f}")


if __name__ == "__main__":
    main()
