"""Standalone parameter-server process (ref: python/mxnet/
kvstore_server.py — the MXKVStoreRunServer role).

Launched by tools/launch.py -s N with DMLC_ROLE=server; serves the
dist_async transport (parallel/ps.py). Blocks until a worker sends
("stop",).

  python -m mxnet_tpu.kvstore_server
"""
from .parallel import ps


def main():
    ps.run_server()


if __name__ == "__main__":
    main()
