"""ResNet v1/v2 (ref: python/mxnet/gluon/model_zoo/vision/resnet.py).

The BASELINE ResNet-50 workload model.  NCHW layout; bf16-friendly
(cast via net.cast('bfloat16') — BatchNorm stats stay fp32 via the op's
internal math).
"""
from __future__ import annotations

from ....base import MXNetError, getenv
from ...block import HybridBlock
from ...parameter import DeferredInitializationError
from ... import nn


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels, layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(
                nn.Conv2D(channels, 1, stride, use_bias=False,
                          in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x_out, act_type="relu")


class BottleneckV1(HybridBlock):
    """1x1 -> 3x3 -> 1x1 bottleneck.

    With ``MXTPU_CONV_EPILOGUE=pallas`` and NHWC layout the forward
    routes the 1x1 convs through the fused Pallas epilogue ops
    (ops/conv_fused_ops.py: conv matmul + BN stats in one pass, the
    previous BN's normalize+ReLU folded into the next matmul's input
    read — the cuDNN fused-op pattern, ref batch_norm.cu /
    CUDNN_FUSED_SCALE_BIAS_ACTIVATION_CONV_BNSTATS).  Parameters,
    names, and checkpoints are IDENTICAL to the standard path — the
    fused forward reads the same child blocks' parameters — so the
    flag can be flipped per-run."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self._stride = stride
        self._fuse = (layout == "NHWC"
                      and getenv("CONV_EPILOGUE", "") == "pallas")
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=ax))
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(
                nn.Conv2D(channels, 1, stride, use_bias=False,
                          in_channels=in_channels, layout=layout))
            self.downsample.add(nn.BatchNorm(axis=ax))
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        if self._fuse and not getattr(F, "__name__", "").endswith("symbol"):
            try:
                return self._fused_forward(F, x)
            except DeferredInitializationError:
                # first call with deferred shapes: one standard pass
                # initializes every child param, fused thereafter
                pass
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x_out, act_type="relu")

    @staticmethod
    def _bn_kw(bn):
        return dict(eps=bn._kwargs["eps"],
                    momentum=bn._kwargs["momentum"],
                    fix_gamma=bn._kwargs["fix_gamma"])

    @staticmethod
    def _pdata(p, ctx):
        # context-aware fetch, mirroring _eager_forward: a net
        # initialized on several devices must compute against (and
        # commit running stats into) the INPUT's context copy
        if ctx is not None and p._data and ctx in p._data:
            return p.data(ctx)
        return p.data()

    def _bn_params(self, bn, ctx):
        return (self._pdata(bn.gamma, ctx), self._pdata(bn.beta, ctx),
                self._pdata(bn.running_mean, ctx),
                self._pdata(bn.running_var, ctx))

    def _fused_forward(self, F, x):
        from ...block import is_tracing

        ctx = None if is_tracing() else x.context
        c1, b1, c2, b2, c3, b3 = (self.body[0], self.body[1],
                                  self.body[3], self.body[4],
                                  self.body[6], self.body[7])
        # conv1 (1x1, stride): raw out + its BN folded to (scale, shift)
        y1, s1, h1 = F.contrib.conv1x1_bn_act(
            x, self._pdata(c1.weight, ctx), *self._bn_params(b1, ctx),
            stride=self._stride, **self._bn_kw(b1))
        # 3x3 stays on the XLA conv path; normalize+ReLU materializes
        # once (XLA fuses it with the conv's input)
        a1 = F.Activation(y1 * s1.astype(y1.dtype) + h1.astype(y1.dtype),
                          act_type="relu")
        y2 = c2(a1)
        # bn2: stats + fold only — NO normalized copy of y2 is written;
        # conv3 consumes the raw y2 with the normalize+ReLU fused into
        # its input read, and computes bn3's stats in its epilogue
        s2, h2 = F.contrib.bn_fold(y2, *self._bn_params(b2, ctx),
                                   **self._bn_kw(b2))
        y3, s3, h3 = F.contrib.conv1x1_bn_act(
            y2, self._pdata(c3.weight, ctx), *self._bn_params(b3, ctx),
            in_scale=s2, in_shift=h2, in_act=True, **self._bn_kw(b3))
        if self.downsample is not None:
            dc, db = self.downsample[0], self.downsample[1]
            yd, sd, hd = F.contrib.conv1x1_bn_act(
                x, self._pdata(dc.weight, ctx), *self._bn_params(db, ctx),
                stride=self._stride, **self._bn_kw(db))
            residual = yd * sd.astype(yd.dtype) + hd.astype(yd.dtype)
        else:
            residual = x
        out = y3 * s3.astype(y3.dtype) + h3.astype(y3.dtype) + residual
        return F.Activation(out, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels, layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False,
                               layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self.bn1 = nn.BatchNorm(axis=ax)
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False,
                               layout=layout)
        self.bn2 = nn.BatchNorm(axis=ax)
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False,
                               layout=layout)
        self.bn3 = nn.BatchNorm(axis=ax)
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False,
                               layout=layout)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels,
                                        layout=layout)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i], layout=layout))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes)

    def _make_layer(self, block, num_layers, channels, stride,
                    in_channels=0, layout="NCHW"):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride,
                        downsample=(channels != in_channels or stride != 1),
                        in_channels=in_channels, layout=layout))
        for _ in range(num_layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels,
                            layout=layout))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        ax = -1 if layout[-1] == "C" else 1
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(axis=ax, scale=False, center=False))
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False, layout=layout))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=ax))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i], layout=layout))
        self.features.add(nn.BatchNorm(axis=ax))
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D(layout=layout))
        self.output = nn.Dense(classes)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


_blocks = {1: {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
           2: {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}}
_nets = {1: ResNetV1, 2: ResNetV2}


def get_resnet(version, num_layers, pretrained=False, ctx=None,
               classes=1000, **kwargs):
    if num_layers not in resnet_spec:
        raise MXNetError(f"unsupported resnet depth {num_layers}")
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress); "
                         "load_parameters from a local file instead")
    block_type, layers, channels = resnet_spec[num_layers]
    return _nets[version](_blocks[version][block_type], layers, channels,
                          classes=classes, **kwargs)


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)
