"""Rich parameter descriptors for the headline ops.

Ref: the DMLC_DECLARE_FIELD blocks in src/operator/nn/*.cc parameter
structs (ConvolutionParam, PoolingParam, BatchNormParam, ...) — the
defaults/ranges/docs that make `help(mx.nd.Convolution)`
self-documenting. Ops without an explicit block here derive typed
descriptors from their kernel signatures (registry.param_descriptors).
"""
from __future__ import annotations

from .registry import Param, get


def _attach(op, *params):
    entry = get(op)
    entry.params = {p.name: p for p in params}
    entry._doc_cache = None


def install():
    _attach(
        "Convolution",
        Param("kernel", tuple, required=True,
              doc="Convolution kernel size (h, w) or (d, h, w)."),
        Param("stride", tuple, (), doc="Stride; defaults to 1 per dim."),
        Param("dilate", tuple, (), doc="Dilation; defaults to 1 per dim."),
        Param("pad", tuple, (), doc="Zero padding; defaults to 0 per dim."),
        Param("num_filter", int, 0, low=0,
              doc="Number of output channels."),
        Param("num_group", int, 1, low=1,
              doc="Grouped convolution group count."),
        Param("no_bias", bool, True, doc="Skip the bias term."),
        Param("layout", str, None,
              choices=(None, "NCHW", "NCDHW", "NCW",
                       "NHWC", "NDHWC", "NWC"),
              doc="Data layout. Channel-last (NHWC & co) is the "
                  "TPU-preferred form: channel lands on the minormost "
                  "(128-lane) tile dim, so conv relayouts and "
                  "per-channel BN reductions vanish. Channel-last "
                  "weights are OHWI."),
        Param("cudnn_tune", str, None,
              choices=(None, "off", "limited_workspace", "fastest"),
              doc="Accepted for reference compatibility; XLA owns "
                  "algorithm choice."),
        Param("cudnn_off", bool, False,
              doc="Accepted for reference compatibility."),
        Param("workspace", int, 1024,
              doc="Accepted for reference compatibility (MB)."),
    )
    _attach(
        "FullyConnected",
        Param("num_hidden", int, 0, low=1, required=True,
              doc="Output feature size."),
        Param("no_bias", bool, False, doc="Skip the bias term."),
        Param("flatten", bool, True,
              doc="Flatten trailing input dims; False applies the layer "
                  "to the last axis only."),
    )
    _attach(
        "Pooling",
        Param("kernel", tuple, (), doc="Pooling window."),
        Param("pool_type", str, "max",
              choices=("max", "avg", "sum", "lp"),
              doc="Pooling function."),
        Param("global_pool", bool, False,
              doc="Pool over the full spatial extent."),
        Param("stride", tuple, (), doc="Stride; defaults to kernel."),
        Param("pad", tuple, (), doc="Padding; defaults to 0."),
        Param("pooling_convention", str, "valid",
              choices=("valid", "full", "same"),
              doc="Output-shape rounding convention."),
        Param("count_include_pad", bool, True,
              doc="avg pool: include padding positions in the divisor."),
        Param("p_value", int, 2, low=1, doc="lp pool exponent."),
        Param("layout", str, None,
              choices=(None, "NCHW", "NCDHW", "NCW",
                       "NHWC", "NDHWC", "NWC"),
              doc="Data layout; channel-last is TPU-preferred."),
    )
    _attach(
        "BatchNorm",
        Param("eps", float, 1e-3, low=0.0, doc="Variance epsilon."),
        Param("momentum", float, 0.9, low=0.0, high=1.0,
              doc="Moving-average momentum."),
        Param("fix_gamma", bool, True, doc="Hold gamma at 1."),
        Param("use_global_stats", bool, False,
              doc="Use moving stats in training too."),
        Param("output_mean_var", bool, False,
              doc="Also return (mean, var)."),
        Param("axis", int, 1, doc="Channel axis."),
    )
    _attach(
        "Activation",
        Param("act_type", str, None, required=True,
              choices=("relu", "sigmoid", "tanh", "softrelu",
                       "softsign"),
              doc="Nonlinearity to apply."),
    )
    _attach(
        "LeakyReLU",
        Param("act_type", str, "leaky",
              choices=("leaky", "elu", "gelu", "selu", "prelu",
                       "rrelu"),
              doc="Leaky-family nonlinearity."),
        Param("slope", float, 0.25, doc="Negative-half slope."),
        Param("lower_bound", float, 0.125, doc="rrelu lower bound."),
        Param("upper_bound", float, 0.334, doc="rrelu upper bound."),
    )
    _attach(
        "Dropout",
        Param("p", float, 0.5, low=0.0, high=1.0,
              doc="Fraction of units dropped during training."),
        Param("mode", str, "training", choices=("training", "always"),
              doc="'always' applies dropout at inference too."),
        Param("axes", tuple, (), doc="Broadcast-dropout axes."),
    )
    _attach(
        "softmax",
        Param("axis", int, -1, doc="Axis to normalize over."),
        Param("temperature", float, None, doc="Logit divisor."),
        Param("dtype", str, None, doc="Output dtype override."),
    )
    _attach(
        "Embedding",
        Param("input_dim", int, 0, low=1, required=True,
              doc="Vocabulary size."),
        Param("output_dim", int, 0, low=1, required=True,
              doc="Embedding width."),
        Param("dtype", str, "float32", doc="Embedding dtype."),
        Param("sparse_grad", bool, False,
              doc="Return a row_sparse gradient."),
    )
    _attach(
        "LayerNorm",
        Param("axis", int, -1, doc="Axis to normalize."),
        Param("eps", float, 1e-5, low=0.0, doc="Variance epsilon."),
        Param("output_mean_std", bool, False,
              doc="Also return (mean, std)."),
    )
    _attach(
        "RNN",
        Param("state_size", int, 0, low=1, required=True,
              doc="Hidden state width."),
        Param("num_layers", int, 0, low=1, required=True,
              doc="Stacked layer count."),
        Param("mode", str, None, required=True,
              choices=("rnn_relu", "rnn_tanh", "lstm", "gru"),
              doc="Cell type (fused over the whole sequence; LSTM uses "
                  "the Pallas recurrence kernel on TPU)."),
        Param("bidirectional", bool, False, doc="Bidirectional stack."),
        Param("p", float, 0.0, low=0.0, high=1.0,
              doc="Inter-layer dropout."),
        Param("state_outputs", bool, False,
              doc="Also return final states."),
    )


install()
