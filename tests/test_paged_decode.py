"""mxnet_tpu.serve paged KV cache + speculative decoding.

Covers the paged arena's contract: paged continuous decode is
bit-identical to paged whole-batch decode (and to the contiguous arena
when the logical ranges match); prefix sharing stores shared pages
ONCE (refcounts asserted) with copy-on-write on first divergence and
eviction only at refcount zero; interleaved admit/finish churn never
leaks pages (allocator ledger invariant); token-budget admission
defers — never drops — requests the pool can't cover and rejects
loudly what can NEVER fit; greedy speculative decoding emits
bit-identical output to non-speculative greedy with exact dispatch
accounting (verify + draft + admission dispatches); and the whole
surface runs with ZERO post-warmup compiles.
"""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _imperative, serve
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve.paging import (PageAllocator, PrefixIndex,
                                    chunk_keys, pages_spanned)

VOCAB = 64


def _make_model(seed=3, vocab=VOCAB, embed=16):
    mx.random.seed(seed)
    model = serve.TinyDecoder(vocab=vocab, embed=embed)
    model.initialize(mx.init.Xavier())
    return model


def _spec(batches=(1, 2, 4), lengths=(4, 8)):
    return serve.BucketSpec(batch_sizes=batches, example_shape=(None,),
                            lengths=lengths, dtype="int32")


def _prompts(n, rng, max_len=8):
    return [rng.randint(0, VOCAB, size=int(rng.randint(2, max_len + 1)))
            .astype(np.int32) for _ in range(n)]


def _server(model, **kwargs):
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 32)
    kwargs.setdefault("page_tokens", 4)
    return serve.DecodeServer(model, kwargs.pop("spec", _spec()),
                              **kwargs)


# ---------------------------------------------------------------------------
# paging primitives


def test_allocator_refcount_lifecycle_and_ledger():
    a = PageAllocator(4, 8)
    assert a.trash == 4
    p = a.alloc()
    assert a.ref(p) == 1 and a.live_count() == 1
    a.retain(p)
    assert a.ref(p) == 2
    assert a.release(p) is False          # still referenced: no evict
    assert a.live_count() == 1
    assert a.release(p) is True           # refcount zero: evicted
    assert a.free_count() == 4
    a.check()
    with pytest.raises(MXNetError):
        a.release(p)                      # double free is a loud bug
    with pytest.raises(MXNetError):
        a.retain(p)                       # retain of a free page too
    for _ in range(4):
        a.alloc()
    with pytest.raises(MXNetError, match="exhausted"):
        a.alloc()


def test_chunk_keys_are_chained_prefix_hashes():
    t = 4
    a = np.arange(10, dtype=np.int32)
    b = np.arange(10, dtype=np.int32)
    b[9] = 63                             # diverge INSIDE the tail
    ka, kb = chunk_keys(a, 10, t), chunk_keys(b, 10, t)
    assert len(ka) == pages_spanned(10, t) == 3
    assert ka[0] == kb[0] and ka[1] == kb[1]   # shared full pages
    assert ka[2] != kb[2]                      # divergent partial tail
    # chained: the SAME chunk after a different history never collides
    c = np.arange(10, dtype=np.int32)
    c[0] = 63
    kc = chunk_keys(c, 10, t)
    assert kc[1] != ka[1] and kc[2] != ka[2]
    # a partial tail never collides with a full page of a longer prompt
    k8 = chunk_keys(a, 8, t)
    k7 = chunk_keys(a, 7, t)
    assert k8[1][0] == "F" and k7[1][0] == "P"
    assert k8[1] != k7[1]


def test_prefix_index_drop_page_invalidates_all_keys():
    idx = PrefixIndex()
    idx.register(("F", 0, "aa"), 3)
    idx.register(("F", 1, "bb"), 3)
    idx.register(("F", 0, "cc"), 5)
    assert idx.lookup(("F", 1, "bb")) == 3 and len(idx) == 3
    idx.drop_page(3)
    assert idx.lookup(("F", 0, "aa")) is None
    assert idx.lookup(("F", 1, "bb")) is None
    assert idx.lookup(("F", 0, "cc")) == 5 and len(idx) == 1


# ---------------------------------------------------------------------------
# parity: the acceptance gates


def test_parity_paged_continuous_vs_whole_batch():
    """Paged continuous decode is bit-identical to paged whole-batch
    decode: page churn, prefix sharing, and COW never change any
    sequence."""
    model = _make_model()
    rng = np.random.RandomState(1)
    prompts = _prompts(14, rng)
    budgets = [int(rng.randint(2, 12)) for _ in prompts]

    def run(admission, stagger=0.0):
        srv = _server(model, admission=admission)
        srv.start()
        handles = []
        for p, m in zip(prompts, budgets):
            handles.append(srv.submit(p, max_new_tokens=m))
            if stagger:
                time.sleep(stagger)
        seqs = [h.result(timeout=120) for h in handles]
        srv.drain()
        return seqs, srv.stats()

    cont, s_cont = run("continuous", stagger=0.002)
    whole, s_whole = run("batch")
    for a, b in zip(cont, whole):
        np.testing.assert_array_equal(a, b)
    assert all(len(seq) == m for seq, m in zip(cont, budgets))
    assert s_cont["graph"]["post_warmup_compiles"] == 0
    assert s_whole["graph"]["post_warmup_compiles"] == 0


def test_parity_paged_vs_contiguous_arena():
    """With the logical range matched (pages_per_slot * page_tokens ==
    max_len), the paged arena emits bit-identical sequences to the
    contiguous arena — paging is a memory-layout change, not a math
    change."""
    model = _make_model()
    rng = np.random.RandomState(7)
    prompts = _prompts(10, rng)
    budgets = [int(rng.randint(2, 10)) for _ in prompts]

    def run(**kw):
        srv = serve.DecodeServer(model, _spec(), max_slots=4,
                                 max_len=32, **kw)
        srv.start()
        hs = [srv.submit(p, max_new_tokens=m)
              for p, m in zip(prompts, budgets)]
        seqs = [h.result(timeout=120) for h in hs]
        srv.drain()
        return seqs

    paged = run(page_tokens=4)            # 8 pages/slot * 4 == 32
    contiguous = run()
    for a, b in zip(paged, contiguous):
        np.testing.assert_array_equal(a, b)


def test_parity_speculative_greedy_bit_identical_and_dispatches():
    """Greedy speculative output is bit-identical to non-speculative
    greedy (acceptance is a pure function of draft + target logits;
    the target's argmax decides every emitted token), and dispatch
    accounting is exact: delta == verify steps + draft proposal steps
    + admission groups."""
    model = _make_model()
    draft = serve.TinyDraft(model)
    rng = np.random.RandomState(5)
    prompts = _prompts(12, rng)
    budgets = [int(rng.randint(2, 12)) for _ in prompts]

    def run(**kw):
        srv = _server(model, **kw)
        srv.start()
        d0 = _imperative.device_dispatch_count()
        hs = [srv.submit(p, max_new_tokens=m)
              for p, m in zip(prompts, budgets)]
        seqs = [h.result(timeout=120) for h in hs]
        srv.drain()
        d = _imperative.device_dispatch_count() - d0
        return seqs, srv.stats(), d

    spec, s_spec, d_spec = run(draft=draft, spec_k=4)
    plain, s_plain, d_plain = run()
    for a, b in zip(spec, plain):
        np.testing.assert_array_equal(a, b)
    assert s_spec["graph"]["post_warmup_compiles"] == 0
    assert d_spec == (s_spec["decode_steps"] + s_spec["spec_draft_steps"]
                      + s_spec["batches"])
    assert d_plain == s_plain["decode_steps"] + s_plain["batches"]
    # the point of speculation: fewer scheduling rounds than tokens,
    # and (TinyDraft ~= the target) a positive acceptance rate
    assert s_spec["decode_steps"] < s_plain["decode_steps"]
    assert s_spec["spec"]["accept_rate"] > 0


def test_paged_exact_dispatch_accounting():
    """Non-speculative paged path: one dispatch per token step plus
    one per fused admission group — COW copies and page-table updates
    ride inside those executables, never as extra dispatches."""
    model = _make_model()
    srv = _server(model, max_queue=128)
    srv.start()
    execs_before = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    rng = np.random.RandomState(2)
    handles = []
    for i, p in enumerate(_prompts(24, rng)):
        handles.append(srv.submit(p,
                                  max_new_tokens=int(rng.randint(1, 9))))
        if i % 5 == 0:
            time.sleep(0.002)
    for h in handles:
        h.result(timeout=120)
    srv.drain()
    d1 = _imperative.device_dispatch_count()
    s = srv.stats()
    assert s["served"] == 24
    assert s["graph"]["post_warmup_compiles"] == 0
    assert _imperative.compiled_executable_count() == execs_before
    assert d1 - d0 == s["decode_steps"] + s["batches"]


# ---------------------------------------------------------------------------
# prefix sharing: stored once, COW on divergence, evict at refcount 0


def test_prefix_sharing_stores_shared_pages_once():
    """Two overlapping requests with the same prompt: every prompt
    page (two full + the partial tail) is physically stored once
    (refcount 2 asserted on the live server), the first write into the
    still-shared tail page goes copy-on-write, and outputs are
    bit-identical to an unshared run."""
    model = _make_model()
    shared = np.arange(1, 9, dtype=np.int32)      # 2 full pages of 4
    p1 = np.concatenate([shared, [9]]).astype(np.int32)
    p2 = p1.copy()                        # identical: tail shared too

    srv = _server(model, spec=_spec(lengths=(4, 8, 16)),
                  max_new_tokens=64)
    srv.start()
    h1 = srv.submit(p1, max_new_tokens=20)
    # let request 1 admit so its prefix pages are resident
    for _ in range(200):
        if srv.live_slots():
            break
        time.sleep(0.005)
    h2 = srv.submit(p2, max_new_tokens=20)
    seen_shared = False
    for _ in range(400):
        if srv.live_slots() == 2:
            tables = [srv._slot_pages[int(s)]
                      for s in np.flatnonzero(srv._active)]
            if len(tables) == 2:
                common = set(tables[0][:2]) & set(tables[1][:2])
                if common and all(srv._alloc.ref(pg) == 2
                                  for pg in common):
                    seen_shared = True
                    break
        time.sleep(0.002)
    out = [h1.result(60), h2.result(60)]
    srv.drain()
    assert seen_shared, "prefix pages were never physically shared"
    s = srv.stats()
    assert s["page_prefix_hits"] >= 2     # both full pages hit
    assert s["page_cow"] >= 1             # divergent tail wrote via COW
    srv._alloc.check()

    # bit-identity vs the unshared path: same requests, run apart so
    # nothing overlaps and no page is ever shared
    ref = _server(model, spec=_spec(lengths=(4, 8, 16)),
                  max_new_tokens=64)
    ref.start()
    r1 = ref.submit(p1, max_new_tokens=20).result(60)
    ref.drain()
    ref2 = _server(model, spec=_spec(lengths=(4, 8, 16)),
                   max_new_tokens=64)
    ref2.start()
    r2 = ref2.submit(p2, max_new_tokens=20).result(60)
    ref2.drain()
    np.testing.assert_array_equal(out[0], r1)
    np.testing.assert_array_equal(out[1], r2)


def test_prefix_eviction_only_at_refcount_zero():
    """A shared page survives its first sharer's finish (refcount
    drops 2 -> 1, the prefix index still serves it) and is evicted
    only when the LAST reference releases."""
    model = _make_model()
    shared = np.arange(1, 9, dtype=np.int32)
    p_short = np.concatenate([shared, [9]]).astype(np.int32)
    p_long = np.concatenate([shared, [11]]).astype(np.int32)
    srv = _server(model, spec=_spec(lengths=(4, 8, 16)),
                  max_new_tokens=64)
    srv.start()
    h_long = srv.submit(p_long, max_new_tokens=20)
    for _ in range(200):
        if srv.live_slots():
            break
        time.sleep(0.005)
    keys = chunk_keys(p_long, len(p_long), 4)
    page0 = srv._prefix.lookup(keys[0])
    assert page0 is not None
    h_short = srv.submit(p_short, max_new_tokens=2)
    h_short.result(60)                    # short sharer finished
    assert srv.live_slots() >= 1          # long one still decoding
    assert srv._alloc.ref(page0) >= 1     # NOT evicted: still live
    assert srv._prefix.lookup(keys[0]) == page0
    h_long.result(60)
    srv.drain()
    assert srv._alloc.ref(page0) == 0     # last release evicted it
    assert srv._prefix.lookup(keys[0]) is None
    srv._alloc.check()


def test_fragmentation_churn_never_leaks_pages():
    """Interleaved admit/finish churn with mixed lengths and shared
    prefixes: after the dust settles, the allocator ledger balances
    and every page is back on the free list."""
    model = _make_model()
    rng = np.random.RandomState(9)
    shared = np.arange(1, 5, dtype=np.int32)
    srv = _server(model, max_queue=256, num_pages=20)
    srv.start()
    handles = []
    for i in range(40):
        if rng.rand() < 0.4:              # share a prefix page
            p = np.concatenate(
                [shared, rng.randint(0, VOCAB,
                                     size=int(rng.randint(1, 4)))])
        else:
            p = rng.randint(0, VOCAB, size=int(rng.randint(2, 9)))
        handles.append(srv.submit(p.astype(np.int32),
                                  max_new_tokens=int(rng.randint(1, 8))))
        if i % 3 == 0:
            time.sleep(0.002)
    for h in handles:
        h.result(timeout=120)
    srv.drain()
    alloc = srv._alloc
    alloc.check()                         # ledger invariant
    assert alloc.free_count() == alloc.num_pages   # zero leaked pages
    assert alloc.allocs == alloc.frees
    assert len(srv._prefix) == 0          # index holds no dead keys
    assert srv._committed == 0
    s = srv.stats()
    assert s["page_allocs"] == s["page_frees"]
    assert s["graph"]["post_warmup_compiles"] == 0


# ---------------------------------------------------------------------------
# token-budget admission


def test_submit_rejects_never_fitting_request_loudly():
    model = _make_model()
    srv = _server(model)                  # 32-token logical range
    srv.start()
    with pytest.raises(MXNetError) as e:
        srv.submit(np.arange(8, dtype=np.int32), max_new_tokens=100)
    msg = str(e.value)
    assert "NEVER fit" in msg
    assert "logical budget" in msg and "page pool" in msg
    srv.drain()


def test_small_pool_defers_admissions_instead_of_failing():
    """A pool far below max_slots * pages_per_slot: admission defers
    on the token budget and every request still resolves — capacity
    scales with tokens in flight, not worst case."""
    model = _make_model()
    # 6 pages of 4 = 24 tokens of physical cache for 4 slots x 32
    # logical — far below worst case
    srv = _server(model, num_pages=6, max_queue=64)
    srv.start()
    rng = np.random.RandomState(3)
    handles = [srv.submit(p, max_new_tokens=int(rng.randint(2, 6)))
               for p in _prompts(12, rng, max_len=6)]
    seqs = [h.result(timeout=120) for h in handles]
    srv.drain()
    assert len(seqs) == 12
    s = srv.stats()
    assert s["served"] == 12
    assert s["graph"]["post_warmup_compiles"] == 0
    srv._alloc.check()


def test_speculation_requires_paged_arena_and_draft():
    model = _make_model()
    draft = serve.TinyDraft(model)
    with pytest.raises(MXNetError, match="paged arena"):
        serve.DecodeServer(model, _spec(), max_slots=4, max_len=32,
                           draft=draft, spec_k=4)
    with pytest.raises(MXNetError, match="draft"):
        _server(model, spec_k=4)
    with pytest.raises(MXNetError, match="spec_k"):
        _server(model, draft=draft)
    other = _make_model(seed=11, vocab=32)
    with pytest.raises(MXNetError, match="vocab mismatch"):
        _server(model, draft=serve.TinyDraft(other), spec_k=4)


# ---------------------------------------------------------------------------
# geometry + observability glue


def test_derive_decode_geometry_paged_pool_sizing():
    from mxnet_tpu.tune.geometry import derive_decode_geometry

    hist = {8: 90, 64: 10}                # heavy-tailed lengths
    g = derive_decode_geometry(hist, max_new_tokens=16, max_slots=8,
                               paged=True, page_tokens=16)
    assert g["page_tokens"] == 16
    assert g["pages_per_slot"] == -(-g["max_len"] // 16)
    # the pool is sized to the MEAN in-flight span, well under the
    # worst case, but never below one slot's worst case
    worst = 8 * g["pages_per_slot"]
    assert g["pages_per_slot"] <= g["num_pages"] < worst
    with pytest.raises(MXNetError):
        derive_decode_geometry(hist, paged=True, page_tokens=0)


def test_paged_knobs_registered():
    from mxnet_tpu.tune.knobs import default_registry

    reg = default_registry()
    for name, env in (("decode_page_tokens", "DECODE_PAGE_TOKENS"),
                      ("decode_spec_k", "DECODE_SPEC_K"),
                      ("decode_draft", "DECODE_DRAFT")):
        k = reg.get(name)
        assert k.env == env
        assert k.restart == "recompile"


def test_stats_and_metrics_export_page_spec_families():
    model = _make_model()
    draft = serve.TinyDraft(model)
    srv = _server(model, draft=draft, spec_k=2)
    srv.start()
    srv.submit(np.arange(1, 6, dtype=np.int32),
               max_new_tokens=4).result(60)
    s = srv.stats()
    assert s["pages"]["page_tokens"] == 4
    assert s["pages"]["hbm_bytes"] > 0
    assert s["spec"]["k"] == 2 and s["spec"]["draft"] is True
    from mxnet_tpu.telemetry import metrics as _metrics

    reg = _metrics.Registry()
    _metrics.register_decode_server(srv, registry=reg)
    text = reg.render()
    for name in ("mxtpu_decode_page_in_flight",
                 "mxtpu_decode_page_hbm_bytes",
                 "mxtpu_decode_page_prefix_hits",
                 "mxtpu_decode_spec_rounds",
                 "mxtpu_decode_spec_accepted"):
        assert name in text, name
    srv.drain()
