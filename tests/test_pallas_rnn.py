"""Pallas fused LSTM kernel parity tests (interpret mode on CPU).

The lax.scan implementation in ops/rnn.py is the oracle — the same
CPU-as-oracle pattern the reference uses for GPU kernels (SURVEY §4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx  # noqa: F401  (registers ops)


def _scan_lstm(x_proj, wh, h0, c0):
    """Oracle recurrence (same math as ops/rnn.py _step_fn('lstm'))."""
    def body(carry, xp_t):
        h, c = carry
        gates = xp_t + h @ wh.T
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    (hn, cn), ys = jax.lax.scan(body, (h0, c0), x_proj)
    return ys, hn, cn


@pytest.mark.parametrize("T,N,H", [(5, 4, 8), (12, 2, 16), (7, 3, 40)])
def test_lstm_forward_parity(interpret_pallas, T, N, H):
    from mxnet_tpu.ops.pallas.rnn import lstm_layer

    rng = np.random.RandomState(0)
    xp = jnp.asarray(rng.randn(T, N, 4 * H), jnp.float32) * 0.5
    wh = jnp.asarray(rng.randn(4 * H, H), jnp.float32) * 0.3
    h0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1
    c0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1

    ys, hn, cn = lstm_layer(xp, wh, h0, c0)
    ys_ref, hn_ref, cn_ref = _scan_lstm(xp, wh, h0, c0)
    np.testing.assert_allclose(ys, ys_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hn, hn_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cn, cn_ref, rtol=1e-5, atol=1e-5)


def test_lstm_backward_parity(interpret_pallas):
    from mxnet_tpu.ops.pallas.rnn import lstm_layer

    T, N, H = 6, 3, 8
    rng = np.random.RandomState(1)
    xp = jnp.asarray(rng.randn(T, N, 4 * H), jnp.float32) * 0.5
    wh = jnp.asarray(rng.randn(4 * H, H), jnp.float32) * 0.3
    h0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1
    c0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1
    wy = jnp.asarray(rng.randn(H,), jnp.float32)

    def loss_pallas(xp, wh, h0, c0):
        ys, hn, cn = lstm_layer(xp, wh, h0, c0)
        return jnp.sum(ys @ wy) + jnp.sum(hn * hn) + jnp.sum(cn)

    def loss_ref(xp, wh, h0, c0):
        ys, hn, cn = _scan_lstm(xp, wh, h0, c0)
        return jnp.sum(ys @ wy) + jnp.sum(hn * hn) + jnp.sum(cn)

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(xp, wh, h0, c0)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(xp, wh, h0, c0)
    for a, b, name in zip(g_p, g_r, ["dxp", "dwh", "dh0", "dc0"]):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


def test_rnn_op_pallas_impl_matches_scan(interpret_pallas, monkeypatch):
    """The full RNN op (multi-layer, bidirectional) through the Pallas
    path matches the scan path, forward and backward."""
    import mxnet_tpu.ops.rnn as rnn_mod
    from mxnet_tpu.ops.rnn import rnn_param_size

    T, N, I, H, L = 5, 3, 6, 8, 2
    rng = np.random.RandomState(2)
    data = jnp.asarray(rng.randn(T, N, I), jnp.float32) * 0.5
    psize = rnn_param_size(L, I, H, "lstm", bidirectional=True)
    params = jnp.asarray(rng.randn(psize), jnp.float32) * 0.2
    state = jnp.asarray(rng.randn(2 * L, N, H), jnp.float32) * 0.1
    cell = jnp.asarray(rng.randn(2 * L, N, H), jnp.float32) * 0.1

    def run(params, use_pallas):
        monkeypatch.setenv("MXTPU_RNN_IMPL",
                           "pallas" if use_pallas else "scan")
        out, hn, cn = rnn_mod._k_rnn(
            data, params, state, cell, state_size=H, num_layers=L,
            mode="lstm", bidirectional=True)
        return out, hn, cn

    out_p, hn_p, cn_p = run(params, True)
    out_s, hn_s, cn_s = run(params, False)
    np.testing.assert_allclose(out_p, out_s, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hn_p, hn_s, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cn_p, cn_s, rtol=1e-5, atol=1e-5)

    def loss(params, use_pallas):
        out, hn, cn = run(params, use_pallas)
        return jnp.sum(out ** 2) + jnp.sum(hn) + jnp.sum(cn)

    gp = jax.grad(loss)(params, True)
    gs = jax.grad(loss)(params, False)
    np.testing.assert_allclose(gp, gs, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# GRU (r5): same oracle pattern against ops/rnn.py _step_fn('gru')


def _scan_gru(x_proj, wh, bh, h0):
    def body(h, xp_t):
        gh = h @ wh.T + bh
        ir, iz, inn = jnp.split(xp_t, 3, axis=-1)
        hr, hz, hn_l = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inn + r * hn_l)
        h = (1 - z) * n + z * h
        return h, h

    hn, ys = jax.lax.scan(body, h0, x_proj)
    return ys, hn


@pytest.mark.parametrize("T,N,H", [(5, 4, 8), (9, 2, 16), (7, 3, 40)])
def test_gru_forward_parity(interpret_pallas, T, N, H):
    from mxnet_tpu.ops.pallas.rnn import gru_layer

    rng = np.random.RandomState(2)
    xp = jnp.asarray(rng.randn(T, N, 3 * H), jnp.float32) * 0.5
    wh = jnp.asarray(rng.randn(3 * H, H), jnp.float32) * 0.3
    bh = jnp.asarray(rng.randn(3 * H), jnp.float32) * 0.1
    h0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1

    ys, hn = gru_layer(xp, wh, bh, h0)
    ys_ref, hn_ref = _scan_gru(xp, wh, bh, h0)
    np.testing.assert_allclose(ys, ys_ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hn, hn_ref, rtol=1e-5, atol=1e-5)


def test_gru_backward_parity(interpret_pallas):
    from mxnet_tpu.ops.pallas.rnn import gru_layer

    T, N, H = 6, 3, 8
    rng = np.random.RandomState(3)
    xp = jnp.asarray(rng.randn(T, N, 3 * H), jnp.float32) * 0.5
    wh = jnp.asarray(rng.randn(3 * H, H), jnp.float32) * 0.3
    bh = jnp.asarray(rng.randn(3 * H), jnp.float32) * 0.1
    h0 = jnp.asarray(rng.randn(N, H), jnp.float32) * 0.1
    wy = jnp.asarray(rng.randn(H,), jnp.float32)

    def loss_pallas(xp, wh, bh, h0):
        ys, hn = gru_layer(xp, wh, bh, h0)
        return jnp.sum(ys @ wy) + jnp.sum(hn * hn)

    def loss_ref(xp, wh, bh, h0):
        ys, hn = _scan_gru(xp, wh, bh, h0)
        return jnp.sum(ys @ wy) + jnp.sum(hn * hn)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(xp, wh, bh, h0)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(xp, wh, bh, h0)
    for a, b, name in zip(gp, gr, ("dxp", "dwh", "dbh", "dh0")):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=name)


def test_gru_full_op_parity_forced_pallas(interpret_pallas, monkeypatch):
    """The registered RNN op with mode='gru' through the forced-Pallas
    path must equal the scan path (multi-layer + bidirectional)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    monkeypatch.setenv("MXTPU_RNN_IMPL", "pallas")
    rng = np.random.RandomState(4)
    T, N, I, H, L = 5, 3, 8, 8, 2
    x = rng.randn(T, N, I).astype(np.float32)
    d = 2
    sizes = []
    for layer in range(L):
        inp = I if layer == 0 else H * d
        for _ in range(d):
            sizes.append(3 * H * inp)
            sizes.append(3 * H * H)
            sizes.append(3 * H)
            sizes.append(3 * H)
    params = rng.randn(sum(sizes)).astype(np.float32) * 0.2
    h0 = np.zeros((L * d, N, H), np.float32)

    out_p = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                   state_size=H, num_layers=L, mode="gru",
                   bidirectional=True, state_outputs=True)
    monkeypatch.setenv("MXTPU_RNN_IMPL", "scan")
    out_s = nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                   state_size=H, num_layers=L, mode="gru",
                   bidirectional=True, state_outputs=True)
    for a, b in zip(out_p, out_s):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-5, atol=1e-5)
