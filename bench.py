"""Benchmark entry point — prints ONE JSON line.

Flagship: ResNet-50 v1 (BASELINE.json config #2) trained with the
compiled SPMD step (forward + backward + grad reduce + SGD fused into
one XLA computation, parameter donation) on synthetic ImageNet-shaped
data. Reports images/sec and MFU (step FLOPs from XLA cost analysis /
chip peak bf16 FLOPs).

Robustness (round-1 failure: the axon TPU backend hung for 9+ minutes
and the driver recorded rc=1 with no parseable output):
- the parent process NEVER imports jax; all device work happens in
  subprocesses with hard timeouts
- the TPU backend is health-probed first (devices + tiny matmul),
  with one retry after backoff
- on TPU failure the bench falls back to CPU so a parseable JSON line
  with a real measurement is always printed, with the TPU failure cause
  recorded in the "note" field

vs_baseline: fraction of the BASELINE.json north-star target (>=50% MFU
on the real chip). On the CPU fallback there is no MFU target, so
vs_baseline reports 0.0 and the note explains why.

Roofline context (profiled on the v5 lite chip, see docs/BENCHMARKS.md):
ResNet-50 training moves ~32 GB of HBM traffic per 1.57-TFLOP step
(BN stats/normalize + ReLU + residual passes over 2.4 GB of bf16
activations) — arithmetic intensity ~49 FLOP/byte against the chip's
~240 FLOP/byte compute/bandwidth crossover, so the model is
HBM-bandwidth-bound on this hardware with an MFU ceiling near 20%;
the measured ~16% is ~80% of that roofline (convolutions themselves
run at near-peak inside their fusions, and reduce/elementwise passes
run near HBM speed).  The >=50% MFU north star is reachable only for
compute-bound workloads — see tools/bench_workloads.py (BERT-base MLM)
for that measurement; the 'roofline_mfu_bound' field reports the
model's bandwidth-implied ceiling for the benched config.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

MFU_TARGET = 0.50  # BASELINE.json north star: >=50% MFU

# peak dense bf16 FLOP/s by TPU generation (public spec sheets)
_PEAK_BF16 = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6", 918e12), ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
)


def _peak_flops(device_kind):
    kind = device_kind.lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# leaf: the actual measurement (runs in a subprocess)
# ---------------------------------------------------------------------------

def _leaf(platform):
    import jax

    # persistent compile cache: the axon tunnel compiles remotely and a
    # cold ResNet-50 train-step compile can take many minutes; cached
    # executables make every later bench run (and the driver's round-end
    # run) start hot
    # separate cache dirs: the axon tunnel compiles remotely, and its
    # cached XLA:CPU AOT artifacts carry that host's machine features —
    # loading them locally risks SIGILL (observed warning) and silent
    # slow paths
    cache = ".jax_cache_cpu" if platform == "cpu" else ".jax_cache"
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(REPO, cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        bs, iters, image = 8, 2, 112
    else:
        bs, iters, image = 128, 30, 224

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import data_parallel

    dev = jax.devices()[0]
    mx.random.seed(0)
    np.random.seed(0)

    # NHWC: channel on the minormost (128-lane) tile dim — conv relayouts
    # and per-channel BN reductions are dramatically cheaper than NCHW
    # (profiled; the reference's perf guide likewise prescribes NHWC+fp16
    # for tensor cores, docs/faq/perf.md)
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize(mx.init.Xavier())
    # bf16 compute (fp32 master params) on the TPU: the MXU runs bf16 at
    # full rate and fp32 at ~1/4; the reference's headline numbers are
    # likewise mixed-precision (fp16 + fp32 master, docs/faq/perf.md)
    compute_dtype = "bfloat16" if platform != "cpu" else None
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        compute_dtype=compute_dtype)

    x = np.random.rand(bs, image, image, 3).astype(np.float32)
    y = np.random.randint(0, 1000, bs).astype(np.float32)

    # warmup / compile (several steps: the first executions through the
    # device tunnel run well below steady state). The CPU fallback skips
    # the eager-step warmup entirely — step_many() builds its own scanned
    # executable, and compiling the single-step one too nearly doubles
    # the ResNet-50 CPU compile time (this is what blew the 900s leaf
    # timeout when the TPU was down)
    if platform != "cpu":
        trainer.step(x, y).wait_to_read()
        for _ in range(5):
            trainer.step(x, y)
        trainer.step(x, y).asnumpy()

    # pre-stage the synthetic batch on device (benchmark_score.py
    # --benchmark 1 semantics: measure compute, not the host feed; the
    # input pipeline's async H2D overlap is exercised by the IO tests)
    from mxnet_tpu.ndarray.ndarray import _wrap as _nd_wrap

    sharding = data_parallel.mesh_mod.batch_sharding(trainer.mesh)
    x_dev = _nd_wrap(jax.device_put(x, sharding))
    y_dev = _nd_wrap(jax.device_put(y, sharding))

    # step FLOPs from the lowered computation's own cost analysis
    # (Lowered.cost_analysis is HLO-level — no second backend compile;
    # the warmup above already built the executable the timed loop uses)
    flops_per_step = None
    try:
        import jax.numpy as jnp

        from mxnet_tpu import random as _random

        trainer.build(x)  # defines _step_fn (trace only, no XLA compile)
        lowered = trainer._step_fn.lower(
            trainer._params, trainer._states,
            jnp.asarray(x), jnp.asarray(y), _random.next_key(),
            jnp.asarray(0.1, jnp.float32), jnp.asarray(3.0, jnp.float32))
        cost = lowered.cost_analysis()
        if cost:
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            flops_per_step = float(c.get("flops", 0.0)) or None
    except Exception:
        pass
    if flops_per_step is None:
        # analytic fallback: ResNet-50 fwd ~= 4.09 GFLOP/img at 224^2,
        # scaled by image area; training ~= 3x forward
        flops_per_step = 3 * 4.089e9 * (image / 224.0) ** 2 * bs

    # bulk execution: all `iters` steps run as ONE XLA computation
    # (lax.scan over the step body — the MXNET_EXEC_BULK_EXEC_TRAIN
    # equivalent), so per-dispatch tunnel latency is out of the timed
    # path entirely; warm up the scanned executable first
    trainer.step_many(x_dev, y_dev, n_steps=iters).asnumpy()
    # best of 3 windows: the device tunnel has large run-to-run variance,
    # and the sustained-best window is the honest compute capability
    # (each window ends with a full device round trip, not a ready-signal)
    dt = None
    for _ in range(3 if platform != "cpu" else 1):
        t0 = time.perf_counter()
        loss = trainer.step_many(x_dev, y_dev, n_steps=iters)
        loss.asnumpy()
        w = time.perf_counter() - t0
        dt = w if dt is None or w < dt else dt
    ips = iters * bs / dt
    loss = loss[-1]

    # flops_per_step covers the GLOBAL batch over the whole dp mesh, so
    # peak must be the aggregate of every chip the step ran on
    chip_peak = _peak_flops(dev.device_kind) \
        if dev.platform != "cpu" else None
    n_chips = len(trainer.mesh.devices.flat)
    peak = chip_peak * n_chips if chip_peak else None
    mfu = (flops_per_step * iters / dt / peak) if peak else None

    # eager per-op dispatch overhead (SURVEY §3.1 hot-loop risk)
    from mxnet_tpu import nd

    a = nd.ones((8, 8))
    b = nd.ones((8, 8))
    (a + b).wait_to_read()  # compile/cache
    n_ops = 300
    t0 = time.perf_counter()
    for _ in range(n_ops):
        c = a + b
    c.wait_to_read()
    eager_us = (time.perf_counter() - t0) / n_ops * 1e6

    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(mfu / MFU_TARGET, 4) if mfu else 0.0,
        "mfu": round(mfu, 4) if mfu else None,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "batch_size": bs,
        "image_size": image,
        "compute_dtype": compute_dtype or "float32",
        "flops_per_step": flops_per_step,
        # bandwidth roofline: ~32 GB HBM traffic per step (profiled;
        # see module docstring) at ~819 GB/s on v5e bounds MFU near
        # 20% for this model+config — the honest ceiling to compare
        # the measured MFU against.  Only reported for the profiled
        # config (v5e-class chip, bs=128, 224^2); other chips/configs
        # have different traffic/BW ratios
        "roofline_mfu_bound": 0.20 if (platform != "cpu" and
                                       "v5 lite" in dev.device_kind.lower()
                                       and bs == 128 and image == 224)
                              else None,
        "eager_us_per_op": round(eager_us, 1),
        "final_loss": round(float(loss.asscalar()), 4),
    }))


# ---------------------------------------------------------------------------
# probe: cheap backend health check (runs in a subprocess)
# ---------------------------------------------------------------------------

def _probe():
    import jax

    ds = jax.devices()
    import jax.numpy as jnp

    y = (jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()
    assert float(y[0, 0]) == 256.0
    print(f"PROBE_OK {ds[0].platform} {ds[0].device_kind}")


# ---------------------------------------------------------------------------
# parent orchestration (never imports jax)
# ---------------------------------------------------------------------------

def _run(args, timeout):
    """Run a bench subprocess; returns (rc, stdout, stderr-tail)."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
        return p.returncode, p.stdout, p.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else \
            (e.stdout or "")
        return -1, out, f"timeout after {timeout}s"


def _last_json_line(out):
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    note = []
    # 1. health-probe the default (TPU) backend, one retry with backoff
    tpu_ok = False
    for attempt in range(2):
        rc, out, err = _run(["--probe"], timeout=180)
        if rc == 0 and "PROBE_OK" in out:
            tpu_ok = "cpu" not in out.split("PROBE_OK", 1)[1].split()[0]
            if not tpu_ok:
                note.append("probe came up on CPU (no TPU registered)")
            break
        note.append(f"probe attempt {attempt + 1} failed "
                    f"(rc={rc}): {err.strip().splitlines()[-1][:200] if err.strip() else 'no output'}")
        if attempt == 0:
            time.sleep(20)

    # 2. run the leaf bench on the healthy backend (TPU first, CPU fallback)
    result = None
    if tpu_ok:
        for attempt in range(2):  # transient tunnel faults get one retry
            # 1800s: a cold remote compile of the ResNet-50 train step
            # through the device tunnel alone can exceed 900s; the
            # persistent compile cache makes retries/reruns much faster
            rc, out, err = _run(["--leaf", "tpu"], timeout=1800)
            result = _last_json_line(out)
            if result is not None:
                break
            note.append(f"tpu leaf attempt {attempt + 1} failed (rc={rc}): "
                        f"{err.strip().splitlines()[-1][:200] if err.strip() else 'no output'}")
            if attempt == 0:
                time.sleep(15)
    if result is None:
        note.append("falling back to CPU" if not tpu_ok else
                    "tpu measurement failed; falling back to CPU")
        # a cold ResNet-50 scanned-step compile on a busy CPU host can
        # exceed 900s (observed when the TPU tunnel was down and the CPU
        # carried the round); give the fallback the same headroom
        rc, out, err = _run(["--leaf", "cpu"], timeout=2400)
        result = _last_json_line(out)
        if result is None:
            note.append(f"cpu leaf failed (rc={rc}): "
                        f"{err.strip().splitlines()[-1][:300] if err.strip() else 'no output'}")

    if result is None:
        # total failure: still print a parseable record with the cause
        result = {"metric": "resnet50_train_throughput", "value": 0.0,
                  "unit": "images/sec", "vs_baseline": 0.0}
    if note:
        result["note"] = "; ".join(note)
    print(json.dumps(result))


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe()
    elif "--leaf" in sys.argv:
        _leaf(sys.argv[sys.argv.index("--leaf") + 1])
    else:
        main()
