"""Resilience gate for `make verify` (see docs/resilience.md).

A short SUPERVISED training run must survive real injected failures and
come out bit-identical to an uninjected run:

1. an injected SIGTERM at step 3 (the PR-1 final-save hook commits a
   checkpoint, the supervisor restarts in-process and resumes);
2. an injected transient collective failure inside kvstore.pushpull
   (classified transient: bounded backoff, re-run from the last
   committed checkpoint);
3. final params bit-identical to the uninjected run — loss parity is
   implied by bit parity (params + RNG + batch sequence all replay);
4. the recovery is VISIBLE: profiler "resilience" section shows the
   restart and the transient retry;
5. with no plan armed, the fault-point hook is the module no-op and a
   hot loop of fires shows zero measurable overhead.

Runs on the CPU backend so the gate is deterministic and fast anywhere.
"""
import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, checkpoint, engine, gluon  # noqa: E402
from mxnet_tpu import pipeline, profiler, resilience  # noqa: E402
from mxnet_tpu.analysis import runtime as lock_order  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402

# 6: the whole chaos rehearsal runs under the runtime lock-order
# checker (docs/static-analysis.md): every lock created from here on
# is order-tracked per thread, module-global locks are rebound in
# place, and one observed inversion anywhere (batcher, checkpoint
# readback, supervisor watchdog, prefetch lanes) fails the gate.
# Record-don't-raise: an inversion raised inside a library background
# thread would kill that worker mid-protocol and turn the report into
# a hang; assert_clean() at the end surfaces everything observed.
lock_order.enable(raise_on_inversion=False)
N_WRAPPED = lock_order.wrap_existing()

FEAT, BS, N = 4, 4, 48
KILL_STEP, TRANSIENT_HIT = 3, 8


def make_data():
    rng = np.random.RandomState(0)
    return [(rng.rand(FEAT).astype(np.float32), np.float32(i % 2))
            for i in range(N)]


def build_model():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=FEAT, activation="relu"),
            nn.Dense(1, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    # dist_sync + local update keeps kvstore.pushpull on the step path
    # (single-process dist degrades to device semantics)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            kvstore="dist_sync", update_on_kvstore=False)
    return net, trainer


def supervised_run(ckdir, plan=None):
    if plan is not None:
        resilience.install_plan(plan)
    try:
        mgr = checkpoint.CheckpointManager(ckdir, keep_n=3)
        sup = resilience.Supervisor(
            mgr, on_preemption="resume", max_restarts=3,
            retry=resilience.RetryPolicy(max_retries=3, base_delay=0.01))
        data = make_data()
        losses = {}

        def train(ctx):
            net, trainer = build_model()
            pipe = (pipeline.Pipeline(data).shuffle(8, seed=5)
                    .batch(BS, last_batch="discard"))
            start = 0
            if ctx.manager.latest() is not None:
                meta = ctx.manager.restore(params=net, trainer=trainer,
                                           pipeline=pipe)
                start = meta["step"] + 1
            cur = {"step": start - 1}
            ctx.set_preemption_state(lambda: dict(
                step=cur["step"], params=net, trainer=trainer,
                pipeline=pipe))
            step = start
            for x, y in pipe:
                with autograd.record():
                    loss = ((net(x) - y.reshape((-1, 1))) ** 2).sum()
                loss.backward()
                trainer.step(BS)
                losses[step] = float(loss.asnumpy())
                cur["step"] = step
                ctx.step_done(step, save=dict(
                    params=net, trainer=trainer, pipeline=pipe,
                    sync=True))
                step += 1
            return {k: v.data().asnumpy()
                    for k, v in net._collect_params_with_prefix().items()}

        return sup.run(train), losses
    finally:
        if plan is not None:
            resilience.clear_plan()


def main():
    # 1+2+3: uninjected vs kill+transient supervised runs, bit parity
    resilience.reset_resilience_stats()
    d_ref = tempfile.mkdtemp(prefix="chaos-smoke-ref-")
    d_chaos = tempfile.mkdtemp(prefix="chaos-smoke-")
    try:
        ref, losses_ref = supervised_run(d_ref)
        plan = resilience.FaultPlan([
            {"site": "train.step", "action": "kill",
             "match": {"step": KILL_STEP}},
            {"site": "kvstore.pushpull", "action": "raise",
             "on_hit": TRANSIENT_HIT},
        ], seed=0)
        got, losses = supervised_run(d_chaos, plan)
    finally:
        shutil.rmtree(d_ref, ignore_errors=True)
        shutil.rmtree(d_chaos, ignore_errors=True)

    fired = [(f["site"], f["action"]) for f in plan.fired()]
    assert ("train.step", "kill") in fired, fired
    assert ("kvstore.pushpull", "raise") in fired, fired
    assert ref.keys() == got.keys()
    for k in ref:
        assert np.array_equal(ref[k], got[k]), \
            f"param {k} diverged after recovery (chaos run is not " \
            "bit-identical to the clean run)"
    assert losses == losses_ref, "per-step loss sequence diverged"

    # 4: recovery is visible in the profiler resilience section
    section = json.loads(profiler.dumps())["resilience"]
    assert section["restarts"] == 2, section            # kill + transient
    assert section["retries"].get("preemption") == 1, section
    assert section["retries"].get("transient") == 1, section
    assert section["time_lost_ms"] > 0, section

    # 5: no plan armed -> the hook IS the no-op, with zero measurable
    # overhead on a hot loop
    assert engine.fault_point is engine._fault_noop
    fire = engine.fault_point
    t0 = time.perf_counter()
    for _ in range(200_000):
        fire("kvstore.pushpull")
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"disarmed fault point cost {dt:.3f}s / 200k fires"

    # 6: zero lock-order inversions observed across both supervised
    # runs (kill/restart, transient retry, async checkpoint capture)
    lock_order.assert_clean()
    lk = lock_order.stats()
    assert lk["acquires"] > 0, "lock-order checker saw no acquisitions"

    print(f"CHAOS_SMOKE_OK steps={len(losses_ref)} "
          f"restarts={section['restarts']} "
          f"retries={section['retries']} "
          f"time_lost_ms={section['time_lost_ms']:.1f} "
          f"final_loss={losses_ref[max(losses_ref)]:.4f} "
          f"disarmed_overhead_ns={dt / 200_000 * 1e9:.0f} "
          f"lock_sites={lk['sites']} lock_edges={lk['edges']} "
          f"lock_inversions={lk['inversions']} "
          f"wrapped_module_locks={N_WRAPPED}")


if __name__ == "__main__":
    main()
