#!/bin/bash
# Tunnel watcher: probe the axon TPU tunnel; the moment it is healthy,
# run the full on-chip battery in priority order (bench first — the
# headline numbers four rounds of VERDICTs have demanded), logging
# everything under bench_logs/.  Exits when the battery completes.
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_logs

probe() {
    timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
import jax.numpy as jnp
assert float(jnp.ones((8, 8)).sum()) == 64.0
EOF
}

echo "$(date -u +%H:%M:%S) watcher start"
while true; do
    if probe; then
        echo "$(date -u +%H:%M:%S) tunnel HEALTHY — battery begins"

        echo "$(date -u +%H:%M:%S) [1/6] bench.py"
        timeout 3600 python bench.py \
            > bench_logs/bench_tpu.json 2> bench_logs/bench_tpu.err
        echo "rc=$? $(tail -c 400 bench_logs/bench_tpu.json)"

        echo "$(date -u +%H:%M:%S) [2/6] preflight"
        timeout 2400 python tools/preflight.py --markdown \
            > bench_logs/preflight.md 2> bench_logs/preflight.err
        echo "rc=$?"

        echo "$(date -u +%H:%M:%S) [3/6] tpu smoke -v"
        MXTPU_TEST_PLATFORM=tpu timeout 2400 python -m pytest \
            tests/test_tpu_smoke.py -v --tb=short \
            > bench_logs/smoke.txt 2>&1
        echo "rc=$? $(tail -1 bench_logs/smoke.txt)"

        echo "$(date -u +%H:%M:%S) [4/6] workloads transformer+deepar"
        timeout 2400 python tools/bench_workloads.py transformer \
            > bench_logs/wl_transformer.json 2>&1
        echo "rc=$?"
        timeout 1800 python tools/bench_workloads.py deepar \
            > bench_logs/wl_deepar.json 2>&1
        echo "rc=$?"

        echo "$(date -u +%H:%M:%S) [5/6] convfuse + quantized + io"
        timeout 2400 python tools/bench_workloads.py convfuse \
            > bench_logs/wl_convfuse.json 2>&1
        echo "rc=$?"
        timeout 1800 python tools/bench_workloads.py quantized \
            > bench_logs/wl_quantized.json 2>&1
        echo "rc=$?"
        timeout 1800 python tools/bench_workloads.py io \
            > bench_logs/wl_io.json 2>&1
        echo "rc=$?"

        echo "$(date -u +%H:%M:%S) [6/6] bandwidth"
        timeout 900 python tools/bandwidth.py \
            > bench_logs/bandwidth.json 2>&1
        echo "rc=$?"

        echo "$(date -u +%H:%M:%S) battery COMPLETE"
        # only stand down if the headline actually measured on TPU;
        # a tunnel that died mid-battery leaves a CPU-fallback record
        # and the next healthy window should retry
        if grep -q '"platform": "tpu"' bench_logs/bench_tpu.json \
                2>/dev/null; then
            echo "$(date -u +%H:%M:%S) TPU numbers captured — done"
            exit 0
        fi
        echo "$(date -u +%H:%M:%S) bench fell back to CPU — re-arming"
        mv bench_logs/bench_tpu.json \
           "bench_logs/bench_cpu_fallback.$(date -u +%H%M%S).json" \
           2>/dev/null
    fi
    echo "$(date -u +%H:%M:%S) tunnel down; retry in 180s"
    sleep 180
done
