"""On-TPU Mosaic validation smoke list (VERDICT r3 #2).

Compiles (value-and-grad, f32 AND bf16) every Pallas kernel family on
the real chip: flash attention (d=128 and the d%64 tiling, causal and
key-padding-masked), all three conv-fused epilogue kernels + bn_stats,
and the LSTM recurrence.  SKIPS off-TPU — interpret mode can't catch
Mosaic lowering failures; this file is the first thing to run when a
chip session opens (`pytest tests/test_tpu_smoke.py -v`).
"""
import jax
import jax.numpy as jnp
import pytest


def _backend():
    try:
        return jax.default_backend()
    except RuntimeError:  # backend init failed (e.g. tunnel down)
        return "unavailable"


pytestmark = pytest.mark.skipif(
    _backend() != "tpu",
    reason="Mosaic lowering is only real on TPU")


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("d,causal,masked", [
    (128, False, False), (128, True, False), (128, False, True),
    (64, False, False), (64, True, False), (64, False, True),
])
def test_flash_attention_compiles(dt, d, causal, masked):
    from mxnet_tpu.ops.pallas.flash_attention import _flash_sdpa

    q = jnp.zeros((1, 2, 256, d), dt)
    km = jnp.zeros((1, 256), jnp.float32) if masked else None

    def loss(a):
        return _flash_sdpa(a, a, a, km, causal, 0.125) \
            .astype(jnp.float32).sum()

    jax.jit(jax.grad(loss)).lower(q).compile()


@pytest.mark.parametrize("dt,causal", [
    (jnp.bfloat16, False), (jnp.bfloat16, True), (jnp.float32, True)],
    ids=["bf16", "bf16-causal", "f32-causal"])
def test_flash_streamed_compiles(dt, causal):
    """Streamed long-KV flash attention (seq 16k, past the resident
    VMEM bound) value-and-grad on the real chip."""
    from mxnet_tpu.ops.pallas.flash_attention import _flash_sdpa

    q = jnp.zeros((1, 1, 16384, 128), dt)

    def loss(a):
        return _flash_sdpa(a, a, a, None, causal, 0.125) \
            .astype(jnp.float32).sum()

    jax.jit(jax.grad(loss)).lower(q).compile()


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_conv_fused_kernels_compile(dt):
    from mxnet_tpu.ops.pallas import batch_norm as pbn
    from mxnet_tpu.ops.pallas import conv_fused as cf

    x = jnp.zeros((512, 256), dt)
    w = jnp.zeros((256, 256), dt)
    sc = jnp.zeros((1, 256), dt)
    sh = jnp.zeros((1, 256), dt)
    jax.jit(jax.grad(lambda a: cf.matmul_bn_stats(a, w)[0]
                     .astype(jnp.float32).sum())).lower(x).compile()
    jax.jit(jax.grad(lambda a: cf.bn_act_matmul(a, sc, sh, w)
                     .astype(jnp.float32).sum())).lower(x).compile()
    jax.jit(jax.grad(lambda a: cf.bn_act_matmul_stats(a, sc, sh, w)[0]
                     .astype(jnp.float32).sum())).lower(x).compile()
    jax.jit(jax.grad(lambda a: pbn.bn_stats(a)[0]
                     .astype(jnp.float32).sum())).lower(x).compile()


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_pallas_lstm_compiles(dt):
    from mxnet_tpu.ops.pallas.rnn import lstm_layer

    T, N, H = 4, 16, 128
    xp = jnp.zeros((T, N, 4 * H), dt)
    wh = jnp.zeros((4 * H, H), dt)
    h0 = jnp.zeros((N, H), dt)
    c0 = jnp.zeros((N, H), dt)
    jax.jit(jax.grad(lambda a: lstm_layer(a, wh, h0, c0)[0]
                     .astype(jnp.float32).sum())).lower(xp).compile()


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_pallas_gru_compiles(dt):
    from mxnet_tpu.ops.pallas.rnn import gru_layer

    T, N, H = 4, 16, 128
    xp = jnp.zeros((T, N, 3 * H), dt)
    wh = jnp.zeros((3 * H, H), dt)
    bh = jnp.zeros((3 * H,), dt)
    h0 = jnp.zeros((N, H), dt)
    jax.jit(jax.grad(lambda a: gru_layer(a, wh, bh, h0)[0]
                     .astype(jnp.float32).sum())).lower(xp).compile()


def test_cpu_oracle_consistency_on_chip():
    """The reference's single most important test idea (SURVEY §4:
    check_consistency CPU-vs-GPU) on real hardware: the same ops on
    XLA:CPU and the TPU must agree within dtype tolerance.  Covers the
    op families the five workloads lean on."""
    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu.test_utils import check_consistency

    rng = np.random.RandomState(0)
    x = rng.rand(4, 8, 14, 14).astype(np.float32)
    w = (rng.rand(16, 8, 3, 3).astype(np.float32) - 0.5) * 0.2
    m = rng.rand(32, 64).astype(np.float32)
    n = rng.rand(64, 48).astype(np.float32)
    # MXU-backed contractions at DEFAULT precision round f32 operands
    # to bf16 passes (eps ~8e-3) — the tolerance users actually get
    check_consistency(
        lambda a, b: nd.Convolution(a, b, kernel=(3, 3), num_filter=16,
                                    no_bias=True),
        [x, w], rtol=2e-2, atol=2e-2)
    check_consistency(lambda a, b: nd.dot(a, b), [m, n],
                      rtol=2e-2, atol=2e-2)
    # with highest precision forced, the oracle must match tightly
    with jax.default_matmul_precision("highest"):
        check_consistency(
            lambda a, b: nd.Convolution(a, b, kernel=(3, 3),
                                        num_filter=16, no_bias=True),
            [x, w], rtol=1e-3, atol=1e-4)
        check_consistency(lambda a, b: nd.dot(a, b), [m, n],
                          rtol=1e-3, atol=1e-4)
    # VPU paths (no MXU contraction): tight at default precision
    s = rng.rand(4, 128).astype(np.float32)
    check_consistency(lambda a: nd.softmax(a), [s])
    check_consistency(lambda a: nd.LayerNorm(
        a, nd.ones((128,), ctx=a.context),
        nd.zeros((128,), ctx=a.context)), [s], rtol=1e-3, atol=1e-3)


def _make_resnet50():
    import numpy as np

    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1(layout="NHWC")
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    return net, (x,)


def _make_bert_block():
    import numpy as np

    from mxnet_tpu.models.bert import BERTEncoderLayer

    net = BERTEncoderLayer(units=128, hidden_size=512, num_heads=4)
    x = np.random.RandomState(0).rand(2, 16, 128).astype(np.float32)
    return net, (x,)


def _make_transformer_layer():
    import numpy as np

    from mxnet_tpu.models.transformer import TransformerLayer

    net = TransformerLayer(units=128, hidden_size=512, num_heads=4,
                           dropout=0.0)
    x = np.random.RandomState(0).rand(2, 16, 128).astype(np.float32)
    return net, (x,)


def _make_deepar_cell():
    import numpy as np

    from mxnet_tpu.gluon import rnn as grnn

    net = grnn.LSTM(40, num_layers=2)
    x = np.random.RandomState(0).rand(12, 2, 8).astype(np.float32)
    return net, (x,)


@pytest.mark.parametrize("family", ["resnet50", "bert_block",
                                    "transformer_layer", "deepar_cell"])
def test_whole_model_cpu_oracle_on_chip(family):
    """Whole hybridized models, one per workload family, TPU vs the
    XLA:CPU oracle (SURVEY §4 'the single most important test idea';
    VERDICT r4 #8): the op-level tier above localizes a divergence,
    THIS tier proves the composed models the benches time agree
    end to end."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.context import cpu
    from mxnet_tpu.test_utils import assert_almost_equal

    net, xs = {"resnet50": _make_resnet50,
               "bert_block": _make_bert_block,
               "transformer_layer": _make_transformer_layer,
               "deepar_cell": _make_deepar_cell}[family]()
    mx.random.seed(7)
    net.initialize(mx.init.Xavier())
    net.hybridize()

    out_tpu = net(*[nd.array(x) for x in xs]).asnumpy()
    net.collect_params().reset_ctx(cpu())
    out_cpu = net(*[nd.array(x, ctx=cpu()) for x in xs])
    if isinstance(out_cpu, (tuple, list)):
        out_cpu = out_cpu[0]
    out_cpu = out_cpu.asnumpy()
    # MXU contractions round operands to bf16 at default precision;
    # depth compounds it (50 layers of it for resnet), so the gate is
    # the bf16-scale tolerance users actually get
    assert_almost_equal(out_cpu, np.asarray(out_tpu), rtol=3e-2,
                        atol=3e-2, names=("cpu-oracle", "tpu"))


def test_probe_gates_report_on_chip():
    """The family gates themselves: on a healthy chip every probe
    should come back True (a False here IS the signal the kernels
    can't lower — the XLA fallback keeps training alive)."""
    from mxnet_tpu.ops.pallas.conv_fused import _use_pallas
    from mxnet_tpu.ops.pallas.flash_attention import _headdim64_allowed
    from mxnet_tpu.ops.rnn import _use_pallas_lstm

    verdicts = {"conv_fused": _use_pallas(),
                "rnn": _use_pallas_lstm(),
                "flash_headdim64": _headdim64_allowed()}
    print(f"pallas probe verdicts: {verdicts}")
    # report, don't fail: a False verdict means the gate did its job
    assert all(isinstance(v, bool) for v in verdicts.values())
