"""Automatic mixed precision (ref: python/mxnet/contrib/amp/ — fp16
cast lists + dynamic loss scaling).

TPU-native: the low-precision dtype is bfloat16, which shares float32's
exponent range — so dynamic loss scaling is unnecessary (kept as an
always-1 scaler for API parity).  ``init()`` flips matmul/conv-heavy
ops to bf16 accumulation by casting block parameters; ``convert_model``
casts a whole Gluon block.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

_initialized = False
_target_dtype = "bfloat16"

# ops that benefit from low precision (MXU-bound) — ref: amp FP16_FUNCS
TARGET_DTYPE_OPS = ["FullyConnected", "Convolution", "Deconvolution",
                    "batch_dot", "dot", "RNN",
                    "scaled_dot_product_attention",
                    "multihead_attention"]
# ops that must stay fp32 (ref: FP32_FUNCS)
FP32_OPS = ["softmax", "log_softmax", "BatchNorm", "LayerNorm", "norm",
            "mean", "sum", "SoftmaxOutput", "exp", "log"]


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Ref: amp.init() — on TPU this records the policy; casting happens
    per-model via convert_model/convert_hybrid_block."""
    global _initialized, _target_dtype
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _target_dtype = target_dtype
    _initialized = True


def convert_model(block, target_dtype=None):
    """Cast a Gluon block's parameters to the AMP dtype, keeping
    normalization params in fp32 (the reference's cast-list split)."""
    dt = target_dtype or _target_dtype
    for name, p in block.collect_params().items():
        stem = name.rsplit("_", 1)[-1]
        if stem in ("gamma", "beta", "running_mean", "running_var",
                    "moving_mean", "moving_var"):
            continue
        p.cast(dt)
    if hasattr(block, "_clear_cache"):
        block._clear_cache()
    return block


convert_hybrid_block = convert_model


class LossScaler:
    """API-parity loss scaler; bf16 needs no scaling (scale always 1)."""

    def __init__(self, init_scale=1.0, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = 1.0

    def scale(self, loss):
        return loss

    def unscale(self, grads):
        return grads

    def update(self, overflow=False):
        return False


def scale_loss(loss, trainer):
    """Context manager parity shim (ref: amp.scale_loss)."""
    class _Noop:
        def __enter__(self):
            return loss

        def __exit__(self, *a):
            return False

    return _Noop()
