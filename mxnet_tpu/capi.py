"""Embedded-orchestrator helpers for the flat C ABI (src/c_api.cc).

The C library hosts a CPython interpreter (DESIGN.md "C ABI" section:
the deliberate inversion of the reference's native-core/Python-shell
layering).  Every trainable-surface entry point — symbol compose,
executor bind/forward/backward, CachedOp, optimizer update, data
iterators, kvstore — lands here as a flat function taking/returning
plain Python objects; the C side only marshals handles (PyObject*) and
scalars.  Keeping the logic on this side keeps src/c_api.cc a thin,
auditable FFI layer.

Ref (behavioral parity): include/mxnet/c_api.h — MXSymbolCreateAtomic
Symbol/MXSymbolCompose, MXExecutorBindEX/Forward/Backward,
MXCreateCachedOpEx/MXInvokeCachedOpEx, MXOptimizerCreateOptimizer/
MXOptimizerUpdate (pre-1.0 surface; later frontends ride KVStore),
MXDataIterCreateIter/Next, MXKVStoreInit/Push/Pull.
"""
from __future__ import annotations

import ast

from . import autograd as _autograd  # noqa: F401  (C side reaches it here)
from . import io as _io
from . import kvstore as _kvstore_mod
from . import optimizer as _optimizer_mod
from .base import MXNetError
from .context import Context
from .ndarray import ndarray as _nd_mod
from .symbol import symbol as _symbol_mod


def _parse_val(v):
    """The reference C API's stringly-typed kwarg convention: values
    arrive as strings and parse as Python literals, falling back to the
    raw string ("(2,2)" -> tuple, "relu" -> "relu")."""
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def _kwargs(keys, vals):
    return {k: _parse_val(v) for k, v in zip(keys, vals)}


def _parse_ctx(ctx):
    if not ctx:
        return None
    dev, _, idx = ctx.partition("(")
    return Context(dev, int(idx.rstrip(")")) if idx else 0)


# ---------------------------------------------------------------------------
# Symbol (ref: MXSymbolCreateVariable / CreateAtomicSymbol + Compose)


def symbol_variable(name):
    return _symbol_mod.var(name)


def symbol_invoke(op_name, inputs, input_keys, attr_keys, attr_vals,
                  name):
    """Atomic-symbol creation + composition in one call: positional
    ``inputs`` (or keyword, via parallel ``input_keys``) are parent
    symbols; attrs are the op's stringly-typed params."""
    fn = getattr(_symbol_mod, op_name, None)
    if fn is None or not callable(fn):
        raise MXNetError(f"unknown op for symbol_invoke: {op_name}")
    kwargs = _kwargs(attr_keys, attr_vals)
    if name:
        kwargs["name"] = name
    args = []
    if input_keys:
        for k, s in zip(input_keys, inputs):
            kwargs[k] = s
    else:
        args = list(inputs)
    return fn(*args, **kwargs)


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_infer_shape(sym, known_names, known_shapes):
    """Ref: MXSymbolInferShape.  Returns (arg_shapes, aux_shapes) as
    tuples aligned with list_arguments / list_auxiliary_states."""
    kw = {n: tuple(s) for n, s in zip(known_names, known_shapes)}
    arg_shapes, _out_shapes, aux_shapes = sym.infer_shape(**kw)
    return list(arg_shapes), list(aux_shapes)


def symbol_tojson(sym):
    return sym.tojson()


def symbol_fromjson(js):
    return _symbol_mod.fromjson(js)


# ---------------------------------------------------------------------------
# Executor (ref: MXExecutorBindEX / Forward / Backward / Outputs)


def executor_bind(sym, ctx, args, grad_req, auxs):
    """Bind with args (list, ``list_arguments`` order) and aux states
    (``list_auxiliary_states`` order).  ``grad_req`` is one req for all
    args or a comma-separated per-arg list (the MXExecutorBindEX
    per-arg form — lets data/label bind as 'null' so backward doesn't
    compute input gradients nobody reads).  Gradient buffers are
    allocated here (zeros) for every non-'null' arg; the caller reads
    them back per-name after backward."""
    ctx = _parse_ctx(ctx) or Context.default_ctx()
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    if len(args) != len(arg_names):
        raise MXNetError(
            f"executor_bind: {len(arg_names)} args required "
            f"({arg_names}), got {len(args)}")
    if len(auxs) != len(aux_names):
        raise MXNetError(
            f"executor_bind: {len(aux_names)} aux states required, "
            f"got {len(auxs)}")
    grad_req = grad_req or "null"
    if "," in grad_req:
        reqs = [r.strip() for r in grad_req.split(",")]
        if len(reqs) != len(arg_names):
            raise MXNetError(
                f"executor_bind: per-arg grad_req has {len(reqs)} "
                f"entries for {len(arg_names)} arguments")
        req_map = dict(zip(arg_names, reqs))
    else:
        req_map = {n: grad_req for n in arg_names}
    args_grad = None
    if any(r != "null" for r in req_map.values()):
        args_grad = {n: _nd_mod.zeros(a.shape, dtype=a.dtype, ctx=ctx)
                     for n, a in zip(arg_names, args)
                     if req_map[n] != "null"}
    return sym.bind(ctx, args=list(args), args_grad=args_grad,
                    grad_req=req_map, aux_states=list(auxs) or None)


def executor_forward(ex, is_train):
    return list(ex.forward(is_train=bool(is_train)))


def executor_backward(ex, out_grads):
    ex.backward(out_grads=list(out_grads) if out_grads else None)


def executor_arg_grad(ex, name):
    g = ex.grad_dict.get(name)
    if g is None:
        raise MXNetError(f"no gradient buffer for argument {name!r}")
    return g


# ---------------------------------------------------------------------------
# CachedOp (ref: MXCreateCachedOpEx / MXInvokeCachedOpEx): the whole
# graph runs as ONE XLA computation per (shapes, train) key — the same
# machinery gluon hybridize rides (symbol/_graph_fn + the jitted-
# executable cache), exposed over a flat handle.


class CApiCachedOp:
    def __init__(self, sym):
        self.sym = sym
        self.arg_names = sym.list_arguments()
        self.aux_names = sym.list_auxiliary_states()
        self._ex = None
        self._n_in = len(self.arg_names) + len(self.aux_names)

    def invoke(self, arrays, is_train):
        if len(arrays) != self._n_in:
            raise MXNetError(
                f"CachedOp: expects {len(self.arg_names)} args + "
                f"{len(self.aux_names)} aux = {self._n_in} inputs, "
                f"got {len(arrays)}")
        n_args = len(self.arg_names)
        args, auxs = arrays[:n_args], arrays[n_args:]
        if self._ex is None:
            ctx = args[0].context if args else Context.default_ctx()
            self._ex = self.sym.bind(ctx, args=list(args),
                                     grad_req="null",
                                     aux_states=list(auxs) or None)
        else:
            for name, a in zip(self.arg_names, args):
                self._ex.arg_dict[name] = a
            for name, a in zip(self.aux_names, auxs):
                self._ex.aux_dict[name] = a
        return list(self._ex.forward(is_train=bool(is_train)))


def cachedop_create(sym):
    return CApiCachedOp(sym)


def cachedop_invoke(op, arrays, is_train):
    return op.invoke(list(arrays), is_train)


# ---------------------------------------------------------------------------
# Optimizer (ref: MXOptimizerCreateOptimizer/MXOptimizerUpdate; state
# per index managed server-side exactly like KVStoreDistServer does)


class CApiOptimizer:
    def __init__(self, name, kwargs):
        self.opt = _optimizer_mod.create(name, **kwargs)
        self.states = {}

    def update(self, index, weight, grad):
        if index not in self.states:
            self.states[index] = self.opt.create_state_multi_precision(
                index, weight)
        self.opt.update_multi_precision(index, weight, grad,
                                        self.states[index])


def optimizer_create(name, keys, vals):
    return CApiOptimizer(name, _kwargs(keys, vals))


def optimizer_update(opt, index, weight, grad):
    opt.update(index, weight, grad)


# ---------------------------------------------------------------------------
# Data iterators (ref: MXDataIterCreateIter by registry name /
# MXDataIterNext / GetData / GetLabel / BeforeFirst)


class CApiDataIter:
    def __init__(self, name, kwargs):
        cls = getattr(_io, name, None)
        if cls is None or not isinstance(cls, type):
            raise MXNetError(f"unknown data iterator: {name}")
        self.it = cls(**kwargs)
        self.batch = None

    def next(self):
        try:
            self.batch = self.it.next()
            return True
        except StopIteration:
            self.batch = None
            return False

    def data(self):
        if self.batch is None:
            raise MXNetError("no current batch (call next first)")
        return self.batch.data[0]

    def label(self):
        if self.batch is None:
            raise MXNetError("no current batch (call next first)")
        return self.batch.label[0]

    def reset(self):
        self.it.reset()
        self.batch = None


def dataiter_create(name, keys, vals):
    return CApiDataIter(name, _kwargs(keys, vals))


def dataiter_next(it):
    return it.next()


def dataiter_data(it):
    return it.data()


def dataiter_label(it):
    return it.label()


def dataiter_reset(it):
    it.reset()


# ---------------------------------------------------------------------------
# KVStore (ref: MXKVStoreCreate/Init/Push/Pull — int keys, the classic
# worker protocol)


def kvstore_create(type_str):
    return _kvstore_mod.create(type_str or "local")


def kvstore_init(kv, keys, vals, priority=0):
    # priority accepted (and ignored) so the C side can marshal init/
    # push/pull through one keyed-call path
    for k, v in zip(keys, vals):
        kv.init(int(k), v)


def kvstore_push(kv, keys, vals, priority):
    for k, v in zip(keys, vals):
        kv.push(int(k), v, priority=priority)


def kvstore_pull(kv, keys, outs, priority):
    for k, o in zip(keys, outs):
        kv.pull(int(k), out=o, priority=priority)


def kvstore_pushpull(kv, keys, vals, outs, priority):
    """Fused push+pull (ref: MXKVStorePushPullEx) — the all-reduce
    spelling Trainer.step uses."""
    for k, v, o in zip(keys, vals, outs):
        kv.pushpull(int(k), v, out=o, priority=priority)


# ---------------------------------------------------------------------------
# NDArray view/transform helpers (ref: MXNDArrayReshape64 / MXNDArraySlice)


def ndarray_reshape(arr, shape):
    return arr.reshape(tuple(int(d) for d in shape))


def ndarray_slice(arr, begin, end):
    # dim-0 slice, the MXNDArraySlice contract
    return arr[int(begin):int(end)]
