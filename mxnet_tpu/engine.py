"""Async execution engine facade.

Ref: src/engine/threaded_engine.{h,cc}, naive_engine.cc, and
include/mxnet/engine.h (Engine::PushAsync / WaitForVar / WaitForAll).

TPU-native design: XLA/PjRt dispatch is already asynchronous — every
``jax.Array`` is a future and data dependencies between ops are enforced
by construction (an op consuming a buffer waits on that buffer's
producer).  That is exactly the guarantee the reference's ThreadedVar
RAW/WAR/WAW state machine provides, so the 5k-line C++ scheduler shrinks
to: (a) a *naive/sync* mode toggle for debugging (ref: NaiveEngine via
MXNET_ENGINE_TYPE), (b) ``waitall``/``wait_to_read`` barriers over live
buffers, and (c) a host-side thread pool used by the IO prefetcher.
"""
from __future__ import annotations

import atexit
import concurrent.futures
import contextlib
import threading

from .base import getenv

# Live-buffer tracking for waitall(): a WeakSet would never extend
# lifetimes, but WeakSet.add costs ~4us/op (guard logic in
# _weakrefset.py), a large slice of the eager per-op budget.  The hot
# path appends strong refs to a plain list instead (~0.1us) and
# amortizes cleanup: once the list passes _COMPACT_AT entries, ready
# buffers are dropped in place (is_ready() is a cheap PjRt C++ call).
# A ready buffer is thus pinned for at most _COMPACT_AT dispatches
# beyond its natural lifetime; pending buffers are pinned by the
# runtime anyway.  In-place del (never rebinding) keeps concurrent
# appends from other threads safe; _compact_mu serializes compactors.
_live_fast = []
_COMPACT_AT = 64
_compact_mu = threading.Lock()

# 'ThreadedEngine' (async, default) or 'NaiveEngine' (every op synchronous)
_engine_type = getenv("ENGINE_TYPE", "ThreadedEngine")


def engine_type():
    return _engine_type


def set_engine_type(name):
    """Switch between async ('ThreadedEngine') and sync ('NaiveEngine')."""
    global _engine_type
    assert name in ("ThreadedEngine", "NaiveEngine"), name
    _engine_type = name


def is_naive():
    return _engine_type == "NaiveEngine"


def track(jarr):
    """Register a device buffer so waitall() can block on it."""
    if _engine_type == "NaiveEngine":
        try:
            jarr.block_until_ready()
        except AttributeError:
            pass
        return jarr
    _live_fast.append(jarr)
    if len(_live_fast) > _COMPACT_AT:
        _compact_live()
    return jarr


def _compact_live():
    """Drop already-computed buffers from the fast tracking list."""
    if not _compact_mu.acquire(blocking=False):
        return  # another thread is compacting
    try:
        for idx in range(len(_live_fast) - 1, -1, -1):
            try:
                done = _live_fast[idx].is_ready()
            except Exception:
                done = True  # deleted/donated/non-array: nothing to await
            if done:
                del _live_fast[idx]
    finally:
        _compact_mu.release()


def _block_on(arr):
    try:
        arr.block_until_ready()
    except AttributeError:
        pass
    except RuntimeError as e:
        msg = str(e).lower()
        if "deleted" not in msg and "donated" not in msg:
            raise


def waitall():
    """Block until all outstanding device work completes.

    Ref: Engine::WaitForAll / mx.nd.waitall() — this is the barrier that
    surfaces async execution errors, so real failures must propagate;
    only already-freed buffers (deleted/donated) are skipped.
    """
    if _native is not None:
        _native.wait_all()
    while _live_fast:
        try:
            arr = _live_fast.pop()
        except IndexError:  # concurrent waitall drained it first
            break
        _block_on(arr)


def wait_for_var(jarr):
    """Ref: Engine::WaitForVar — block on one buffer."""
    jarr.block_until_ready()


# ---------------------------------------------------------------------------
# Host-side scheduling: the surviving role of the threaded engine — overlap
# host work (decode, checkpoint, H2D staging) with device steps.  Backed by
# the native C++ dependency engine (src/engine.cc, ThreadedVar RAW/WAR/WAW
# semantics) when built; a plain thread pool otherwise.

_pool = None
_native = None
_native_tried = False


def host_pool():
    global _pool
    if _pool is None:
        n = getenv("CPU_WORKER_NTHREADS", 4, int)
        _pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=n, thread_name_prefix="mxtpu-host-worker")
    return _pool


def native_engine():
    """The C++ threaded engine, or None when unavailable."""
    global _native, _native_tried
    if _native is None and not _native_tried:
        _native_tried = True
        try:
            from .utils import native_engine as ne
            if ne.load() is not None:
                _native = ne.NativeEngine()
                # C++ workers must not call back into Python during
                # interpreter finalization: drain + free before teardown
                # (ThreadPoolExecutor gets this via its own atexit hook).
                atexit.register(_shutdown_native)
        except Exception:
            _native = None
    return _native


def _shutdown_native():
    global _native
    if _native is not None:
        _native.close()
        _native = None


def _sync_future(fn, *args, **kwargs):
    f = concurrent.futures.Future()
    try:
        f.set_result(fn(*args, **kwargs))
    except BaseException as e:  # noqa: BLE001 - mirror future semantics
        f.set_exception(e)
    return f


def new_variable():
    """Engine var for dependency-tracked host ops (ref: NewVariable)."""
    eng = native_engine()
    assert eng is not None, "native engine unavailable"
    return eng.new_variable()


def push(fn, const_vars=(), mutable_vars=()):
    """Push host work with explicit read/write var deps (ref: PushAsync).

    The C++ engine guarantees: concurrent readers, exclusive writers,
    FIFO grants per var.  Falls back to synchronous execution when the
    native lib is missing (correct, just unoverlapped).
    """
    if is_naive():
        return push_host(fn)
    eng = native_engine()
    if eng is None:
        return _sync_future(fn)
    return eng.push(fn, const_vars, mutable_vars)


def push_host(fn, *args, **kwargs):
    """Run host-side work async (ref: Engine::PushAsync with CPU ctx)."""
    if is_naive():
        return _sync_future(fn, *args, **kwargs)
    eng = native_engine()
    if eng is not None:
        return eng.push(lambda: fn(*args, **kwargs))
    return host_pool().submit(fn, *args, **kwargs)


# ---------------------------------------------------------------------------
# Streams (ref: src/engine/stream_manager.h + mshadow Stream<gpu> in
# RunContext): per-device ordered lanes so transfers never queue behind
# unrelated work.  TPU translation: device-side ordering belongs to
# XLA/PjRt, but HOST-side lanes still matter — H2D staging, D2H
# checkpoint reads, and IO decode are independent queues that should
# overlap each other while staying FIFO within themselves.  A Stream is
# realized as one mutable engine var: the C++ engine's per-var FIFO
# grant IS the stream-order guarantee, and distinct vars give cross-
# stream parallelism.  Without the native lib, each stream degrades to
# its own single-thread executor (same contract, plain threads).


class Stream:
    """One FIFO lane. Ops pushed to the same stream run in push order;
    different streams run concurrently."""

    def __init__(self, name):
        self.name = name
        eng = native_engine()
        if eng is not None:
            self._var = eng.new_variable()
            self._exec = None
        else:
            self._var = None
            self._exec = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"mxtpu-stream-{name}")

    def push(self, fn, *args, **kwargs):
        """Enqueue fn on this lane; returns a future."""
        if is_naive():
            return _sync_future(fn, *args, **kwargs)
        if self._var is not None:
            return native_engine().push(
                lambda: fn(*args, **kwargs), (), (self._var,))
        return self._exec.submit(fn, *args, **kwargs)

    def wait(self):
        """Block until everything pushed so far has run (ref:
        Stream::Wait — a lane-local barrier, unlike waitall)."""
        self.push(lambda: None).result()


class StreamManager:
    """Per-(context, kind) stream registry (ref: StreamManager hands a
    compute + copy stream per GPU via RunContext).  Kinds: 'h2d'
    (host→device staging), 'd2h' (checkpoint/eval readback), 'io'
    (decode output ordering), 'aux' (anything else)."""

    _KINDS = ("h2d", "d2h", "io", "aux")

    def __init__(self):
        self._streams = {}
        self._mu = threading.Lock()

    def get(self, ctx=None, kind="h2d"):
        if kind not in self._KINDS:
            raise ValueError(f"unknown stream kind {kind!r}; "
                             f"valid: {self._KINDS}")
        key = (str(ctx), kind)
        with self._mu:
            s = self._streams.get(key)
            if s is None:
                s = self._streams[key] = Stream(f"{ctx}-{kind}")
            return s


_stream_manager = None


def stream_manager():
    global _stream_manager
    if _stream_manager is None:
        _stream_manager = StreamManager()
    return _stream_manager


def d2h_stream(ctx=None):
    """The device→host readback lane for `ctx` — the stream checkpoint
    saves and eval readbacks share so they stay FIFO among themselves
    while overlapping compute and H2D staging."""
    return stream_manager().get(ctx, "d2h")


def h2d_stream(ctx=None):
    """The host→device staging lane for `ctx` — the pipeline's device
    prefetcher double-buffers batches here (pull + batched_put per
    batch, FIFO within the lane) so input staging overlaps both the
    consumer's previous step and the d2h checkpoint readbacks."""
    return stream_manager().get(ctx, "h2d")


# ---------------------------------------------------------------------------
# Flat-buffer staging (the fused trainer-step tier; ref: the reference's
# aggregate multi_sgd updates + the bucketed gradient fusion the
# redistribution paper motivates): packing N small same-dtype tensors
# into ONE flat buffer turns N tiny XLA dispatches / collectives into a
# single large one.  The pack/unpack kernels are jitted through the
# standard executable cache (_imperative.get_jitted) so they share the
# no-recompile accounting every other op gets.


def _k_flatten(ts):
    """ONE dispatch: many buffers -> one flat buffer (same dtype)."""
    import jax.numpy as jnp

    if len(ts) == 1:
        return jnp.ravel(ts[0])
    return jnp.concatenate([jnp.ravel(t) for t in ts])


def _k_unflatten(flat, *, shapes):
    """ONE dispatch: one flat buffer -> per-tensor views of `shapes`."""
    import jax.numpy as jnp

    outs, off = [], 0
    for shp in shapes:
        n = 1
        for s in shp:
            n *= int(s)
        outs.append(jnp.reshape(flat[off:off + n], shp))
        off += n
    return tuple(outs)


def _k_flatten_pad(ts, *, padded):
    """ONE dispatch: many buffers -> one flat buffer zero-padded to
    ``padded`` elements (the ZeRO-1 shard tier: flat buckets must be a
    multiple of the world size so every rank's shard is equal-sized;
    the pad region is zeros, which every ``_fk_*`` update kernel maps
    to finite values and the unpack side never reads)."""
    import jax.numpy as jnp

    flat = _k_flatten(ts)
    pad = int(padded) - flat.shape[0]
    if pad <= 0:
        return flat
    return jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])


def flatten_pad(jarrs, padded):
    """Eager form of :func:`_k_flatten_pad`: pack raw same-dtype buffers
    into one flat buffer padded with zeros to ``padded`` elements, as a
    single cached-executable dispatch."""
    from . import _imperative

    _imperative.count_dispatch()
    return track(_imperative.get_jitted(
        _k_flatten_pad, {"padded": int(padded)})(list(jarrs)))


def _k_slice1d(flat, *, start, size):
    """ONE dispatch: a static [start, start+size) window of a flat
    buffer (the ZeRO eager weight-shard extraction — one slice per
    rank instead of materializing every rank's piece)."""
    return flat[int(start):int(start) + int(size)]


def slice_flat(jarr, start, size):
    """Eager cached-executable form of :func:`_k_slice1d`."""
    from . import _imperative

    _imperative.count_dispatch()
    return track(_imperative.get_jitted(
        _k_slice1d, {"start": int(start), "size": int(size)})(jarr))


def flatten_arrays(jarrs):
    """Pack raw jax buffers (same device, same dtype) into one flat
    buffer with a single cached-executable dispatch."""
    from . import _imperative

    _imperative.count_dispatch()
    return track(_imperative.get_jitted(_k_flatten, {})(list(jarrs)))


def unflatten_array(flat, shapes):
    """Inverse of :func:`flatten_arrays`: one dispatch yielding the
    per-tensor slices reshaped to ``shapes``."""
    from . import _imperative

    _imperative.count_dispatch()
    outs = _imperative.get_jitted(
        _k_unflatten, {"shapes": tuple(tuple(int(s) for s in shp)
                                       for shp in shapes)})(flat)
    return [track(o) for o in outs]


def batched_put(jarrs, device):
    """One transfer submission moving every buffer in ``jarrs`` to
    ``device`` (ref: CopyFromTo batched per destination) — the replica
    broadcast uses this instead of a per-parameter device_put loop."""
    import jax

    fault_point("engine.h2d", n=len(jarrs), device=str(device))
    outs = jax.device_put(list(jarrs), device)
    return [track(o) for o in outs]


# ---------------------------------------------------------------------------
# Fault points (mxnet_tpu.resilience.faults): named chaos-injection sites
# compiled into the runtime's failure-prone seams — transfers, collectives,
# checkpoint commits, pipeline map batches, training-step boundaries.  The
# default binding is a pure no-op; ``resilience.faults.install_plan``
# rebinds the module global to the armed plan's dispatcher, so callers
# (`engine.fault_point(...)` — attribute lookup resolves the CURRENT
# binding) pay one no-op call when nothing is armed and zero branches are
# taken.  ``MXTPU_FAULT_PLAN`` (JSON, inline or a file path) arms a plan
# at first fire without import-order coupling.


def _fault_noop(site, /, **ctx):
    """Disarmed fault point: nothing beyond the call is evaluated.
    (`site` is positional-only so ctx keys like `name` never clash.)"""
    return None


fault_point = _fault_noop


def set_fault_dispatcher(fn):
    """Rebind the fault-point hook (resilience.faults installs/clears
    the armed plan's dispatcher here; ``None`` restores the no-op)."""
    global fault_point
    fault_point = _fault_noop if fn is None else fn


def fault_points_armed():
    return fault_point is not _fault_noop


if getenv("FAULT_PLAN"):
    def _fault_bootstrap(site, /, **ctx):
        # first fire installs the env plan (lazy: resilience imports
        # engine, so the import must not happen at engine-import time),
        # which rebinds `fault_point`; dispatch through the new binding
        from .resilience import faults

        faults.install_from_env()
        return fault_point(site, **ctx)

    fault_point = _fault_bootstrap


# Donation coordination: the async checkpoint tier snapshots live
# device-buffer REFERENCES and reads them back later on the d2h stream,
# relying on XLA arrays being immutable.  Buffer DONATION (the fused
# optimizer step on accelerator backends) voids that — a donated buffer
# is deleted after the call.  While any hold is active, donating
# consumers must fall back to their non-donating executables so held
# references survive the readback window.

_donation_holds = 0
# RLock: the SIGTERM final-save hook may fire while the training thread
# sits inside donation_dispatch_guard — its synchronous save must be
# able to re-enter from the same thread (it completes, readback and
# all, before the guarded dispatch resumes, so the snapshot is safe)
_donation_mu = threading.RLock()


def acquire_donation_hold():
    global _donation_holds
    with _donation_mu:
        _donation_holds += 1


def release_donation_hold():
    global _donation_holds
    with _donation_mu:
        _donation_holds = max(0, _donation_holds - 1)


@contextlib.contextmanager
def donation_dispatch_guard():
    """Make a donating dispatch atomic w.r.t. acquire_donation_hold():
    a checkpoint capture on ANOTHER thread cannot slip between the
    hold check and the donating executable call and snapshot buffers
    that are about to be deleted.  Yields whether a hold is active."""
    with _donation_mu:
        yield _donation_holds > 0


def donation_held():
    return _donation_holds > 0
