"""Network visualization — mx.viz (ref: python/mxnet/visualization.py).

``print_summary`` renders the layer table (name, shape, params) to
stdout; ``plot_network`` returns a graphviz Digraph when the graphviz
package is importable, else raises with a clear message (the package is
not a framework dependency, matching the reference's soft requirement).
"""
from __future__ import annotations

import json

from .base import MXNetError


def _node_shape_map(symbol, shape=None):
    """Infer per-node output shapes when input shapes are given."""
    if shape is None:
        return {}
    try:
        from .symbol.symbol import Group

        internals = symbol.get_internals()
        grouped = Group(list(internals))
        _, out_shapes, _ = grouped.infer_shape(**shape)
        return dict(zip([s.name for s in internals], out_shapes))
    except Exception:
        return {}


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a Keras-style layer summary (ref: mx.viz.print_summary)."""
    graph = json.loads(symbol.tojson())
    nodes = graph["nodes"]
    heads = {h[0] for h in graph["heads"]}
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    positions = [int(line_length * p) for p in positions]
    shape_map = _node_shape_map(symbol, shape)

    def prow(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos - 1].ljust(pos)
        print(line.rstrip())

    print("_" * line_length)
    prow(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)
    total = 0

    # parameter counts: variables feeding an op node count toward it
    arg_shapes = {}
    if shape is not None:
        try:
            arg_s, _, _ = symbol.infer_shape_partial(**shape)
            arg_shapes = dict(zip(symbol.list_arguments(), arg_s))
        except Exception:
            pass

    import numpy as np

    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        inputs = [nodes[j[0]]["name"] for j in node.get("inputs", [])]
        prev_layers = [n for n in inputs
                       if not any(n.endswith(s) for s in
                                  ("_weight", "_bias", "_gamma", "_beta",
                                   "_moving_mean", "_moving_var"))]
        params = 0
        for n in inputs:
            if (n in arg_shapes and n not in shape
                    and not n.endswith("_label")):
                s = arg_shapes[n]
                if s:
                    params += int(np.prod(s))
        total += params
        out_shape = shape_map.get(name, "")
        prow([f"{name} ({op})", out_shape, params,
              ", ".join(prev_layers)])
        print(("=" if i == len(nodes) - 1 else "_") * line_length)
    print(f"Total params: {total}")
    print("_" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the network (ref: mx.viz.plot_network).

    Requires the optional ``graphviz`` package, like the reference."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz python package") from e

    graph = json.loads(symbol.tojson())
    nodes = graph["nodes"]
    dot = Digraph(name=title, format=save_format)
    dot.attr("node", shape="box", style="rounded,filled",
             **(node_attrs or {}))

    def is_weight(n):
        return hide_weights and any(
            n["name"].endswith(s) for s in
            ("_weight", "_bias", "_gamma", "_beta", "_moving_mean",
             "_moving_var"))

    palette = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
               "BatchNorm": "#bebada", "Activation": "#ffffb3",
               "Pooling": "#80b1d3", "Concat": "#fdb462",
               "softmax": "#fccde5"}
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            if not is_weight(node):
                dot.node(str(i), node["name"], fillcolor="#8dd3c7")
            continue
        label = f"{node['name']}\n{node['op']}"
        dot.node(str(i), label,
                 fillcolor=palette.get(node["op"], "#b3de69"))
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for j, _, *_rest in [tuple(x) for x in node.get("inputs", [])]:
            if is_weight(nodes[j]):
                continue
            dot.edge(str(j), str(i))
    return dot
