"""Channel-last (NHWC) layout support.

Ref: ConvolutionParam/PoolingParam `layout` field
(src/operator/nn/convolution.cc, pooling.cc) — the reference supports
NHWC for tensor-core paths; here it is the TPU-preferred layout (channel
on the minormost 128-lane tile dim). Weights are OHWI for channel-last
convs, matching the reference convention.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn


def test_conv2d_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 9, 9).astype(np.float32)
    w = rng.rand(8, 3, 3, 3).astype(np.float32)  # OIHW
    b = rng.rand(8).astype(np.float32)
    out_ref = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                             kernel=(3, 3), num_filter=8, stride=(2, 2),
                             pad=(1, 1), no_bias=False).asnumpy()
    x_cl = np.transpose(x, (0, 2, 3, 1))
    w_cl = np.transpose(w, (0, 2, 3, 1))  # OHWI
    out_cl = nd.Convolution(nd.array(x_cl), nd.array(w_cl), nd.array(b),
                            kernel=(3, 3), num_filter=8, stride=(2, 2),
                            pad=(1, 1), no_bias=False,
                            layout="NHWC").asnumpy()
    np.testing.assert_allclose(np.transpose(out_cl, (0, 3, 1, 2)),
                               out_ref, rtol=1e-5, atol=1e-5)


def test_pooling_nhwc_matches_nchw():
    rng = np.random.RandomState(1)
    x = rng.rand(2, 4, 8, 8).astype(np.float32)
    x_cl = np.transpose(x, (0, 2, 3, 1))
    for pool_type in ("max", "avg"):
        ref = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                         pad=(1, 1), pool_type=pool_type).asnumpy()
        cl = nd.Pooling(nd.array(x_cl), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), pool_type=pool_type,
                        layout="NHWC").asnumpy()
        np.testing.assert_allclose(np.transpose(cl, (0, 3, 1, 2)), ref,
                                   rtol=1e-5, atol=1e-5, err_msg=pool_type)
    # global pool honours layout too
    ref = nd.Pooling(nd.array(x), pool_type="avg",
                     global_pool=True).asnumpy()
    cl = nd.Pooling(nd.array(x_cl), pool_type="avg", global_pool=True,
                    layout="NHWC").asnumpy()
    np.testing.assert_allclose(cl.squeeze(), ref.squeeze(), rtol=1e-5)


def test_batchnorm_negative_axis_per_channel_stats():
    """axis=-1 (NHWC) must compute PER-CHANNEL train-mode stats, not a
    scalar over all dims (regression: negative axis never matched the
    reduction-exclusion test, silently normalizing with global stats)."""
    rng = np.random.RandomState(0)
    x = rng.rand(4, 5, 5, 3).astype(np.float32)
    # give each channel a wildly different scale so per-channel vs
    # global stats are distinguishable
    x[..., 1] *= 100.0
    x[..., 2] += 50.0
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    from mxnet_tpu import autograd

    with autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           nd.array(mm), nd.array(mv), axis=-1,
                           fix_gamma=False, eps=1e-5)
    o = out.asnumpy()
    # each channel independently standardized
    for c in range(3):
        assert abs(o[..., c].mean()) < 2e-2, c
        assert abs(o[..., c].std() - 1.0) < 5e-2, c
    # and identical to the channels-first result on transposed input
    with autograd.train_mode():
        out_cf = nd.BatchNorm(
            nd.array(np.transpose(x, (0, 3, 1, 2))), nd.array(gamma),
            nd.array(beta), nd.array(mm), nd.array(mv), axis=1,
            fix_gamma=False, eps=1e-5)
    np.testing.assert_allclose(
        np.transpose(out_cf.asnumpy(), (0, 2, 3, 1)), o, rtol=1e-4,
        atol=1e-4)


def test_deconv_rejects_channel_last():
    import pytest

    with pytest.raises(Exception, match="channel-first"):
        nd.Deconvolution(nd.ones((1, 4, 4, 2)), nd.ones((2, 3, 3, 2)),
                         kernel=(3, 3), num_filter=2, layout="NHWC")


def test_gluon_conv_nhwc_weight_shape():
    net = nn.Conv2D(16, 3, layout="NHWC")
    net.initialize()
    x = nd.array(np.random.rand(2, 8, 8, 5).astype(np.float32))
    out = net(x)
    assert out.shape == (2, 6, 6, 16)
    assert net.weight.shape == (16, 3, 3, 5)  # OHWI


def test_xavier_fan_matches_across_layouts():
    """OHWI weights are shape-ambiguous: Xavier must use the fan hint so
    NHWC and NCHW convs get the SAME init scale (regression: fan_out was
    read as O*prod(shape[2:]) = O*W*I for OHWI, ~85x off)."""
    mx.random.seed(0)
    a = nn.Conv2D(64, 3, layout="NHWC", in_channels=32)
    a.initialize(mx.init.Xavier(factor_type="avg", magnitude=3))
    mx.random.seed(0)
    b = nn.Conv2D(64, 3, layout="NCHW", in_channels=32)
    b.initialize(mx.init.Xavier(factor_type="avg", magnitude=3))
    sa = a.weight.data().asnumpy().std()
    sb = b.weight.data().asnumpy().std()
    assert abs(sa - sb) / sb < 0.05, (sa, sb)
    # deferred-init path (in_channels unknown at ctor) gets it too
    mx.random.seed(0)
    c = nn.Conv2D(64, 3, layout="NHWC")
    c.initialize(mx.init.Xavier(factor_type="avg", magnitude=3))
    c(nd.ones((1, 8, 8, 32)))
    sc = c.weight.data().asnumpy().std()
    assert abs(sc - sb) / sb < 0.05, (sc, sb)


def test_resnet_nhwc_parity_with_nchw():
    """resnet18 NHWC == NCHW given identical (transposed) weights."""
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(0)
    net = vision.resnet18_v1(layout="NHWC", classes=7)
    net.initialize(mx.init.Xavier())
    x = np.random.RandomState(2).rand(2, 32, 32, 3).astype(np.float32)
    out_cl = net(nd.array(x))
    net2 = vision.resnet18_v1(layout="NCHW", classes=7)
    net2.initialize(mx.init.Xavier())
    x_cf = np.transpose(x, (0, 3, 1, 2))
    net2(nd.array(x_cf))  # finish deferred init
    for (_, a), (_, b) in zip(net._ordered_params(),
                              net2._ordered_params()):
        src = a.data().asnumpy()
        if src.ndim == 4:
            src = np.transpose(src, (0, 3, 1, 2))  # OHWI -> OIHW
        assert src.shape == tuple(b.shape)
        b.set_data(nd.array(src))
    out_cf = net2(nd.array(x_cf))
    np.testing.assert_allclose(out_cl.asnumpy(), out_cf.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_resnet_nhwc_trains():
    """NHWC resnet trains end-to-end through the SPMD compiled step
    with bf16 compute (the flagship bench configuration)."""
    import jax

    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import data_parallel

    mx.random.seed(0)
    net = vision.resnet18_v1(layout="NHWC", classes=4, thumbnail=True)
    net.initialize(mx.init.Xavier())
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-3}, compute_dtype="bfloat16")
    rng = np.random.RandomState(3)
    x = rng.rand(8, 16, 16, 3).astype(np.float32)
    y = rng.randint(0, 4, 8).astype(np.float32)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(8)]
    assert all(np.isfinite(v) for v in losses), losses
    assert min(losses[4:]) < losses[0], losses
    # master params stayed fp32 under bf16 compute
    assert all(r.dtype == np.float32 for r in tr._params
               if jax.numpy.issubdtype(r.dtype, jax.numpy.floating))
