"""Runtime lock-order checker: the dynamic complement of the static
MXA101 pass (which can only order what it can resolve).

``enable()`` patches ``threading.Lock``/``threading.RLock`` with
factories returning checked wrappers; every wrapper records, per
thread, the stack of locks currently held and folds each (held ->
acquired) pair into a global order graph keyed by the lock's CREATION
SITE (file:line, or module.attr for locks wrapped in place by
``wrap_existing``).  Acquiring B while holding A when a B->...->A path
already exists is an observed inversion — the interleaving that
deadlocks exists even if this run got lucky — and raises
:class:`LockInversionError` (or just records it with
``raise_on_inversion=False``).

Usage (``make chaos-smoke`` and the slow serve/pipeline stress tests)::

    from mxnet_tpu.analysis import runtime as lock_order
    lock_order.enable()          # wrap locks created from here on
    lock_order.wrap_existing()   # rebind module-global locks in place
    ... exercise the concurrent paths ...
    lock_order.assert_clean()

Scope: locks created after ``enable()`` (plus module globals rebound by
``wrap_existing``).  Locks captured into closures/attributes before
that are invisible — the static pass covers import-time structure.
Same-site pairs (two instances from one allocation site) are skipped:
instance-level ordering within a homogeneous pool is a protocol the
graph cannot judge.  ``MXTPU_LOCK_CHECK=1`` lets ``maybe_enable()``
turn the checker on without code changes.
"""
from __future__ import annotations

import sys
import threading
import traceback

from ..base import getenv

_orig_Lock = threading.Lock
_orig_RLock = threading.RLock

_mu = _orig_Lock()          # guards the order graph + inversion log
_succ = {}                  # site -> set(site): observed held->acquired
_edge_where = {}            # (a, b) -> "thread/file:line" first witness
_inversions = []
_enabled = False
_raise = True
_tls = threading.local()
_counts = {"wrapped": 0, "acquires": 0}   # liveness telemetry


class LockInversionError(RuntimeError):
    """Two threads were observed acquiring the same locks in opposite
    orders — a latent deadlock."""


def _held_stack():
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _caller_site():
    for frame in traceback.extract_stack()[-8:][::-1]:
        fn = frame.filename
        if "analysis/runtime" in fn.replace("\\", "/") or \
                fn.endswith("threading.py"):
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


def _path_exists(src, dst):
    # BFS under _mu; the graph is tiny (one node per allocation site)
    if src == dst:
        return True
    seen, stack = {src}, [src]
    while stack:
        n = stack.pop()
        for m in _succ.get(n, ()):
            if m == dst:
                return True
            if m not in seen:
                seen.add(m)
                stack.append(m)
    return False


def _note_acquire(lock):
    if not _enabled:
        return
    held = _held_stack()
    site = lock._site
    inversion = None
    with _mu:
        _counts["acquires"] += 1
        for prior in held:
            psite = prior._site
            if psite == site:
                continue   # same-site pool ordering: not judged here
            if (psite, site) not in _edge_where:
                if _path_exists(site, psite):
                    inversion = {
                        "acquiring": site, "while_holding": psite,
                        "thread": threading.current_thread().name,
                        "at": _caller_site(),
                        "reverse_first_seen": _edge_where.get(
                            (site, psite)),
                    }
                    _inversions.append(inversion)
                _succ.setdefault(psite, set()).add(site)
                _edge_where[(psite, site)] = (
                    f"{threading.current_thread().name} "
                    f"@ {_caller_site()}")
    held.append(lock)
    if inversion is not None and _raise:
        raise LockInversionError(
            f"lock-order inversion: acquiring {site} while holding "
            f"{inversion['while_holding']} at {inversion['at']}, but the "
            f"opposite order was first seen at "
            f"{inversion['reverse_first_seen']}")


def _note_release(lock):
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


class _CheckedLock:
    """Order-checking wrapper around a threading.Lock/RLock, API-
    compatible enough for with-blocks, Condition(lock), and manual
    acquire/release."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site):
        self._inner = inner
        self._site = site
        _counts["wrapped"] += 1

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self)
            except LockInversionError:
                # unwind: the caller never observed a successful
                # acquire, so the lock must not stay held
                _note_release(self)
                self._inner.release()
                raise
        return ok

    def release(self):
        _note_release(self)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        f = getattr(self._inner, "locked", None)
        return f() if f is not None else False

    # Condition(lock) compatibility: delegate the private protocol when
    # the inner lock provides it, keeping the held-stack symmetric
    def _is_owned(self):
        f = getattr(self._inner, "_is_owned", None)
        if f is not None:
            return f()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        _note_release(self)
        f = getattr(self._inner, "_release_save", None)
        if f is not None:
            return f()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        f = getattr(self._inner, "_acquire_restore", None)
        if f is not None:
            f(state)
        else:
            self._inner.acquire()
        _note_acquire(self)

    def __repr__(self):
        return f"<CheckedLock {self._site} wrapping {self._inner!r}>"


def _lock_factory():
    return _CheckedLock(_orig_Lock(), _caller_site())


def _rlock_factory():
    return _CheckedLock(_orig_RLock(), _caller_site())


def enable(raise_on_inversion=True):
    """Start wrapping newly created locks; returns True if this call
    turned the checker on (False = already enabled)."""
    global _enabled, _raise
    _raise = bool(raise_on_inversion)
    if _enabled:
        return False
    _enabled = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    return True


def disable():
    """Restore the original factories.  Already-wrapped locks keep
    working but stop recording."""
    global _enabled
    _enabled = False
    threading.Lock = _orig_Lock
    threading.RLock = _orig_RLock


def maybe_enable():
    """enable() iff MXTPU_LOCK_CHECK is set (docs/ENV_VARS.md)."""
    if getenv("LOCK_CHECK", False, bool):
        return enable()
    return False


def wrap_existing(prefix="mxnet_tpu"):
    """Rebind module-global Lock/RLock objects under `prefix` to
    checked wrappers (named module.attr).  Only effective for locks the
    owning module reads back through the global name — which is the
    repo convention (`with _events_lock:` etc.).  Call at a quiescent
    point: a lock held while being rebound would split its identity."""
    if not _enabled:
        return 0
    lock_types = (type(_orig_Lock()), type(_orig_RLock()))
    n = 0
    for modname, mod in list(sys.modules.items()):
        if mod is None or not (modname == prefix
                               or modname.startswith(prefix + ".")):
            continue
        if modname.endswith("analysis.runtime"):
            continue
        for attr, val in list(vars(mod).items()):
            if isinstance(val, lock_types):
                setattr(mod, attr, _CheckedLock(val, f"{modname}.{attr}"))
                n += 1
    return n


def unwrap_existing(prefix="mxnet_tpu"):
    """Undo :func:`wrap_existing`: rebind every module-global
    _CheckedLock under `prefix` back to its raw inner lock, so a test
    that enabled the checker leaves pristine module state behind."""
    n = 0
    for modname, mod in list(sys.modules.items()):
        if mod is None or not (modname == prefix
                               or modname.startswith(prefix + ".")):
            continue
        for attr, val in list(vars(mod).items()):
            if isinstance(val, _CheckedLock):
                setattr(mod, attr, val._inner)
                n += 1
    return n


def inversions():
    with _mu:
        return [dict(i) for i in _inversions]


def stats():
    """`sites`/`edges` describe observed NESTED pairs only (a lock
    never held together with another contributes nothing); use
    `locks_wrapped`/`acquires` as the did-the-checker-see-anything
    liveness signal."""
    with _mu:
        sites = set(_succ)
        for targets in _succ.values():
            sites.update(targets)
        return {"sites": len(sites),
                "edges": sum(len(v) for v in _succ.values()),
                "inversions": len(_inversions),
                "locks_wrapped": _counts["wrapped"],
                "acquires": _counts["acquires"]}


def reset():
    """Forget the observed order graph, inversion log, and liveness
    counters (held-stack bookkeeping is left alone — it tracks live
    state)."""
    with _mu:
        _succ.clear()
        _edge_where.clear()
        del _inversions[:]
        _counts["wrapped"] = 0
        _counts["acquires"] = 0


def assert_clean():
    """Raise AssertionError listing every observed inversion."""
    inv = inversions()
    if inv:
        lines = [f"  acquiring {i['acquiring']} while holding "
                 f"{i['while_holding']} ({i['thread']} @ {i['at']}; "
                 f"reverse order first seen {i['reverse_first_seen']})"
                 for i in inv]
        raise AssertionError(
            "lock-order inversions observed:\n" + "\n".join(lines))
