"""Resource manager: temp-space & RNG resources for operators.

Ref: include/mxnet/resource.h + src/resource.cc — ops declare
ResourceRequest{kTempSpace, kRandom, kParallelRandom} and the manager
hands them scratch buffers / seeded generators tied to a device.

TPU-native translation: on-device scratch is XLA's job (the compiler
materializes and reuses temp buffers inside a fused computation), so
kTempSpace here provides HOST scratch from the pooled staging allocator
(src/storage.cc size-class free lists) — the piece custom ops and IO
actually need.  kRandom/kParallelRandom hand out jax PRNG keys split
from the framework seed stream (random.py), so resource-supplied
randomness composes with `mx.random.seed` the way the reference's
per-device generators compose with its seeds.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError


class ResourceRequest:
    """Ref: ResourceRequest::Type."""

    kTempSpace = "temp_space"
    kRandom = "random"
    kParallelRandom = "parallel_random"

    def __init__(self, type):
        if type not in (self.kTempSpace, self.kRandom,
                        self.kParallelRandom):
            raise MXNetError(f"unknown resource type {type!r}")
        self.type = type


class Resource:
    """A granted resource (ref: struct Resource)."""

    def __init__(self, req_type, manager):
        self.req = ResourceRequest(req_type)
        self._manager = manager
        self._handles = []

    # -- kTempSpace ----------------------------------------------------------

    def get_space(self, shape, dtype=np.float32):
        """Host scratch ndarray from the pooled staging allocator.

        Valid until release()/the next epoch of requests — same
        contract as the reference's temp space (one live buffer per
        resource, reused across calls).
        """
        if self.req.type != ResourceRequest.kTempSpace:
            raise MXNetError("get_space on a non-temp-space resource")
        from . import storage

        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        h = storage.Storage.get().alloc(max(nbytes, 1))
        self._handles.append(h)
        flat = h.as_numpy(dtype)[:int(np.prod(shape))]
        return flat.reshape(shape)

    def release(self):
        from . import storage

        for h in self._handles:
            storage.Storage.get().free(h)
        self._handles.clear()

    # -- kRandom / kParallelRandom ------------------------------------------

    def get_key(self):
        """One jax PRNG key from the framework seed stream."""
        if self.req.type not in (ResourceRequest.kRandom,
                                 ResourceRequest.kParallelRandom):
            raise MXNetError("get_key on a non-random resource")
        from . import random as _random

        return _random.next_key()

    def get_parallel_keys(self, n):
        """n independent keys (ref: kParallelRandom per-thread gens)."""
        import jax

        if self.req.type != ResourceRequest.kParallelRandom:
            raise MXNetError("get_parallel_keys needs kParallelRandom")
        from . import random as _random

        return list(jax.random.split(_random.next_key(), n))


class ResourceManager:
    """Ref: ResourceManager::Get() — grants resources per request."""

    _instance = None

    @classmethod
    def get(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def request(self, req_type):
        return Resource(req_type, self)


def request(req_type):
    """Module-level convenience: mx.resource.request('temp_space')."""
    return ResourceManager.get().request(req_type)
