"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

No network egress in this environment: datasets read local files only
(pass `root` pointing at pre-downloaded raw files); when files are
missing, a synthetic deterministic fallback can be enabled for smoke
tests via synthetic=True.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ....base import MXNetError
from ..dataset import ArrayDataset, Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def _make_synthetic(self, image_shape, num_classes, seed):
        """Shared no-egress fallback: deterministic random images."""
        from ....ndarray import ndarray as _nd

        n = 1024 if self._train else 256
        rng = np.random.RandomState(seed)
        data = rng.randint(0, 255, (n,) + image_shape).astype(np.uint8)
        self._data = _nd.array(data, dtype=np.uint8)
        self._label = rng.randint(0, num_classes, n).astype(np.int32)

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (ref: gluon.data.vision.MNIST)."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None, synthetic=False):
        self._synthetic = synthetic
        super().__init__(root, train, transform)

    def _get_data(self):
        from ....io.io import _read_idx_images, _read_idx_labels
        from ....ndarray import ndarray as _nd

        img, lbl = self._files[self._train]
        img_path = os.path.join(self._root, img)
        lbl_path = os.path.join(self._root, lbl)
        for p in (img_path, lbl_path):
            if not os.path.exists(p) and os.path.exists(p + ".gz"):
                p += ".gz"
        if not (os.path.exists(img_path) or os.path.exists(img_path + ".gz")):
            if self._synthetic:
                self._make_synthetic((28, 28, 1), 10, 42)
                return
            raise MXNetError(
                f"MNIST raw files not found under {self._root} "
                "(no network egress; place idx files there or pass "
                "synthetic=True)")
        if os.path.exists(img_path + ".gz"):
            img_path += ".gz"
            lbl_path += ".gz"
        images = _read_idx_images(img_path)
        labels = _read_idx_labels(lbl_path).astype(np.int32)
        self._data = _nd.array(images[..., None], dtype=np.uint8)
        self._label = labels


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None, synthetic=False):
        super().__init__(root, train, transform, synthetic)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the local python-pickle batches
    (ref: gluon.data.vision.CIFAR10)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None, synthetic=False):
        self._synthetic = synthetic
        super().__init__(root, train, transform)

    def _get_data(self):
        import pickle

        from ....ndarray import ndarray as _nd

        base = os.path.join(self._root, "cifar-10-batches-py")
        files = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        if not os.path.exists(base):
            if self._synthetic:
                self._make_synthetic((32, 32, 3), 10, 7)
                return
            raise MXNetError(
                f"CIFAR10 batches not found under {base} (no egress)")
        xs, ys = [], []
        for fn in files:
            with open(os.path.join(base, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"].reshape(-1, 3, 32, 32)
                      .transpose(0, 2, 3, 1))
            ys.append(np.asarray(d[b"labels"], np.int32))
        self._data = _nd.array(np.concatenate(xs), dtype=np.uint8)
        self._label = np.concatenate(ys)


class ImageRecordDataset(Dataset):
    """Image dataset over a .rec file (ref: vision.ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset

        self._rec = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._rec)

    def __getitem__(self, idx):
        from ....io import recordio as rio
        from ....ndarray import ndarray as _nd

        header, img = rio.unpack_img(self._rec[idx], iscolor=self._flag)
        label = header.label if np.isscalar(header.label) \
            else header.label[0]
        data = _nd.array(img if img.ndim == 3 else img[..., None],
                         dtype=np.uint8)
        if self._transform is not None:
            return self._transform(data, label)
        return data, np.float32(label)


class ImageFolderDataset(Dataset):
    """Folder-of-class-folders dataset (ref: vision.ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        exts = (".jpg", ".jpeg", ".png", ".bmp")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith(exts):
                    self.items.append((os.path.join(path, fn), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from PIL import Image

        from ....ndarray import ndarray as _nd

        path, label = self.items[idx]
        img = Image.open(path)
        img = img.convert("RGB") if self._flag else img.convert("L")
        arr = np.asarray(img)
        data = _nd.array(arr if arr.ndim == 3 else arr[..., None],
                         dtype=np.uint8)
        if self._transform is not None:
            return self._transform(data, label)
        return data, np.float32(label)


class CIFAR100(CIFAR10):
    """CIFAR100 (ref: gluon.data.vision.CIFAR100). fine_label=False
    gives the 20 coarse labels."""

    def __init__(self, root="~/.mxnet/datasets/cifar100",
                 fine_label=False, train=True, transform=None,
                 synthetic=False):
        # reference signature: (root, fine_label=False, train=True, ...)
        self._fine = fine_label
        super().__init__(root=root, train=train, transform=transform,
                         synthetic=synthetic)

    def _get_data(self):
        import pickle

        from ....ndarray import ndarray as _nd

        base = os.path.join(self._root, "cifar-100-python")
        if not os.path.exists(base):
            if self._synthetic:
                self._make_synthetic((32, 32, 3),
                                     100 if self._fine else 20, 11)
                return
            raise MXNetError(
                f"CIFAR100 batches not found under {base} (no egress)")
        fn = "train" if self._train else "test"
        with open(os.path.join(base, fn), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        key = b"fine_labels" if self._fine else b"coarse_labels"
        self._data = _nd.array(
            d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
            dtype=np.uint8)
        self._label = np.asarray(d[key], np.int32)
