"""model_zoo.vision (ref: python/mxnet/gluon/model_zoo/vision/)."""
from .resnet import (get_resnet, resnet18_v1, resnet34_v1, resnet50_v1,  # noqa: F401
                     resnet101_v1, resnet152_v1, resnet18_v2, resnet34_v2,
                     resnet50_v2, resnet101_v2, resnet152_v2, ResNetV1,
                     ResNetV2)
from .others import (alexnet, lenet, AlexNet, LeNet, VGG, get_vgg, vgg11,  # noqa: F401
                     vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn, vgg16_bn,
                     vgg19_bn, MobileNet, MobileNetV2, mobilenet1_0,
                     mobilenet0_75, mobilenet0_5, mobilenet0_25,
                     mobilenet_v2_1_0, mobilenet_v2_0_75, mobilenet_v2_0_5,
                     mobilenet_v2_0_25, SqueezeNet, squeezenet1_0,
                     squeezenet1_1, DenseNet, densenet121, densenet161,
                     densenet169, densenet201)
from .inception import Inception3, inception_v3  # noqa: F401

_models = {k: v for k, v in globals().items() if callable(v)
           and not k.startswith("_") and k not in
           ("get_resnet", "get_vgg")}


def get_model(name, **kwargs):
    """Ref: model_zoo.vision.get_model."""
    name = name.lower()
    if name not in _models:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_models)}")
    return _models[name](**kwargs)
