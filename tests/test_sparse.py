"""Sparse storage types (ref: tests/python/unittest/test_sparse_ndarray.py
+ test_sparse_operator.py — numpy-oracle checks)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rand_sparse(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.rand(*shape) * (rng.rand(*shape) < density)
    return dense.astype(np.float32)


def test_csr_roundtrip():
    dense = _rand_sparse((6, 5))
    csr = sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    assert csr.shape == (6, 5)
    np.testing.assert_allclose(csr.asnumpy(), dense)
    # component accessors
    assert csr.indptr.shape == (7,)
    assert csr.data.shape == csr.indices.shape
    # back to dense via tostype
    np.testing.assert_allclose(csr.tostype("default").asnumpy(), dense)


def test_csr_from_components():
    data, indices, indptr = [1., 2., 3.], [0, 2, 1], [0, 2, 2, 3]
    csr = sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
    expect = np.array([[1, 0, 2], [0, 0, 0], [0, 3, 0]], np.float32)
    np.testing.assert_allclose(csr.asnumpy(), expect)


def test_csr_row_slice():
    dense = _rand_sparse((8, 4))
    csr = sparse.csr_matrix(dense)
    sl = csr[2:5]
    assert sl.stype == "csr"
    np.testing.assert_allclose(sl.asnumpy(), dense[2:5])
    np.testing.assert_allclose(csr[3].asnumpy(), dense[3:4])


def test_row_sparse_roundtrip():
    dense = np.zeros((7, 3), np.float32)
    dense[1] = 1.0
    dense[4] = [1, 2, 3]
    rsp = sparse.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    np.testing.assert_allclose(np.asarray(rsp.indices.asnumpy()), [1, 4])
    np.testing.assert_allclose(rsp.asnumpy(), dense)


def test_row_sparse_from_components():
    rsp = sparse.row_sparse_array(([[1., 1.], [2., 2.]], [0, 3]),
                                  shape=(5, 2))
    expect = np.zeros((5, 2), np.float32)
    expect[0], expect[3] = 1, 2
    np.testing.assert_allclose(rsp.asnumpy(), expect)


def test_cast_storage_and_tostype():
    dense = _rand_sparse((5, 6))
    x = nd.array(dense)
    csr = x.tostype("csr")
    assert isinstance(csr, sparse.CSRNDArray)
    rsp = x.tostype("row_sparse")
    assert isinstance(rsp, sparse.RowSparseNDArray)
    np.testing.assert_allclose(rsp.asnumpy(), dense)
    np.testing.assert_allclose(
        nd.cast_storage(x, "csr").asnumpy(), dense)
    assert x.tostype("default") is x
    assert x.stype == "default"


def test_sparse_zeros():
    z = sparse.zeros("csr", (3, 4))
    assert z.asnumpy().sum() == 0 and z.shape == (3, 4)
    z = sparse.zeros("row_sparse", (3, 4))
    assert z.asnumpy().sum() == 0
    assert sparse.zeros("default", (2, 2)).stype == "default"


@pytest.mark.parametrize("transpose_a", [False, True])
def test_csr_dot(transpose_a):
    lhs = _rand_sparse((6, 5), seed=1)
    rhs = np.random.RandomState(2).rand(6 if transpose_a else 5, 4) \
        .astype(np.float32)
    csr = sparse.csr_matrix(lhs)
    out = sparse.dot(csr, nd.array(rhs), transpose_a=transpose_a)
    expect = (lhs.T if transpose_a else lhs) @ rhs
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5, atol=1e-6)


def test_sparse_retain():
    dense = np.diag(np.arange(1, 6)).astype(np.float32)
    rsp = sparse.row_sparse_array(dense)
    kept = sparse.retain(rsp, nd.array([1, 3], dtype="int32"))
    expect = np.zeros_like(dense)
    expect[1, 1], expect[3, 3] = 2, 4
    np.testing.assert_allclose(kept.asnumpy(), expect)


def test_row_sparse_add():
    a = sparse.row_sparse_array(([[1., 1.]], [1]), shape=(4, 2))
    b = sparse.row_sparse_array(([[2., 2.], [3., 3.]], [1, 3]), shape=(4, 2))
    out = sparse.add(a, b)
    assert out.stype == "row_sparse"
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy() + b.asnumpy())


def test_sparse_dense_fallback_arith():
    dense = _rand_sparse((4, 4))
    csr = sparse.csr_matrix(dense)
    out = csr + nd.ones((4, 4))
    np.testing.assert_allclose(out.asnumpy(), dense + 1)


def test_sgd_lazy_row_sparse_update():
    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9)
    w = nd.ones((6, 3))
    state = opt.create_state(0, w)
    grad = sparse.row_sparse_array(([[1., 1., 1.]], [2]), shape=(6, 3))
    w_before = w.asnumpy()
    opt.update(0, w, grad, state)
    w_after = w.asnumpy()
    # only row 2 moved
    np.testing.assert_allclose(np.delete(w_after, 2, 0),
                               np.delete(w_before, 2, 0))
    np.testing.assert_allclose(w_after[2], w_before[2] - 0.5)


def test_adam_lazy_vs_dense_touched_rows():
    # on rows present in the gradient, lazy update == dense update when
    # the gradient has only those rows and the moments start at zero
    g_dense = np.zeros((5, 2), np.float32)
    g_dense[1] = 0.3
    opt1 = mx.optimizer.Adam(learning_rate=0.1)
    opt2 = mx.optimizer.Adam(learning_rate=0.1)
    w1, w2 = nd.ones((5, 2)), nd.ones((5, 2))
    s1, s2 = opt1.create_state(0, w1), opt2.create_state(0, w2)
    opt1.update(0, w1, nd.array(g_dense), s1)
    opt2.update(0, w2, sparse.row_sparse_array(g_dense), s2)
    np.testing.assert_allclose(w1.asnumpy()[1], w2.asnumpy()[1], rtol=1e-6)


def test_kvstore_sparse_push_row_sparse_pull():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((6, 2)))
    g1 = sparse.row_sparse_array(([[1., 1.]], [0]), shape=(6, 2))
    g2 = sparse.row_sparse_array(([[2., 2.]], [4]), shape=(6, 2))
    kv.push("w", [g1, g2])
    out = nd.zeros((6, 2))
    kv.pull("w", out=out)
    expect = np.zeros((6, 2), np.float32)
    expect[0], expect[4] = 1, 2
    np.testing.assert_allclose(out.asnumpy(), expect)
    # row-filtered pull into a row_sparse out
    rs_out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("w", out=rs_out, row_ids=nd.array([4], dtype="int32"))
    np.testing.assert_allclose(np.asarray(rs_out.indices.asnumpy()), [4])
    np.testing.assert_allclose(rs_out.asnumpy()[4], [2, 2])


def test_kvstore_multi_key_row_sparse_pull():
    # regression: per-key row_ids must align with keys (round-1 bug pulled
    # key 0's rows for every key)
    kv = mx.kv.create("local")
    kv.init("a", nd.array(np.arange(12, dtype=np.float32).reshape(6, 2)))
    kv.init("b", nd.array(100 + np.arange(12,
                                          dtype=np.float32).reshape(6, 2)))
    oa = sparse.zeros("row_sparse", (6, 2))
    ob = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull(["a", "b"], out=[oa, ob],
                       row_ids=[nd.array([1], dtype="int32"),
                                nd.array([2], dtype="int32")])
    np.testing.assert_allclose(np.asarray(oa.indices.asnumpy()), [1])
    np.testing.assert_allclose(oa.asnumpy()[1], [2, 3])
    np.testing.assert_allclose(np.asarray(ob.indices.asnumpy()), [2])
    np.testing.assert_allclose(ob.asnumpy()[2], [104, 105])
    # mismatched rid count errors instead of silently recycling
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull(["a", "b"], out=[oa, ob],
                           row_ids=[nd.array([0]), nd.array([1]),
                                    nd.array([2])])


def test_sparse_copyto_shape_mismatch_errors():
    src = sparse.row_sparse_array(([[1., 1.]], [0]), shape=(6, 2))
    with pytest.raises(mx.MXNetError):
        src.copyto(nd.zeros((4, 2)))
    # dtype casts to the destination's dtype
    dst = nd.zeros((6, 2), dtype="float16")
    src.copyto(dst)
    assert dst.dtype == np.float16
    np.testing.assert_allclose(dst.asnumpy()[0], [1, 1])


def test_embedding_sparse_grad_end_to_end():
    from mxnet_tpu import gluon, autograd

    net = gluon.nn.Embedding(10, 4, sparse_grad=True)
    net.initialize(mx.init.Uniform(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    x = nd.array([1, 3], dtype="int32")
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w_before = net.weight.data().asnumpy()
    trainer.step(1)
    w_after = net.weight.data().asnumpy()
    # rows 1 and 3 moved by -lr * 1; every other row untouched
    np.testing.assert_allclose(np.delete(w_after, [1, 3], 0),
                               np.delete(w_before, [1, 3], 0))
    np.testing.assert_allclose(w_after[[1, 3]], w_before[[1, 3]] - 1.0,
                               rtol=1e-6)


def test_sparse_save_load(tmp_path):
    dense = _rand_sparse((4, 3))
    f = str(tmp_path / "x.params")
    nd.save(f, {"w": nd.array(dense)})
    loaded = nd.load(f)
    np.testing.assert_allclose(loaded["w"].asnumpy(), dense)


def test_kvstore_sparse_init_then_pull():
    # regression: init with a row_sparse value must store a dense
    # canonical copy so pull/row_sparse_pull work
    kv = mx.kv.create("local")
    kv.init("s", sparse.row_sparse_array(([[1., 1.]], [0]), shape=(4, 2)))
    out = nd.zeros((4, 2))
    kv.pull("s", out=out)
    np.testing.assert_allclose(out.asnumpy()[0], [1, 1])
    rs = sparse.zeros("row_sparse", (4, 2))
    kv.row_sparse_pull("s", out=rs, row_ids=nd.array([0], dtype="int32"))
    np.testing.assert_allclose(rs.asnumpy()[0], [1, 1])


def test_sparse_grad_with_non_sparse_optimizer():
    # regression: optimizers without a lazy row kernel (rmsprop) get the
    # dense grad instead of crashing inside jit
    from mxnet_tpu import gluon, autograd

    net = gluon.nn.Embedding(10, 4, sparse_grad=True)
    net.initialize(mx.init.Uniform(0.1))
    tr = gluon.Trainer(net.collect_params(), "rmsprop",
                       {"learning_rate": 0.1})
    with autograd.record():
        loss = net(nd.array([1, 3], dtype="int32")).sum()
    loss.backward()
    tr.step(1)  # must not raise


def test_pad_rows_bucketing():
    # lazy updates compile per power-of-2 bucket, not per exact nnz
    from mxnet_tpu.optimizer import _pad_rows

    vals = nd.array(np.ones((5, 3), np.float32))
    idx = nd.array([0, 1, 2, 3, 4], dtype="int32")
    v, i = _pad_rows(vals, idx)
    assert v.shape[0] == 8 and i.shape[0] == 8
    # padding repeats entry 0 → identical computed update, set() safe
    np.testing.assert_allclose(i.asnumpy()[5:], [0, 0, 0])
    # result correctness with padding: sgd on 5 rows of a 9-row weight
    opt = mx.optimizer.SGD(learning_rate=1.0)
    w = nd.ones((9, 3))
    g = sparse.row_sparse_array((np.ones((5, 3), np.float32),
                                 [0, 1, 2, 3, 4]), shape=(9, 3))
    opt.update(0, w, g, None)
    expect = np.ones((9, 3), np.float32)
    expect[:5] -= 1.0
    np.testing.assert_allclose(w.asnumpy(), expect)


def test_entropy_calibration_incremental_hist():
    # regression: entropy stats keep O(num_bins) memory and match the
    # one-shot threshold on the same data
    from mxnet_tpu.contrib.quantization import _Stats, _get_optimal_threshold

    rng = np.random.RandomState(0)
    batches = [rng.randn(1000).astype(np.float32) for _ in range(4)]
    st = _Stats("entropy")
    for b in batches:
        st.update(b)
    assert st.hist is not None and st.hist.shape == (st.NUM_BINS,)
    lo, hi = st.range()
    t_oneshot = _get_optimal_threshold(np.concatenate(batches))
    assert abs(hi - t_oneshot) / t_oneshot < 0.05
    assert lo == -hi


def test_csr_negative_and_oob_index():
    dense = _rand_sparse((4, 3))
    csr = sparse.csr_matrix(dense)
    np.testing.assert_allclose(csr[-1].asnumpy(), dense[3:4])
    with pytest.raises(IndexError):
        csr[4]
    with pytest.raises(IndexError):
        csr[-5]


def test_kvstore_pull_sparse_out_raises():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((4, 2)))
    with pytest.raises(mx.MXNetError):
        kv.pull("w", out=sparse.zeros("row_sparse", (4, 2)))
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull("w", out=sparse.zeros("row_sparse", (4, 2)))
    with pytest.raises(mx.MXNetError):
        kv.row_sparse_pull("w", out=sparse.zeros("row_sparse", (4, 2)),
                           row_ids=nd.array([100], dtype="int32"))


def test_row_sparse_array_device_path_matches_numpy():
    dense = _rand_sparse((8, 3), density=0.4, seed=3)
    via_nd = sparse.row_sparse_array(nd.array(dense))
    via_np = sparse.row_sparse_array(dense)
    np.testing.assert_allclose(via_nd.asnumpy(), via_np.asnumpy())
    np.testing.assert_array_equal(np.asarray(via_nd.indices.asnumpy()),
                                  np.asarray(via_np.indices.asnumpy()))


def test_getnnz():
    """Ref contrib/nnz.cc: stored-value counts for csr."""
    from mxnet_tpu.ndarray import sparse

    m = np.array([[1, 0, 2], [0, 0, 3], [4, 5, 0]], np.float32)
    csr = sparse.cast_storage(nd.array(m), "csr")
    assert nd.contrib.getnnz(csr).asnumpy()[0] == 5
    assert list(nd.contrib.getnnz(csr, axis=1).asnumpy()) == [2, 1, 2]
    assert list(nd.contrib.getnnz(csr, axis=0).asnumpy()) == [2, 1, 2]
    rs = sparse.cast_storage(nd.array(m), "row_sparse")
    assert nd.contrib.getnnz(rs).asnumpy()[0] == 9  # stored elements
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="expects a sparse"):
        nd.contrib.getnnz(nd.array(m))  # dense rejected
    out = nd.zeros((3,))
    got = nd.contrib.getnnz(csr, axis="1", out=out)  # string attr + out=
    assert got is out and list(out.asnumpy()) == [2, 1, 2]
    import mxnet_tpu as _mx

    with pytest.raises(MXNetError, match="not supported symbolically"):
        _mx.sym.getnnz(_mx.sym.Variable("d"))


def test_edge_id():
    """Ref _contrib_edge_id: CSR adjacency lookup, -1 for absent."""
    from mxnet_tpu.ndarray import sparse

    adj = np.array([[0, 5, 0], [7, 0, 0], [0, 0, 9]], np.float32)
    csr = sparse.cast_storage(nd.array(adj), "csr")
    out = nd.contrib.edge_id(csr, nd.array([0, 1, 2, 0]),
                             nd.array([1, 0, 2, 0])).asnumpy()
    assert list(out) == [5.0, 7.0, 9.0, -1.0]
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError, match="csr"):
        nd.contrib.edge_id(nd.array(adj), nd.array([0]), nd.array([0]))


def test_edge_id_empty_and_dtype():
    from mxnet_tpu.ndarray import sparse

    empty = sparse.cast_storage(nd.zeros((3, 3)), "csr")
    out = nd.contrib.edge_id(empty, nd.array([0, 2]), nd.array([1, 2]))
    assert list(out.asnumpy()) == [-1.0, -1.0]
    # integer edge ids keep their dtype (no float promotion)
    csr = sparse.csr_matrix((np.array([10, 20], np.int32),
                             np.array([1, 0]), np.array([0, 1, 2])),
                            shape=(2, 2), dtype="int32")
    out = nd.contrib.edge_id(csr, nd.array([0, 1, 1]),
                             nd.array([1, 0, 1]))
    assert out.dtype == np.int32
    assert list(out.asnumpy()) == [10, 20, -1]


def test_edge_id_out_of_range_queries():
    """v >= ncols / u >= nrows must miss (-1), never alias into a
    neighbouring row's key space."""
    from mxnet_tpu.ndarray import sparse

    adj = np.array([[0, 5, 0], [7, 0, 0], [0, 0, 9]], np.float32)
    csr = sparse.cast_storage(nd.array(adj), "csr")
    out = nd.contrib.edge_id(csr, nd.array([0, 3, 0]),
                             nd.array([3, 0, -1])).asnumpy()
    assert list(out) == [-1.0, -1.0, -1.0]
