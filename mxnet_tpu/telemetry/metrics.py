"""Metrics registry + Prometheus text rendering.

One :class:`Registry` unifies every counter surface in the tree behind
a single scrape: the profiler's section registry (cachedGraph /
trainerStep / dataPipeline / resilience / telemetry) is exported by a
built-in collector, ``ModelServer`` instances self-register via
:func:`register_server`, and subsystems can create explicit
counters/gauges/histograms.  ``render()`` emits Prometheus text
exposition format 0.0.4 — what the stdlib-http ``/metrics`` endpoint
(:mod:`.httpd`) serves.

Two kinds of sources:

- **metric objects** — ``registry.counter/gauge/histogram(name)``
  create owned instruments mutated imperatively (``inc``/``set``/
  ``observe``).
- **collectors** — callables returning ``(name, mtype, help, samples)``
  families computed at scrape time from an existing stats surface
  (``samples`` = iterable of ``(labels_dict, value)``, or for
  histograms ``(labels_dict, {"buckets": [(le, cumulative_count),
  ...], "sum": s, "count": n})``).  Collectors keep the existing
  per-subsystem counter code authoritative: a scrape reads the same
  numbers ``profiler.dumps()`` reports, by construction.

Metric names follow Prometheus conventions (``mxtpu_`` prefix,
snake_case); every name literal in the tree must appear in
docs/observability.md (the MXA405 catalog pass).
"""
from __future__ import annotations

import itertools
import re
import threading
import weakref

from ..base import MXNetError

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

DEFAULT_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, float("inf"))


def _escape(value):
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Metric:
    """One metric family (counter | gauge | histogram), label-aware."""

    def __init__(self, name, mtype, help="", buckets=None):
        if not _NAME_RE.match(name):
            raise MXNetError(f"invalid metric name {name!r}")
        if mtype not in ("counter", "gauge", "histogram"):
            raise MXNetError(f"invalid metric type {mtype!r}")
        self.name = name
        self.mtype = mtype
        self.help = help
        self._lock = threading.Lock()
        self._values = {}       # labels tuple -> float | [counts, sum, n]
        self._buckets = None
        if mtype == "histogram":
            bs = tuple(float(b) for b in (buckets or DEFAULT_BUCKETS_MS))
            if bs[-1] != float("inf"):
                bs = bs + (float("inf"),)
            self._buckets = bs

    def _key(self, labels):
        for k in labels:
            if not _LABEL_RE.match(k):
                raise MXNetError(f"invalid label name {k!r}")
        return tuple(sorted(labels.items()))

    def inc(self, n=1, **labels):
        if self.mtype not in ("counter", "gauge"):
            raise MXNetError(f"{self.name}: inc() on a {self.mtype}")
        if self.mtype == "counter" and n < 0:
            raise MXNetError(f"{self.name}: counters only go up")
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + n

    def set(self, value, **labels):
        if self.mtype != "gauge":
            raise MXNetError(f"{self.name}: set() on a {self.mtype}")
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def observe(self, value, **labels):
        if self.mtype != "histogram":
            raise MXNetError(f"{self.name}: observe() on a {self.mtype}")
        k = self._key(labels)
        with self._lock:
            slot = self._values.get(k)
            if slot is None:
                slot = self._values[k] = [
                    [0] * len(self._buckets), 0.0, 0]
            counts, _s, _n = slot
            for i, le in enumerate(self._buckets):
                if value <= le:
                    counts[i] += 1
                    break
            slot[1] += float(value)
            slot[2] += 1

    def value(self, **labels):
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self):
        """((labels_dict, payload)) pairs; histogram payloads are the
        collector-shaped dict with CUMULATIVE bucket counts."""
        with self._lock:
            items = list(self._values.items())
        out = []
        for k, v in items:
            labels = dict(k)
            if self.mtype == "histogram":
                counts, total, n = v
                cum, acc = [], 0
                for le, c in zip(self._buckets, counts):
                    acc += c
                    cum.append((le, acc))
                out.append((labels, {"buckets": cum, "sum": total,
                                     "count": n}))
            else:
                out.append((labels, v))
        return out


class Registry:
    """Metric families + scrape-time collectors, rendered as one
    Prometheus text page."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._collectors = []

    # -- instruments --------------------------------------------------------

    def _make(self, name, mtype, help, buckets=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.mtype != mtype:
                    raise MXNetError(
                        f"metric {name} already registered as {m.mtype}")
                return m
            m = self._metrics[name] = Metric(name, mtype, help,
                                             buckets=buckets)
            return m

    def counter(self, name, help=""):
        return self._make(name, "counter", help)

    def gauge(self, name, help=""):
        return self._make(name, "gauge", help)

    def histogram(self, name, help="", buckets=None):
        return self._make(name, "histogram", help, buckets=buckets)

    # -- collectors ---------------------------------------------------------

    def register_collector(self, fn):
        """``fn()`` -> iterable of ``(name, mtype, help, samples)``
        families, evaluated per scrape."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)
        return fn

    def unregister_collector(self, fn):
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    # -- scrape -------------------------------------------------------------

    def collect(self):
        """Every family as ``(name, mtype, help, [(labels, payload)])``,
        metrics first, then collectors in registration order."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out = [(m.name, m.mtype, m.help, m.samples()) for m in metrics]
        for fn in collectors:
            fams = fn()
            if fams:
                out.extend((n, t, h, list(s)) for n, t, h, s in fams)
        return out

    def render(self):
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for name, mtype, help, samples in self.collect():
            if help:
                lines.append(f"# HELP {name} {_escape(help)}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, payload in samples:
                if mtype == "histogram":
                    for le, c in payload["buckets"]:
                        bl = dict(labels, le=_fmt_value(le))
                        lines.append(
                            f"{name}_bucket{_fmt_labels(bl)} {int(c)}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(payload['sum'])}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} "
                                 f"{int(payload['count'])}")
                else:
                    lines.append(f"{name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(payload)}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The default registry and its built-in collectors.


_default = Registry()


def default_registry():
    return _default


def _snake(name):
    return re.sub(r"(?<=[a-z0-9])([A-Z])",
                  lambda m: "_" + m.group(1).lower(), name)


def _profiler_sections_collector():
    """Every profiler section (cachedGraph/trainerStep/dataPipeline/
    resilience/telemetry/...) as ``mxtpu_<section>_<key>`` gauges —
    gauges, not counters, because ``profiler.dumps(reset=True)``
    legitimately rewinds the window.  Nested dicts (retries by fault
    class, bucket hits) become labeled samples.  Reads the same
    providers ``dumps()`` reads, so a scrape and a dump always agree.
    """
    import sys

    root = __package__.rsplit(".", 1)[0]
    profiler = sys.modules.get(root + ".profiler")
    if profiler is None:
        return []
    fams = []
    for section, stats in profiler.sections().items():
        base = "mxtpu_" + _snake(section)
        for key, val in sorted(stats.items()):
            if isinstance(val, bool) or val is None:
                continue
            if isinstance(val, (int, float)):
                fams.append((f"{base}_{_snake(key)}", "gauge",
                             f"profiler section {section}.{key}",
                             [({}, float(val))]))
            elif isinstance(val, dict):
                samples = [({"key": str(k)}, float(v))
                           for k, v in sorted(val.items())
                           if isinstance(v, (int, float))
                           and not isinstance(v, bool)]
                if samples:
                    fams.append((f"{base}_{_snake(key)}", "gauge",
                                 f"profiler section {section}.{key}",
                                 samples))
    return fams


_default.register_collector(_profiler_sections_collector)

# explicit built-ins (names cataloged in docs/observability.md)
_scrapes = _default.counter(
    "mxtpu_metrics_scrapes_total",
    "scrapes served by the /metrics endpoint")
_flight_dumps = _default.counter(
    "mxtpu_flight_dumps_total",
    "flight-recorder files written (process lifetime)")


def count_scrape():
    """Book one scrape (called by the endpoint per /metrics render)."""
    from . import tracer

    _scrapes.inc()
    tracer.bump("scrapes")


# -- ModelServer export ------------------------------------------------------

_server_ids = itertools.count(0)
_TALLY_KEYS = ("submitted", "served", "rejected_overload",
               "expired_deadline", "failed", "cancelled", "batches",
               "warmup_batches", "reloads")
_GAUGE_KEYS = ("queue_depth", "in_flight", "batch_fill_ratio",
               "padding_overhead")


def register_server(server, registry=None):
    """Export a ``ModelServer``'s ``stats()`` under
    ``mxtpu_serve_*{server="<id>"}``; holds only a weak reference (a
    collected server silently drops out of the scrape).  Returns the
    collector (pass to ``unregister_collector`` to remove early).

    Everything is exported as a GAUGE, never a Prometheus counter:
    ``stats(reset=True)`` legitimately rewinds the accounting window
    (the same reason the profiler-section collector exports gauges),
    and a monotonic-counter type would make ``rate()`` misread every
    window reset as a process restart."""
    reg = registry or _default
    ref = weakref.ref(server)
    sid = str(next(_server_ids))

    def _collect():
        s = ref()
        if s is None:
            reg.unregister_collector(_collect)
            return []
        snap = s.stats()
        lab = {"server": sid}
        fams = []
        for k in _TALLY_KEYS:
            fams.append((f"mxtpu_serve_{k}", "gauge",
                         f"serve {k} (current accounting window)",
                         [(lab, float(snap.get(k, 0)))]))
        for k in _GAUGE_KEYS:
            v = snap.get(k)
            if v is not None:
                fams.append((f"mxtpu_serve_{k}", "gauge", f"serve {k}",
                             [(lab, float(v))]))
        hits = snap.get("bucket_hits") or {}
        if hits:
            fams.append(("mxtpu_serve_bucket_hits", "gauge",
                         "batches per bucket shape (current window)",
                         [(dict(lab, bucket=str(b)), float(n))
                          for b, n in sorted(hits.items())]))
        # per-bucket traffic quality: where padding waste actually
        # lands — the data the bucket autotuner (ROADMAP item 4) and
        # the decode-vs-whole-batch comparison need, vs the aggregate
        # fill ratio that was the only scrapeable figure before
        fill = snap.get("bucket_fill_ratio") or {}
        if fill:
            fams.append(("mxtpu_serve_bucket_fill_ratio", "gauge",
                         "real requests / padded rows per bucket "
                         "(current window)",
                         [(dict(lab, bucket=str(b)), float(v))
                          for b, v in sorted(fill.items())]))
        pad = snap.get("bucket_padding_overhead") or {}
        if pad:
            fams.append(("mxtpu_serve_bucket_padding_overhead", "gauge",
                         "padded/real elements - 1 per bucket "
                         "(current window)",
                         [(dict(lab, bucket=str(b)), float(v))
                          for b, v in sorted(pad.items())]))
        hist = (snap.get("latency") or {}).get("histogram")
        if hist:
            fams.append(("mxtpu_serve_latency_ms", "histogram",
                         "request latency (submit to resolve)",
                         [(lab, {"buckets": [(b, c) for b, c in
                                             hist["buckets"]],
                                 "sum": hist["sum_ms"],
                                 "count": hist["count"]})]))
        graph = snap.get("graph") or {}
        for k, v in sorted(graph.items()):
            fams.append((f"mxtpu_serve_graph_{k}", "gauge",
                         f"serve compiled-graph {k}",
                         [(lab, float(v))]))
        return fams

    reg.register_collector(_collect)
    return _collect


# -- Router export -----------------------------------------------------------


def register_router(router, registry=None):
    """Export a ``serve.Router``'s ``stats()`` under
    ``mxtpu_router_*{router="<id>"}`` — weakly held, gauges throughout
    (``stats(reset=True)`` rewinds the window), mirroring
    :func:`register_server` for the replica-pool tier.  Per-replica
    health and attribution land as ``{replica=}``-labeled samples, so
    a dashboard can watch one sick replica get evicted and its warm
    replacement join."""
    from ..serve.router import ROUTER_COUNTERS

    reg = registry or _default
    ref = weakref.ref(router)
    sid = str(next(_server_ids))

    def _collect():
        r = ref()
        if r is None:
            reg.unregister_collector(_collect)
            return []
        snap = r.stats()
        lab = {"router": sid}
        fams = []
        for k in ROUTER_COUNTERS:
            fams.append((f"mxtpu_router_{k}", "gauge",
                         f"router {k} (current accounting window)",
                         [(lab, float(snap.get(k, 0)))]))
        for k in ("requests_lost", "pool_size", "healthy",
                  "queue_depth", "in_flight"):
            fams.append((f"mxtpu_router_{k}", "gauge", f"router {k}",
                         [(lab, float(snap.get(k) or 0))]))
        if snap.get("last_recovery_ms") is not None:
            fams.append(("mxtpu_router_last_recovery_ms", "gauge",
                         "eviction -> warm replacement admitted, ms",
                         [(lab, float(snap["last_recovery_ms"]))]))
        reps = snap.get("replicas") or {}
        if reps:
            fams.append(("mxtpu_router_replica_healthy", "gauge",
                         "1 = replica in rotation",
                         [(dict(lab, replica=str(i)),
                           1.0 if info["state"] == "healthy" else 0.0)
                          for i, info in sorted(reps.items())]))
            fams.append(("mxtpu_router_replica_pending", "gauge",
                         "queued + in-flight requests per replica",
                         [(dict(lab, replica=str(i)),
                           float(info["pending"]))
                          for i, info in sorted(reps.items())]))
            fams.append(("mxtpu_router_replica_ewma_ms", "gauge",
                         "EWMA service time per replica, ms",
                         [(dict(lab, replica=str(i)),
                           float(info["ewma_ms"]))
                          for i, info in sorted(reps.items())]))
        hist = (snap.get("latency") or {}).get("histogram")
        if hist:
            fams.append(("mxtpu_router_latency_ms", "histogram",
                         "request latency through the pool (submit to "
                         "resolve, re-dispatches included)",
                         [(lab, {"buckets": [(b, c) for b, c in
                                             hist["buckets"]],
                                 "sum": hist["sum_ms"],
                                 "count": hist["count"]})]))
        return fams

    reg.register_collector(_collect)
    return _collect


# -- DecodeServer export -----------------------------------------------------


def register_decode_server(server, registry=None):
    """Export a ``DecodeServer``'s ``stats()`` under
    ``mxtpu_decode_*{server="<id>"}`` — weakly held, gauges throughout
    (``stats(reset=True)`` rewinds the window), mirroring
    :func:`register_server` for the continuous-batching tier."""
    # the decode tier defines its counter set ONCE; importing it here
    # (lazily — decode.py imports this module) keeps the export from
    # drifting out of sync with the stats it scrapes
    from ..serve.decode import DECODE_COUNTERS

    reg = registry or _default
    ref = weakref.ref(server)
    sid = str(next(_server_ids))

    def _collect():
        s = ref()
        if s is None:
            reg.unregister_collector(_collect)
            return []
        snap = s.stats()
        lab = {"server": sid}
        fams = []
        for k in DECODE_COUNTERS:
            fams.append((f"mxtpu_decode_{k}", "gauge",
                         f"decode serve {k} (current accounting window)",
                         [(lab, float(snap.get(k, 0)))]))
        fams.append(("mxtpu_decode_queue_depth", "gauge",
                     "queued admissions",
                     [(lab, float(snap.get("queue_depth", 0)))]))
        slots = snap.get("slots") or {}
        fams.append(("mxtpu_decode_slots_live", "gauge",
                     "occupied decode slots",
                     [(lab, float(slots.get("live", 0)))]))
        if slots.get("occupancy") is not None:
            fams.append(("mxtpu_decode_slot_occupancy", "gauge",
                         "token-step-weighted mean live/max_slots",
                         [(lab, float(slots["occupancy"]))]))
        for name, key in (("mxtpu_decode_ttft_ms", "ttft"),
                          ("mxtpu_decode_token_ms", "token_latency"),
                          ("mxtpu_decode_latency_ms", "latency")):
            hist = (snap.get(key) or {}).get("histogram")
            if hist:
                fams.append((name, "histogram",
                             f"decode serve {key}",
                             [(lab, {"buckets": [(b, c) for b, c in
                                                 hist["buckets"]],
                                     "sum": hist["sum_ms"],
                                     "count": hist["count"]})]))
        graph = snap.get("graph") or {}
        for k, v in sorted(graph.items()):
            fams.append((f"mxtpu_decode_graph_{k}", "gauge",
                         f"decode serve compiled-graph {k}",
                         [(lab, float(v))]))
        pages = snap.get("pages")
        if pages:
            for name, key, help_ in (
                    ("mxtpu_decode_page_in_flight", "in_flight",
                     "physical cache pages currently referenced"),
                    ("mxtpu_decode_page_free", "free",
                     "physical cache pages on the free list"),
                    ("mxtpu_decode_page_committed", "committed",
                     "worst-case pages committed to admitted requests"),
                    ("mxtpu_decode_page_deferred", "deferred",
                     "admissions deferred on the page budget"),
                    ("mxtpu_decode_page_hbm_bytes", "hbm_bytes",
                     "paged KV-cache pool bytes (incl. trash page)")):
                fams.append((name, "gauge", help_,
                             [(lab, float(pages.get(key, 0)))]))
        spec = snap.get("spec")
        if spec:
            fams.append(("mxtpu_decode_spec_proposed", "gauge",
                         "draft tokens proposed (window)",
                         [(lab, float(spec.get("proposed", 0)))]))
            fams.append(("mxtpu_decode_spec_accepted", "gauge",
                         "draft tokens accepted (window)",
                         [(lab, float(spec.get("accepted", 0)))]))
            if spec.get("accept_rate") is not None:
                fams.append(("mxtpu_decode_spec_accept_rate", "gauge",
                             "accepted/proposed draft tokens (window)",
                             [(lab, float(spec["accept_rate"]))]))
        return fams

    reg.register_collector(_collect)
    return _collect
