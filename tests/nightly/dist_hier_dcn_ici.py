"""Hierarchical DCN x ICI pod-shape test (VERDICT r3 #5).

2 processes x 4 virtual CPU devices each — the v5p-pod shape in
miniature (ps-lite workers x multi-GPU per worker, SURVEY §3.4; here
process boundary = DCN, local devices = ICI).  Launched by
tools/launch.py via tests/test_dist_nightly.py.

Two compositions are asserted:

1. DataParallelTrainer on a 2-level mesh {'dcn': 2, 'dp': 4}: the
   outer axis spans processes (DCN), the inner axis local devices
   (ICI); GSPMD emits the hierarchical all-reduce inside the compiled
   step.  Per-step losses must match the 8-device single-process
   oracle (computed by the launching pytest, passed via
   MXTPU_ORACLE_FILE).  1b repeats with {'dcn': 2, 'dp': 2, 'tp': 2}
   + shard_params=True — DCN data parallelism composing with Megatron
   tensor parallelism inside each slice, same oracle.
2. kvstore('dist_sync') composed WITH an in-process 4-device psum:
   gradients reduce over the local mesh in-graph (CommDevice role),
   then push/pull through the dist kvstore's in-graph DCN all-reduce
   (ps-lite role).  The composed gradient must equal the full-batch
   single-device gradient.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)  # 4 local devices per proc

from mxnet_tpu.parallel import dist  # noqa: E402

dist.init()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, kvstore, nd  # noqa: E402
from mxnet_tpu.parallel import data_parallel  # noqa: E402
from mxnet_tpu.parallel import mesh as mesh_mod  # noqa: E402

rank, size = dist.rank(), dist.num_workers()
assert size == 2, f"expected 2 processes, got {size}"
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

GLOBAL_BATCH, FEAT, NCLS = 16, 20, 10
rng = np.random.RandomState(0)
X = rng.rand(GLOBAL_BATCH, FEAT).astype(np.float32)
Y = rng.randint(0, NCLS, GLOBAL_BATCH).astype(np.float32)

oracle = np.load(os.environ["MXTPU_ORACLE_FILE"])

# --- 1. trainer on the 2-level mesh, then composed with TP ---------------
# (a) pure hierarchical data parallelism {'dcn': 2, 'dp': 4};
# (b) DCN x dp x Megatron-tp with sharded params — the pod's actual
#     3-axis layout.  Both must match the flat-dp single-process oracle.
ref = np.asarray(oracle["losses"])
for shape, extra in (({"dcn": 2, "dp": 4}, {}),
                     ({"dcn": 2, "dp": 2, "tp": 2},
                      {"shard_params": True})):
    mesh = mesh_mod.make_mesh(shape)
    # the outer axis must actually span processes (DCN), row r = proc r
    for r in range(2):
        assert all(d.process_index == r
                   for d in mesh.devices[r].flat), (
            f"outer mesh axis of {shape} does not align with process "
            "boundaries")
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(NCLS))
    net.initialize(mx.init.Xavier())
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh, **extra)
    losses = []
    for _ in range(5):
        loss = trainer.step(X, Y)
        losses.append(float(np.asarray(loss._data.addressable_data(0))))
    assert np.allclose(losses, ref, atol=1e-5), (shape, losses,
                                                 ref.tolist())

# --- 2. kvstore('dist_sync') x in-process psum ----------------------------
# model: linear least squares; grads reduce hierarchically in two
# explicit stages so each transport is visible
from jax.sharding import Mesh, NamedSharding, PartitionSpec  # noqa: E402

local_mesh = Mesh(np.array(jax.local_devices()), ("ldp",))
W = np.linspace(-0.5, 0.5, FEAT * NCLS).reshape(FEAT, NCLS) \
    .astype(np.float32)
Y1h = np.eye(NCLS, dtype=np.float32)[Y.astype(int)]


def mse_grad(w, x, y1h):
    def loss(w):
        return jnp.mean((x @ w - y1h) ** 2)
    return jax.grad(loss)(w)


# this worker's half of the batch, mean-grad over its 8 samples with
# the batch sharded across the 4 LOCAL devices: GSPMD inserts the
# in-process (ICI-role) psum
half = slice(rank * 8, rank * 8 + 8)
# the local mesh is fully addressable, but under jax.distributed numpy
# args with non-trivial shardings must be placed explicitly
w_l = jax.device_put(W, NamedSharding(local_mesh, PartitionSpec()))
x_l = jax.device_put(X[half],
                     NamedSharding(local_mesh, PartitionSpec("ldp")))
y_l = jax.device_put(Y1h[half],
                     NamedSharding(local_mesh, PartitionSpec("ldp")))
local_grad = jax.jit(
    mse_grad,
    out_shardings=NamedSharding(local_mesh, PartitionSpec()))(
        w_l, x_l, y_l)
local_grad = np.asarray(local_grad.addressable_data(0))

# cross-process (DCN role): dist kvstore sums the per-process means
kv = kvstore.create("dist_sync")
kv.init("g", nd.zeros((FEAT, NCLS)))
kv.barrier()
kv.push("g", [nd.array(local_grad)])
out = nd.zeros((FEAT, NCLS))
kv.pull("g", out=out)
composed = out.asnumpy() / size  # mean of per-half means = global mean

full = np.asarray(jax.jit(mse_grad)(W, X, Y1h))
assert np.allclose(composed, full, atol=1e-6), \
    float(np.abs(composed - full).max())
kv.barrier()

print(f"worker {rank}/{size}: hier dcn x ici OK "
      f"(trainer losses {losses[0]:.4f}->{losses[-1]:.4f}, "
      f"grad maxdiff {float(np.abs(composed - full).max()):.2e})")
