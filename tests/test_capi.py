"""Flat C ABI (multi-frontend boundary) — compile and run a pure-C
frontend against lib/libmxtpu_capi.so.

Ref: include/mxnet/c_api.h + src/c_api/c_api.cc (the reference's ~400
MX* flat functions that Scala/R/Julia/cpp-package ride).  The TPU build
inverts the embedding (C hosts the Python orchestrator, which drives
XLA), but the frontend-facing contract is the same: opaque NDArray
handles, string-keyed imperative invoke against the op registry,
GetLastError error protocol, stateless flat calls.

The test builds the .so (make) and the C driver (gcc), then runs the
driver in a clean subprocess — a frontend with no Python of its own.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    return shutil.which(name)


@pytest.mark.skipif(not _tool("g++") or not _tool("python3-config"),
                    reason="native toolchain unavailable")
def test_c_frontend_drives_the_framework(tmp_path):
    # 1. build the shared library
    r = subprocess.run(["make", "lib/libmxtpu_capi.so"], cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]

    # 2. build the C driver (plain C, no python headers — the point)
    exe = str(tmp_path / "capi_driver")
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "tests", "capi_driver.c"),
         "-o", exe, "-L" + os.path.join(REPO, "lib"), "-lmxtpu_capi",
         # the driver pthread_joins its own threads; toolchains that
         # don't link libpthread implicitly need it spelled out
         "-lpthread", "-Wl,-rpath," + os.path.join(REPO, "lib")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]

    # 3. run it: the embedded interpreter must find the venv + repo.
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in sys.path if "site-packages" in p])
    # the driver pins jax to cpu itself (MXTPUCAPIInit("cpu")); make sure
    # the axon plugin's env pin doesn't fight that in the subprocess
    env.pop("JAX_PLATFORMS", None)
    save_path = str(tmp_path / "capi_saved.params")
    r = subprocess.run([exe, save_path], capture_output=True, text=True,
                       timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "CAPI_DRIVER_OK" in r.stdout
    # the C frontend's save must be loadable by the python frontend
    # (backend/path setup already done by conftest)
    import numpy as np

    from mxnet_tpu.ndarray import ndarray as _nd

    loaded = _nd.load(save_path)
    assert set(loaded) == {"weight_a", "weight_b"}
    assert np.allclose(loaded["weight_a"].asnumpy(),
                       np.arange(1, 7).reshape(2, 3))


def _write_mnist_idx(tmp_path, n=640, seed=0):
    """Synthesize a learnable MNIST-format dataset: each class is a
    bright block at a class-dependent position plus noise (so LeNet can
    drive the loss down in a couple of epochs without the real files)."""
    import struct

    import numpy as np

    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = (rng.rand(n, 28, 28) * 64).astype(np.uint8)
    for i, c in enumerate(labels):
        r, col = divmod(int(c), 5)
        images[i, 4 + r * 12:4 + r * 12 + 8,
               2 + col * 5:2 + col * 5 + 5] = 255
    img_path = str(tmp_path / "train-images.idx")
    lbl_path = str(tmp_path / "train-labels.idx")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path


@pytest.mark.skipif(not _tool("g++") or not _tool("python3-config"),
                    reason="native toolchain unavailable")
def test_c_frontend_trains_lenet(tmp_path):
    """VERDICT r3 #4: the trainable C ABI — a pure-C frontend composes
    LeNet symbolically, binds an executor, iterates MNISTIter batches,
    runs forward/backward, applies SGD updates, and the loss decreases;
    plus imperative autograd, kvstore push/pull, and CachedOp inference,
    all through the flat C surface (ref: cpp-package/example/lenet.cpp
    over include/mxnet/c_api.h)."""
    r = subprocess.run(["make", "lib/libmxtpu_capi.so"], cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]

    exe = str(tmp_path / "capi_train_lenet")
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "tests", "capi_train_lenet.c"),
         "-o", exe, "-L" + os.path.join(REPO, "lib"), "-lmxtpu_capi",
         "-lm", "-Wl,-rpath," + os.path.join(REPO, "lib")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]

    img, lbl = _write_mnist_idx(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in sys.path if "site-packages" in p])
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run([exe, img, lbl], capture_output=True, text=True,
                       timeout=900, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "CAPI_TRAIN_OK" in r.stdout
    # the driver asserts the loss curve itself; sanity-check the print
    assert "epoch 2 loss" in r.stdout


@pytest.mark.skipif(not _tool("g++") or not _tool("python3-config"),
                    reason="native toolchain unavailable")
def test_cpp_frontend_header_only_api(tmp_path):
    """The cpp-package role: include/mxtpu_cpp.hpp (RAII + exceptions
    over the flat C ABI) trains an MLP from C++ — a SECOND non-Python
    frontend on the same boundary (ref: cpp-package/include/mxnet-cpp
    over include/mxnet/c_api.h)."""
    r = subprocess.run(["make", "lib/libmxtpu_capi.so"], cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]

    exe = str(tmp_path / "capi_cpp_driver")
    r = subprocess.run(
        ["g++", "-std=c++17", "-I" + os.path.join(REPO, "include"),
         os.path.join(REPO, "tests", "capi_cpp_driver.cc"),
         "-o", exe, "-L" + os.path.join(REPO, "lib"), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.join(REPO, "lib")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in sys.path if "site-packages" in p])
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run([exe], capture_output=True, text=True,
                       timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "CAPI_CPP_OK" in r.stdout
