"""Train a causal transformer LM under dp x tp x pp on one 3D mesh.

The PP/TP/DP product-surface example (capability upgrade; the reference
has no pipeline tier — SURVEY §2.3 'PP: ABSENT'): non-uniform GPipe
stages (embedding on stage 0, LM head on the last stage), Megatron
tensor parallelism inside each block, data parallelism across the
microbatch dim — all expressed as ONE shard_map over a
``jax.sharding.Mesh`` and jitted once.

Synthetic copy-task corpus by default so the script runs anywhere:

  python examples/pipeline_lm/train_pipeline_lm.py --cpu \
      --dp 2 --tp 2 --pp 2 --steps 20
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import add_cpu_flag, apply_backend  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    add_cpu_flag(p)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel (Ulysses) axis size")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--n-micro", type=int, default=2)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    args = p.parse_args()
    apply_backend(args)

    import jax

    n_dev = args.dp * args.tp * args.pp * args.sp
    if args.cpu and len(jax.devices()) < n_dev:
        raise SystemExit(
            f"need {n_dev} devices; run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_dev}")

    import numpy as np

    from mxnet_tpu.parallel import mesh as mesh_mod
    from mxnet_tpu.parallel import pipeline_lm as plm

    axes = {"dp": args.dp}
    if args.sp > 1:
        axes["sp"] = args.sp
    axes.update({"tp": args.tp, "pp": args.pp})
    mesh = mesh_mod.make_mesh(axes, devices=jax.devices()[:n_dev])
    params = plm.init_pipeline_lm(
        args.vocab, args.d_model, args.layers, args.d_ff, args.heads,
        args.seq_len, n_stages=args.pp, seed=0)
    trainer = plm.PipelineLMTrainer(params, mesh, n_heads=args.heads,
                                    n_micro=args.n_micro, lr=args.lr)

    rng = np.random.RandomState(0)
    # copy task: predict the previous token (learnable quickly)
    toks = rng.randint(2, args.vocab, (args.batch_size, args.seq_len))
    tgts = np.roll(toks, -1, axis=1)

    t0 = time.time()
    for step in range(1, args.steps + 1):
        loss = trainer.step(toks, tgts)
        if step == 1 or step % 5 == 0:
            print(f"step {step:4d} loss {loss:.4f} "
                  f"({time.time() - t0:.1f}s)", flush=True)
    print(f"done: mesh {dict(mesh.shape)}, final loss {loss:.4f}")


if __name__ == "__main__":
    main()
