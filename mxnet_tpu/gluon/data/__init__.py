"""gluon.data (ref: python/mxnet/gluon/data/)."""
from .dataset import (Dataset, SimpleDataset, ArrayDataset,  # noqa: F401
                      RecordFileDataset)
from .sampler import (Sampler, SequentialSampler, RandomSampler,  # noqa: F401
                      BatchSampler, FilterSampler)
from .dataloader import DataLoader  # noqa: F401
from . import vision  # noqa: F401
