"""Transformer-big on WMT14-style data — BASELINE config #4.

Ref: Sockeye-era training shape (hybridized encoder/decoder -> one XLA
computation). Length-bucketed batches exercise the shape-bucketed
executable cache (the BucketingModule translation): one compiled step
per bucket, reused across batches.

  python examples/nmt/train_transformer.py --model tiny --steps 20
  python examples/nmt/train_transformer.py --model big \
      --batch-size 64 --buckets 16,32,64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import add_cpu_flag, apply_backend  # noqa: E402

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import HybridBlock
from mxnet_tpu.models import transformer as tfm


class LabelSmoothedCE(gluon.loss.Loss):
    """Per-token label-smoothed cross entropy with padding mask."""

    def __init__(self, smoothing=0.1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._eps = smoothing

    def hybrid_forward(self, F, pred, label):
        # pred: (B, T, V) logits; label: (B, T) int (0 = padding)
        V = pred.shape[-1]
        logp = F.log_softmax(pred)
        nll = -F.pick(logp, label, axis=-1)
        smooth = -F.mean(logp, axis=-1)
        loss = (1 - self._eps) * nll + self._eps * smooth
        mask = label != 0
        return F.sum(loss * mask) / (F.sum(mask) + 1e-6)


class Seq2SeqTrainNet(HybridBlock):
    """Wraps the model with teacher forcing: inputs (src, tgt_in)."""

    def __init__(self, model, **kwargs):
        super().__init__(**kwargs)
        self.model = model

    def hybrid_forward(self, F, src, tgt_in, src_valid_len=None):
        # masking the encoder's PAD tail in training keeps train-time
        # and beam-decode-time encodings consistent
        return self.model(src, tgt_in, src_valid_len)


def synthetic_pairs(rng, bs, src_len, vocab):
    """Copy-task pairs: target = source (learnable signal)."""
    src = rng.randint(3, vocab, (bs, src_len)).astype(np.int32)
    tgt_in = np.concatenate(
        [np.ones((bs, 1), np.int32), src[:, :-1]], axis=1)  # BOS shift
    return src, tgt_in, src  # (src, tgt_in, tgt_out)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="big",
                   choices=["tiny", "base", "big"])
    p.add_argument("--src-vocab", type=int, default=32000)
    p.add_argument("--tgt-vocab", type=int, default=32000)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--buckets", default="16,32",
                   help="sequence-length buckets")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--disp", type=int, default=10)
    p.add_argument("--data-src", default=None,
                   help="source-side parallel corpus (one sentence per "
                        "line); with --data-tgt enables the WMT-style "
                        "BPE + length-bucketing pipeline")
    p.add_argument("--data-tgt", default=None,
                   help="target-side parallel corpus")
    p.add_argument("--bpe-merges", type=int, default=8000,
                   help="joint BPE merges learned from the corpus")
    p.add_argument("--translate", type=int, default=0,
                   help="after training, beam-decode this many corpus "
                        "sentences (Sockeye decode role; needs --data)")
    add_cpu_flag(p)
    args = p.parse_args()
    apply_backend(args)
    if bool(args.data_src) != bool(args.data_tgt):
        p.error("--data-src and --data-tgt must be given together")
    if args.translate and not args.data_src:
        p.error("--translate needs a corpus (--data-src/--data-tgt)")
    if args.model == "tiny":
        args.src_vocab = min(args.src_vocab, 1000)
        args.tgt_vocab = min(args.tgt_vocab, 1000)

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    # sorted: buckets[-1] is the true max length whatever order the
    # user wrote (encode_pairs drops pairs longer than it)
    buckets = sorted(int(b) for b in args.buckets.split(","))

    data_iter = None
    if args.data_src:
        # real-corpus path (VERDICT r3 #6): shared BPE + bucketed
        # batches from mxnet_tpu.data.nmt — same training loop
        from mxnet_tpu.data import nmt as dnmt

        pairs = dnmt.load_parallel(args.data_src, args.data_tgt)
        bpe = dnmt.build_shared_bpe(pairs, num_merges=args.bpe_merges)
        encoded = dnmt.encode_pairs(pairs, bpe, max_len=buckets[-1])
        data_iter = dnmt.NMTBucketIter(encoded, args.batch_size,
                                       buckets=tuple(buckets), seed=0)
        args.src_vocab = args.tgt_vocab = len(bpe)
        print(f"corpus: {len(pairs)} pairs, shared BPE vocab "
              f"{len(bpe)}, dropped(too long) {data_iter.dropped}")

    builder = getattr(tfm, f"transformer_{args.model}")
    net = Seq2SeqTrainNet(builder(args.src_vocab, args.tgt_vocab))
    net.initialize(mx.init.Xavier())

    from mxnet_tpu.parallel import data_parallel

    trainer = data_parallel.DataParallelTrainer(
        net, LabelSmoothedCE(), "adam",
        {"learning_rate": args.lr, "beta2": 0.98})

    tic, tic_n = time.time(), 0
    for step in range(args.steps):
        if data_iter is not None:
            try:
                b = data_iter.next()
            except StopIteration:
                data_iter.reset()
                b = data_iter.next()
            src, tgt_in = b.data
            tgt_out = b.label[0]
            svl = b.src_valid_length
            L = b.bucket_key
        else:
            L = buckets[rng.randint(len(buckets))]  # bucketed lengths
            src, tgt_in, tgt_out = synthetic_pairs(
                rng, args.batch_size, L,
                min(args.src_vocab, args.tgt_vocab))
            svl = np.full((args.batch_size,), L, np.int32)
        loss = trainer.step((src, tgt_in, svl), tgt_out)
        tic_n += args.batch_size * L
        if step % args.disp == 0 and step:
            loss.wait_to_read()
            print(f"step {step} bucket {L} "
                  f"loss {float(loss.asscalar()):.4f} "
                  f"{tic_n / (time.time() - tic):.0f} tokens/s")
            tic, tic_n = time.time(), 0
    loss.wait_to_read()
    print(f"done: final loss {float(loss.asscalar()):.4f}")

    if args.translate and data_iter is not None:
        # trained params live in the trainer's donated device buffers;
        # decoding goes through the block
        trainer.sync_to_block()
        bos, eos = bpe.ids[bpe.BOS], bpe.ids[bpe.EOS]
        n = min(args.translate, len(pairs))
        L = buckets[-1]
        src_ids = np.zeros((n, L), np.int32)
        src_len = np.zeros((n,), np.int32)
        for i, (s, _) in enumerate(pairs[:n]):
            ids = bpe.encode(s, eos=True)[:L]
            src_ids[i, :len(ids)] = ids
            src_len[i] = len(ids)
        from mxnet_tpu import nd

        # src_valid_len masks the PAD tail exactly as in training, so
        # bucket-16-trained sentences decode identically when padded
        # to the widest bucket here
        seqs, scores = net.model.beam_search_decode(
            nd.array(src_ids), beam_size=4, max_len=L, bos=bos, eos=eos,
            src_valid_len=nd.array(src_len))
        for i in range(n):
            print(f"src: {pairs[i][0]!r} -> "
                  f"{bpe.decode(list(seqs[i]))!r} ({scores[i]:.2f})")


if __name__ == "__main__":
    main()
