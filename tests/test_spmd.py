"""Multi-axis sharded whole-step training (parallel.spmd).

The contract under test: ``Trainer(..., mesh_shape='dp=4,mp=2')`` (or
``MXTPU_MESH_SHAPE``) runs every whole step as ONE GSPMD executable on
a named multi-axis mesh — params sharded over 'mp', batch over 'dp',
ZeRO-1 optimizer state over both — with 1 device dispatch per step,
0 post-warmup recompiles under LR decay, allclose parity with the
single-device whole step, checkpoints that are mesh-AGNOSTIC (full
arrays) so a (dp=4,mp=2) → (dp=2,mp=2) → (dp=4,mp=2) round trip is
bit-exact on params AND optimizer state, and a loud error for every
invalid mesh configuration.
"""
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _imperative, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import trainer as trainer_mod
from mxnet_tpu.parallel import spmd

X = np.random.RandomState(3).rand(8, 16).astype(np.float32)
Y = np.random.RandomState(4).rand(8, 4).astype(np.float32)


def loss_fn(out, y):
    return (out - y) ** 2


def build(mesh_shape=None, zero=False, opt_args=None, layers=2, **tkw):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    units = 16
    for _ in range(layers):
        net.add(nn.Dense(16, in_units=units, activation="tanh"))
        units = 16
    net.add(nn.Dense(4, in_units=units))
    net.initialize(mx.init.Xavier(), ctx=mx.xla(0))
    kwargs = dict(opt_args or {"learning_rate": 0.05, "momentum": 0.9})
    tr = gluon.Trainer(net.collect_params(), "sgd", kwargs,
                       mesh_shape=mesh_shape, zero_shard=zero, **tkw)
    return net, tr


def weights(net):
    return [p.data().asnumpy() for _, p in net._ordered_params()]


def host_blob(blob):
    """A states blob as a checkpoint file delivers it: device leaves
    captured, fetched to numpy, pickled (the CheckpointManager path) —
    in particular NOT aliasing the donor trainer's live buffers."""
    from mxnet_tpu.checkpoint import manager as _mgr

    return pickle.loads(pickle.dumps(_mgr._fetch(_mgr._capture(blob))))


def states(tr):
    out = []
    for st in tr._states:
        entry = next(iter(st.values())) if st else None
        if entry is None:
            out.append(())
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(s.asnumpy() for s in entry))
        else:
            out.append((entry.asnumpy(),))
    return out


# -- mesh-shape spec parsing ------------------------------------------------


def test_parse_mesh_shape():
    assert spmd.parse_mesh_shape("dp=4,mp=2") == {"dp": 4, "mp": 2}
    assert spmd.parse_mesh_shape({"dp": 8}) == {"dp": 8}
    assert spmd.format_mesh_shape({"dp": 4, "mp": 2}) == "dp=4,mp=2"


@pytest.mark.parametrize("bad", [
    "", "dp", "dp=4,zz=2", "dp=4,dp=2", "dp=0", "dp=x",
    "mp=2,dp=4",   # out of canonical order
])
def test_parse_mesh_shape_loud(bad):
    with pytest.raises(MXNetError):
        spmd.parse_mesh_shape(bad)


def test_mesh_device_count_mismatch_loud():
    with pytest.raises(MXNetError, match="devices"):
        spmd.make_spmd_mesh("dp=4,mp=4")  # 16 > the 8 virtual devices


def test_pick_mesh_shape_keeps_model_axes():
    assert spmd.pick_mesh_shape("dp=4,mp=2", 4) == {"dp": 2, "mp": 2}
    assert spmd.pick_mesh_shape("dp=8", 2) == {"dp": 2}
    assert spmd.pick_mesh_shape("dcn=2,dp=2,mp=2", 8) == \
        {"dcn": 2, "dp": 2, "mp": 2}
    # dcn no longer divides -> folds into dp
    assert spmd.pick_mesh_shape("dcn=2,dp=2,mp=2", 2) == \
        {"dp": 1, "mp": 2}
    with pytest.raises(MXNetError, match="model-axis product"):
        spmd.pick_mesh_shape("dp=2,mp=2", 3)


def test_stage_partition():
    assert spmd.stage_partition(7, 3) == ((0, 3), (3, 5), (5, 7))
    with pytest.raises(MXNetError, match="pipeline stages"):
        spmd.stage_partition(2, 4)  # pp stages > layers


def test_trainer_pp_rejected_loudly():
    with pytest.raises(MXNetError, match="PipelineTrainStep"):
        spmd.SpmdStepCompiler.from_shape(None, "dp=2,pp=4")


def test_replica_mesh_alias():
    import jax

    from mxnet_tpu.parallel import mesh as mesh_mod

    devs = jax.devices()[:4]
    m = mesh_mod.replica_mesh(devs)
    assert m.axis_names == ("dp",) and m.shape["dp"] == 4
    m2 = mesh_mod.make_mesh("dp=4,mp=2")
    assert m2.axis_names == ("dp", "mp")


# -- sharding plan ----------------------------------------------------------


def test_sharding_plan_rules():
    from jax.sharding import PartitionSpec as P

    mesh = spmd.make_spmd_mesh("dp=4,mp=2")
    plan = spmd.ShardingPlan(mesh)
    assert plan.param_spec("dense0_weight", (16, 16)) == P("mp", None)
    assert plan.param_spec("blk_out_proj_weight", (16, 16)) == \
        P(None, "mp")
    assert plan.param_spec("dense0_bias", (16,)) == P("mp")
    assert plan.param_spec("odd_weight", (3, 5)) == P()
    # ZeRO composition: 'dp' lands on the first free divisible dim
    assert plan.state_spec("dense0_weight", (16, 16), zero=True) == \
        P("mp", "dp")
    assert plan.state_spec("dense0_weight", (16, 16), zero=False) == \
        P("mp", None)


def test_sharding_plan_override():
    from jax.sharding import PartitionSpec as P

    mesh = spmd.make_spmd_mesh("dp=4,mp=2")
    plan = spmd.ShardingPlan(mesh).override("*_bias", P())
    assert plan.param_spec("dense0_bias", (16,)) == P()
    assert plan.param_spec("dense0_weight", (16, 16)) == P("mp", None)
    with pytest.raises(MXNetError, match="mesh axis"):
        spmd.ShardingPlan(mesh).override("*", P("tp"))


# -- the spmd whole step ----------------------------------------------------


def test_spmd_step_matches_single_device():
    net, tr = build(mesh_shape="dp=4,mp=2", zero=True)
    ref_net, ref_tr = build(whole_step=True)
    for _ in range(5):
        tr.whole_step(net, loss_fn, X, Y)
        ref_tr.whole_step(ref_net, loss_fn, X, Y)
    nd.waitall()
    for w, rw in zip(weights(net), weights(ref_net)):
        assert np.allclose(w, rw, atol=1e-5)


def test_spmd_one_dispatch_no_recompile_under_lr_decay():
    net, tr = build(mesh_shape="dp=4,mp=2", zero=True)
    trainer_mod.reset_trainer_step_stats()
    for _ in range(3):  # warmup: donation twin + donating executable
        tr.whole_step(net, loss_fn, X, Y)
    nd.waitall()
    n0 = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    for i in range(4):
        tr.set_learning_rate(0.05 * (0.9 ** i))  # LR decay: no retrace
        tr.whole_step(net, loss_fn, X, Y)
    nd.waitall()
    assert _imperative.compiled_executable_count() == n0
    assert _imperative.device_dispatch_count() - d0 == 4
    stats = trainer_mod.trainer_step_stats()
    assert stats["spmd_steps"] == 7
    assert stats["whole_step_steps"] == 7
    assert stats["zero_steps"] == 7
    assert stats["whole_step_fallbacks"] == 0


def test_spmd_state_physically_sharded():
    net, tr = build(mesh_shape="dp=4,mp=2", zero=True)
    tr.whole_step(net, loss_fn, X, Y)
    nd.waitall()
    comp = tr._whole_step_compiler
    per_dev = comp.state_bytes_per_device()
    full = sum(int(np.prod(s.shape)) * s.dtype.itemsize
               for gsts in comp._gstates for s in gsts)
    # (16,16) momenta shard 1/8 (mp x dp), (16,) biases 1/2 (mp only):
    # well under half of the full bytes lives on any one device
    assert 0 < per_dev < full / 4


def test_spmd_batch_not_divisible_falls_back_loudly():
    net, tr = build(mesh_shape="dp=4,mp=2")
    trainer_mod.reset_trainer_step_stats()
    x = X[:6]  # 6 % 4 != 0
    y = Y[:6]
    tr.whole_step(net, loss_fn, x, y)
    nd.waitall()
    assert trainer_mod.trainer_step_stats()["whole_step_fallbacks"] == 1


def test_sharding_plan_mesh_mismatch_loud():
    mesh_a = spmd.make_spmd_mesh("dp=4,mp=2")
    with pytest.raises(MXNetError, match="different mesh"):
        build(mesh_shape="dp=2,mp=4",
              sharding_plan=spmd.ShardingPlan(mesh_a))[1].whole_step(
            None, None, X, Y)


# -- elastic mesh reshaping -------------------------------------------------


def test_mesh_resize_round_trip_bit_exact():
    """(dp=4,mp=2) -> (dp=2,mp=2) -> (dp=4,mp=2): params and ZeRO
    optimizer state bit-exact across both reshapes, and training at the
    shrunken shape stays bit-identical to an uninterrupted run at that
    shape (spmd snapshots hold full arrays — the reshard is a remap)."""
    net, tr = build(mesh_shape="dp=4,mp=2", zero=True)
    for _ in range(3):
        tr.whole_step(net, loss_fn, X, Y)
    nd.waitall()
    w0 = weights(net)
    s0 = states(tr)
    blob = host_blob(tr.states_dict())
    assert blob["mesh_shape"] == "dp=4,mp=2"
    params0 = [p.data().asnumpy() for _, p in net._ordered_params()]

    # restore at the surviving shape (half the devices)
    net2, tr2 = build(mesh_shape="dp=2,mp=2", zero=True)
    for (_, p), w in zip(net2._ordered_params(), params0):
        p.set_data(mx.nd.array(w))
    tr2.load_states_dict(blob)
    assert [np.array_equal(a, b) for a, b in
            zip(weights(net2), w0)] == [True] * len(w0)
    for sa, sb in zip(states(tr2), s0):
        for a, b in zip(sa, sb):
            assert np.array_equal(a, b)

    # train one step at the surviving shape; must be bit-identical to
    # an uninjected trainer at that same shape
    ref_net, ref_tr = build(mesh_shape="dp=2,mp=2", zero=True)
    for (_, p), w in zip(ref_net._ordered_params(), params0):
        p.set_data(mx.nd.array(w))
    ref_tr.load_states_dict(host_blob(tr.states_dict()))
    tr2.whole_step(net2, loss_fn, X, Y)
    ref_tr.whole_step(ref_net, loss_fn, X, Y)
    nd.waitall()
    for a, b in zip(weights(net2), weights(ref_net)):
        assert np.array_equal(a, b)

    # grow back to the original shape: still bit-exact adoption
    blob2 = host_blob(tr2.states_dict())
    assert blob2["mesh_shape"] == "dp=2,mp=2"
    net3, tr3 = build(mesh_shape="dp=4,mp=2", zero=True)
    for (n, p), (_, p2) in zip(net3._ordered_params(),
                               net2._ordered_params()):
        p.set_data(mx.nd.array(p2.data().asnumpy()))
    tr3.load_states_dict(blob2)
    for sa, sb in zip(states(tr3), states(tr2)):
        for a, b in zip(sa, sb):
            assert np.array_equal(a, b)
    tr3.whole_step(net3, loss_fn, X, Y)  # and it still steps
    nd.waitall()


def test_env_knob_routes_spmd(monkeypatch):
    monkeypatch.setenv("MXTPU_MESH_SHAPE", "dp=4,mp=2")
    net, tr = build()
    assert tr._mesh_shape == {"dp": 4, "mp": 2}
    trainer_mod.reset_trainer_step_stats()
    tr.whole_step(net, loss_fn, X, Y)
    nd.waitall()
    assert trainer_mod.trainer_step_stats()["spmd_steps"] == 1


def test_supervisor_mesh_shape_rule(monkeypatch):
    from mxnet_tpu.resilience.supervisor import RunContext

    class _Sup:
        _world = 4
        manager = None

    monkeypatch.setenv("MXTPU_MESH_SHAPE", "dp=4,mp=2")
    ctx = RunContext.__new__(RunContext)
    ctx._sup = _Sup()
    assert ctx.mesh_shape() == {"dp": 2, "mp": 2}
    monkeypatch.delenv("MXTPU_MESH_SHAPE")
    assert ctx.mesh_shape() is None


def test_check_mesh_change_paths():
    from mxnet_tpu.checkpoint.reshard import check_mesh_change

    assert check_mesh_change("dp=4,mp=2", {"dp": 2, "mp": 2}) == \
        {"dp": 2, "mp": 2}
    assert check_mesh_change("dp=4,mp=2", None) is None
    assert check_mesh_change(None, None) is None
    # model-parallelism change: allowed, loud (warning), still parses
    assert check_mesh_change("dp=4,mp=2", "dp=2,mp=4") == \
        {"dp": 2, "mp": 4}


# -- pipeline schedule ------------------------------------------------------


def test_pipeline_train_step_loss_decreases():
    import jax

    P_STAGES = 4
    mesh = spmd.make_spmd_mesh({"dp": 2, "pp": P_STAGES},
                               jax.devices())
    rng = np.random.RandomState(0)
    Ws = rng.randn(P_STAGES, 12, 12).astype(np.float32) * 0.3
    bs = np.zeros((P_STAGES, 12), np.float32)

    def stage_fn(params, x):
        import jax.numpy as jnp

        W, b = params
        return jnp.tanh(x @ W + b)

    step = spmd.PipelineTrainStep(stage_fn, mesh, n_micro=4,
                                  momentum=0.9)
    params = (Ws, bs)
    sts = step.init_states(params)
    x = rng.rand(8, 12).astype(np.float32)
    y = rng.rand(8, 12).astype(np.float32)
    losses = []
    for _ in range(10):
        loss, params, sts = step(params, sts, x, y, 0.001)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5


def test_pipeline_train_step_validation():
    import jax

    mesh = spmd.make_spmd_mesh({"dp": 2, "pp": 4}, jax.devices())

    def stage_fn(params, x):
        return x

    step = spmd.PipelineTrainStep(stage_fn, mesh, n_micro=3)
    with pytest.raises(MXNetError, match="divide"):
        step((np.zeros((4, 2, 2), np.float32),), (), np.zeros((8, 2)),
             np.zeros((8, 2)), 0.1)
    mesh_mp = spmd.make_spmd_mesh("dp=4,mp=2")
    with pytest.raises(MXNetError, match="no 'pp' axis"):
        spmd.PipelineTrainStep(stage_fn, mesh_mp)
    mesh_3ax = spmd.make_spmd_mesh("dp=2,mp=2,pp=2")
    with pytest.raises(MXNetError, match="Trainer whole-step"):
        spmd.PipelineTrainStep(stage_fn, mesh_3ax)


def test_pipeline_apply_legacy_import():
    # the old parallel.pipeline path keeps working (shim)
    from mxnet_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
    from mxnet_tpu.parallel.pipeline import stage_partition
    assert stage_partition(4, 2) == ((0, 2), (2, 4))
