"""Multi-axis mesh specs for the spmd whole-step path.

The mesh SHAPE — which named axes exist and how many devices each gets
— is configuration, not code: ``MXTPU_MESH_SHAPE=dp=4,mp=2`` (or the
``Trainer(mesh_shape=...)`` ctor arg) names it, this module parses and
validates it, and ``parallel.mesh.make_mesh`` realizes it over the
device list.  The axis alphabet is fixed so a typo is a loud error, not
a silently replicated axis:

- ``dcn`` — cross-slice/process data axis (outermost; hierarchical
  gradient reduction, see ``parallel.mesh.data_axes``)
- ``dp``  — data parallel: the batch dim shards here; ZeRO-1 optimizer
  state shards here too
- ``mp``  — model/tensor parallel: param dims shard here
  (``plan.ShardingPlan``); XLA inserts the matmul collectives
- ``pp``  — pipeline stages (``spmd.schedule``); the generic
  whole-step cannot auto-stage an arbitrary block, so ``pp > 1`` in a
  Trainer mesh is rejected loudly with a pointer to the schedule API

Elastic resizes change the shape, not just the world size:
:func:`pick_mesh_shape` keeps the MODEL axes (mp/pp — live layouts
partition over them) and shrinks the data axes to the surviving device
count, the rule ``Supervisor`` applies after ``dist.shrink`` (e.g.
(dp=4,mp=2) → (dp=2,mp=2) after losing half the devices).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError, getenv

# the full axis vocabulary, outermost first (mesh axis ORDER is
# meaningful: device coordinates map to axes in this order, and the
# spec string must follow it so two jobs spelling the same shape get
# the same device placement)
AXIS_ORDER = ("dcn", "dp", "mp", "pp")


def parse_mesh_shape(spec):
    """``"dp=4,mp=2"`` → ``{"dp": 4, "mp": 2}`` (insertion-ordered).

    Accepts a dict (validated and passed through) or a spec string.
    Loud errors: empty/malformed entries, an axis outside
    :data:`AXIS_ORDER`, a duplicate axis, a non-positive size, or axes
    out of the canonical order."""
    if isinstance(spec, dict):
        items = [(str(k), v) for k, v in spec.items()]
    else:
        text = str(spec).strip()
        if not text:
            raise MXNetError(
                "empty mesh shape — expected e.g. 'dp=4,mp=2' "
                f"(axes from {AXIS_ORDER})")
        items = []
        for part in text.split(","):
            part = part.strip()
            if "=" not in part:
                raise MXNetError(
                    f"malformed mesh-shape entry {part!r} in {spec!r} "
                    "— expected axis=size, e.g. 'dp=4,mp=2'")
            name, _, val = part.partition("=")
            items.append((name.strip(), val.strip()))
    shape = {}
    for name, val in items:
        if name not in AXIS_ORDER:
            raise MXNetError(
                f"unknown mesh axis {name!r} in {spec!r} — the axis "
                f"alphabet is {AXIS_ORDER} (dcn=cross-slice data, "
                "dp=data, mp=tensor, pp=pipeline)")
        if name in shape:
            raise MXNetError(f"duplicate mesh axis {name!r} in {spec!r}")
        try:
            size = int(val)
        except (TypeError, ValueError):
            raise MXNetError(
                f"mesh axis {name!r} size {val!r} is not an integer "
                f"(in {spec!r})") from None
        if size < 1:
            raise MXNetError(
                f"mesh axis {name!r} size must be >= 1, got {size} "
                f"(in {spec!r})")
        shape[name] = size
    order = [a for a in AXIS_ORDER if a in shape]
    if list(shape) != order:
        raise MXNetError(
            f"mesh axes must follow the canonical order {AXIS_ORDER} "
            f"(outermost first), got {list(shape)} in {spec!r} — two "
            "jobs spelling one shape must agree on device placement")
    return shape


def format_mesh_shape(shape):
    """Inverse of :func:`parse_mesh_shape`: ``{"dp":4,"mp":2}`` →
    ``"dp=4,mp=2"`` (the canonical env-knob spelling)."""
    return ",".join(f"{a}={int(n)}" for a, n in shape.items())


def mesh_shape_from_env():
    """The configured ``MXTPU_MESH_SHAPE`` as a validated dict, or None
    when the knob is unset (single-axis 'dp' semantics everywhere)."""
    spec = getenv("MESH_SHAPE", None)
    if spec is None or not str(spec).strip():
        return None
    return parse_mesh_shape(spec)


def make_spmd_mesh(shape, devices=None):
    """Realize a parsed/spec mesh shape as a ``jax.sharding.Mesh`` over
    ``devices`` (default: all local devices).

    A shape needing MORE devices than available raises loudly (the
    axis-product probe).  A shape covering FEWER takes the first
    axis-product devices — deterministic prefix selection, the contract
    an elastic resize relies on: the surviving shape from
    :func:`pick_mesh_shape` must build on a host whose local device
    count did not shrink (single-process rehearsal, and the restored
    smaller-world job on shared hardware)."""
    from .. import mesh as _mesh_mod

    shape = parse_mesh_shape(shape)
    need = int(np.prod(list(shape.values()) or [1]))
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    if need > len(devices):
        raise MXNetError(
            f"mesh shape {format_mesh_shape(shape)!r} needs {need} "
            f"devices, have {len(devices)}")
    return _mesh_mod.make_mesh(shape, devices[:need])


def model_axes(shape):
    """The non-data axes of a shape dict — the ones an elastic resize
    must PRESERVE (live param/stage layouts partition over them)."""
    return {a: n for a, n in shape.items() if a in ("mp", "pp")}


def pick_mesh_shape(shape, new_world):
    """The mesh shape a resized job runs at: keep every model axis
    (mp/pp), shrink the data axes to fit ``new_world`` devices.

    ``new_world`` must remain a multiple of the model-axis product —
    losing a rank out of an mp/pp group leaves layouts that cannot be
    repartitioned without a full reshard from checkpoint at a smaller
    model parallelism, which is a deliberate decision, not something a
    supervisor should silently pick.  A 'dcn' axis is kept when it
    still divides the data budget and folded into 'dp' otherwise
    (single-slice survivor)."""
    shape = parse_mesh_shape(shape)
    new_world = int(new_world)
    if new_world < 1:
        raise MXNetError(f"cannot shape a mesh over {new_world} devices")
    model = int(np.prod(list(model_axes(shape).values()) or [1]))
    if new_world % model:
        raise MXNetError(
            f"surviving world {new_world} is not a multiple of the "
            f"model-axis product {model} ({format_mesh_shape(model_axes(shape))}) "
            "— an elastic resize only reshapes the data axes; shrink "
            "mp/pp explicitly (new MXTPU_MESH_SHAPE + restore from "
            "checkpoint) to change model parallelism")
    data = new_world // model
    out = {}
    for a, n in shape.items():
        if a in ("mp", "pp"):
            out[a] = n
        elif a == "dcn":
            if data % n == 0 and data // n >= 1 and n <= data:
                out[a] = n
                data //= n
            # else: fold the dcn axis into dp (single-slice survivor)
    out2 = {}
    for a in AXIS_ORDER:
        if a == "dp":
            out2["dp"] = data
        elif a in out:
            out2[a] = out[a]
    return {a: n for a, n in out2.items()
            if a in shape or a == "dp"}
