"""Contrib neural-network blocks (ref: python/mxnet/gluon/contrib/nn/
basic_layers.py)."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn import Embedding


class Concurrent(HybridBlock):
    """Run children on the same input, concat outputs
    (ref: contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            setattr(self, f"c{len(self._layers)}", b)
            self._layers.append(b)

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._layers], dim=self.axis)


class HybridConcurrent(Concurrent):
    """Hybridizable Concurrent (ref: contrib.nn.HybridConcurrent)."""


class Identity(HybridBlock):
    """Pass-through block, useful in Concurrent branches
    (ref: contrib.nn.Identity)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row_sparse gradient (ref: contrib.nn.SparseEmbedding
    — here simply Embedding(sparse_grad=True), the lazy row-update path)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class MoEFFN(HybridBlock):
    """Mixture-of-Experts feed-forward (Switch-style top-1 routing with
    static capacity; GShard einsum dispatch — see parallel/moe.py for
    the expert-parallel sharded form).

    Input (batch, d_model) -> (output (batch, d_model), aux_loss (1,)).
    Add ``aux_weight * aux_loss`` to the training objective for load
    balancing.
    """

    def __init__(self, num_experts, d_model, d_hidden,
                 capacity_factor=1.25, weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        if num_experts < 2:
            raise ValueError("MoEFFN needs >= 2 experts")
        self._cf = float(capacity_factor)
        self.router_weight = self.params.get(
            "router_weight", shape=(d_model, num_experts),
            init=weight_initializer)
        self.w1 = self.params.get(
            "w1", shape=(num_experts, d_model, d_hidden),
            init=weight_initializer)
        self.b1 = self.params.get("b1", shape=(num_experts, d_hidden),
                                  init="zeros")
        self.w2 = self.params.get(
            "w2", shape=(num_experts, d_hidden, d_model),
            init=weight_initializer)
        self.b2 = self.params.get("b2", shape=(num_experts, d_model),
                                  init="zeros")

    def hybrid_forward(self, F, x, router_weight, w1, b1, w2, b2):
        return F._contrib_MoEFFN(x, router_weight, w1, b1, w2, b2,
                                 capacity_factor=self._cf)
