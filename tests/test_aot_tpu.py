"""Offline Mosaic validation: AOT-compile every Pallas kernel family
for a DESCRIBED TPU topology — no chip required (VERDICT r4 #2).

jax.experimental.topologies hands out v5e device descriptions whose
jit/lower/compile path runs the real Mosaic + XLA:TPU compilers
locally (libtpu is in the image).  That converts "will Mosaic reject
this kernel?" from an on-chip question (tests/test_tpu_smoke.py, needs
the tunnel) into a CPU-box regression gate that runs in every suite.
The first chip session proved the two tiers agree: the same lse-tiling
and batched-matmul rejections this file would have caught were hit
live on the v5 lite chip and fixed (see ops/pallas/ docstrings).

Single-device mesh on purpose: Mosaic kernels cannot be automatically
partitioned (multi-chip runs wrap them in shard_map; that composition
is dryrun_multichip's job).
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

try:
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    _TOPO = topologies.get_topology_desc(platform="tpu",
                                         topology_name="v5e:2x2")
    _SKIP = None
except Exception as e:  # pragma: no cover - environment-dependent
    _TOPO, _SKIP = None, str(e)

pytestmark = pytest.mark.skipif(
    _TOPO is None, reason=f"no AOT TPU topology support: {_SKIP}")


@functools.lru_cache(None)
def _sharding():
    mesh = Mesh(np.array(_TOPO.devices[:1]), ("d",))
    return NamedSharding(mesh, PartitionSpec())


def _aot_grad_compile(loss_fn, *specs):
    """value-and-grad of loss_fn AOT-compiled for the v5e target."""
    s = _sharding()
    jitted = jax.jit(jax.grad(loss_fn), in_shardings=(s,) * len(specs),
                     out_shardings=s)
    jitted.lower(*specs).compile()


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("d,causal,masked", [
    (128, False, False), (128, True, False), (128, False, True),
    (64, False, False), (64, True, True),
])
def test_flash_attention_aot(dt, d, causal, masked):
    from mxnet_tpu.ops.pallas.flash_attention import _flash_sdpa

    q = jax.ShapeDtypeStruct((1, 2, 256, d), dt)

    if masked:
        km = jnp.zeros((1, 256), jnp.float32)

        def loss(a):
            return _flash_sdpa(a, a, a, km, causal, 0.125) \
                .astype(jnp.float32).sum()
    else:
        def loss(a):
            return _flash_sdpa(a, a, a, None, causal, 0.125) \
                .astype(jnp.float32).sum()
    _aot_grad_compile(loss, q)


@pytest.mark.parametrize("dt,causal", [
    (jnp.bfloat16, False), (jnp.bfloat16, True), (jnp.float32, True)],
    ids=["bf16", "bf16-causal", "f32-causal"])
def test_flash_streamed_long_context_aot(dt, causal):
    """The STREAMED kernels (K/V swept by a grid dim) Mosaic-compile at
    seq 16384 — past the resident path's VMEM bound; single-chip
    long-context attention with no ceiling."""
    from mxnet_tpu.ops.pallas.flash_attention import _flash_sdpa

    q = jax.ShapeDtypeStruct((1, 1, 16384, 128), dt)

    def loss(a):
        return _flash_sdpa(a, a, a, None, causal, 0.125) \
            .astype(jnp.float32).sum()
    _aot_grad_compile(loss, q)


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_conv_fused_aot(dt):
    from mxnet_tpu.ops.pallas import batch_norm as pbn
    from mxnet_tpu.ops.pallas import conv_fused as cf

    x = jax.ShapeDtypeStruct((512, 256), dt)
    w = jnp.zeros((256, 256), dt)
    sc = jnp.zeros((1, 256), dt)
    sh = jnp.zeros((1, 256), dt)
    _aot_grad_compile(
        lambda a: cf.matmul_bn_stats(a, w)[0].astype(jnp.float32).sum(),
        x)
    _aot_grad_compile(
        lambda a: cf.bn_act_matmul(a, sc, sh, w)
        .astype(jnp.float32).sum(), x)
    _aot_grad_compile(
        lambda a: cf.bn_act_matmul_stats(a, sc, sh, w)[0]
        .astype(jnp.float32).sum(), x)
    _aot_grad_compile(
        lambda a: pbn.bn_stats(a)[0].astype(jnp.float32).sum(), x)


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_pallas_lstm_aot(dt):
    from mxnet_tpu.ops.pallas.rnn import lstm_layer

    T, N, H = 4, 16, 128
    xp = jax.ShapeDtypeStruct((T, N, 4 * H), dt)
    wh = jnp.zeros((4 * H, H), dt)
    h0 = jnp.zeros((N, H), dt)
    c0 = jnp.zeros((N, H), dt)
    _aot_grad_compile(
        lambda a: lstm_layer(a, wh, h0, c0)[0]
        .astype(jnp.float32).sum(), xp)


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_pallas_gru_aot(dt):
    from mxnet_tpu.ops.pallas.rnn import gru_layer

    T, N, H = 4, 16, 128
    xp = jax.ShapeDtypeStruct((T, N, 3 * H), dt)
    wh = jnp.zeros((3 * H, H), dt)
    bh = jnp.zeros((3 * H,), dt)
    h0 = jnp.zeros((N, H), dt)
    _aot_grad_compile(
        lambda a: gru_layer(a, wh, bh, h0)[0]
        .astype(jnp.float32).sum(), xp)


@pytest.mark.slow
@pytest.mark.parametrize("family", ["resnet50", "bert_block"])
def test_whole_graph_aot(family):
    """The full flagship forward graphs also Mosaic/XLA-compile for the
    v5e target (catches non-pallas lowering issues — layout, dtype,
    dynamic shapes — before any chip time is spent): the hybridize-time
    pure graph fn (CachedOp._build_fn) is AOT-jitted for the described
    topology, exactly the computation the chip would run."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.block import CachedOp

    if family == "resnet50":
        from mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet50_v1(layout="NHWC")
        x = nd.ones((4, 64, 64, 3))
    else:
        from mxnet_tpu.models.bert import BERTEncoderLayer

        net = BERTEncoderLayer(units=256, hidden_size=1024, num_heads=4)
        x = nd.ones((4, 32, 256))
    mx.random.seed(0)
    net.initialize(mx.init.Xavier())
    net(x)  # eager shape-inference pass materializes deferred params

    op = CachedOp(net)
    fn = op._build_fn(False)
    raws = [p.data()._data for _, p in net._ordered_params()]
    key = jax.random.PRNGKey(0)

    s = _sharding()
    jitted = jax.jit(functools.partial(fn, _n_params=len(raws)),
                     in_shardings=s, out_shardings=s)
    jitted.lower(key, *raws, x._data).compile()
