"""BERT fine-tuning for sentence(-pair) classification — the GluonNLP
finetune_classifier.py role (the second half of the reference-era BERT
story: pretrain, then fine-tune the pooled [CLS] representation).

Synthetic task by default (runnable with zero data): two-segment word
sequences where the label says whether segment B shares a majority of
words with segment A. With --data, reads a TSV of
``sentence_a<TAB>sentence_b<TAB>label`` (single-sentence rows:
``sentence<TAB>label``), builds a WordPiece vocab from it and
fine-tunes on real text; --params warm-starts the backbone from a
pretraining checkpoint (save_parameters format).

  python examples/bert/finetune_classifier.py --model tiny --steps 30
  python examples/bert/finetune_classifier.py --data pairs.tsv
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import add_cpu_flag, apply_backend  # noqa: E402

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import HybridBlock, nn
from mxnet_tpu.models import bert


class BERTClassifier(HybridBlock):
    """Backbone + dropout + dense over the pooled [CLS] output (ref:
    gluonnlp.model.BERTClassifier)."""

    def __init__(self, backbone, num_classes=2, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.backbone = backbone
        self.dropout = nn.Dropout(dropout)
        self.classifier = nn.Dense(num_classes, flatten=False)

    def hybrid_forward(self, F, inputs, token_types, valid_length=None):
        # a use_decoder=False/use_classifier=False backbone returns
        # (sequence, pooled) — the public gluonnlp contract
        _seq, pooled = self.backbone(inputs, token_types, valid_length)
        return self.classifier(self.dropout(pooled))


def synthetic_pair_batch(rng, bs, seq_len, vocab, n_special=5):
    """Sentence-pair task: the vocab splits into two 'topics'; label 1
    iff both segments come from the SAME topic (entailment-shaped and
    separable from unigram statistics, so a tiny backbone converges in
    a CI-sized run)."""
    half = seq_len // 2
    mid = n_special + (vocab - n_special) // 2
    ranges = [(n_special, mid), (mid, vocab)]
    ids = np.zeros((bs, seq_len), np.int64)
    types = np.zeros((bs, seq_len), np.int64)
    valid = np.full((bs,), seq_len, np.int64)
    labels = rng.randint(0, 2, bs)
    for r in range(bs):
        ta = rng.randint(0, 2)
        tb = ta if labels[r] else 1 - ta
        a = rng.randint(*ranges[ta], size=half - 2)
        b = rng.randint(*ranges[tb], size=seq_len - half - 1)
        row = np.concatenate([[2], a, [3], b, [3]])  # CLS a SEP b SEP
        ids[r, :len(row)] = row
        types[r, half:len(row)] = 1
        valid[r] = len(row)
    return (ids.astype(np.int32), types.astype(np.int32),
            labels.astype(np.float32), valid.astype(np.int32))


def load_tsv(path, tokenizer, seq_len):
    """sentence_a [TAB sentence_b] TAB label -> model tensors.
    Non-conforming lines (headers, GLUE index columns) are skipped and
    counted; an unreadable file fails loudly with a format hint."""
    rows, skipped = [], 0
    with open(path) as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            try:
                if len(parts) == 2:
                    a, b, label = parts[0], None, int(parts[1])
                elif len(parts) == 3:
                    a, b, label = parts[0], parts[1], int(parts[2])
                else:
                    raise ValueError
            except ValueError:
                skipped += 1  # header row / extra columns / bad label
                continue
            rows.append((a, b, label))
    if not rows:
        raise SystemExit(
            f"{path}: no usable rows (skipped {skipped}); expected "
            "sentence_a[<TAB>sentence_b]<TAB>int_label per line")
    if skipped:
        print(f"{path}: skipped {skipped} non-conforming lines")
    cls_id, sep_id = tokenizer.ids["[CLS]"], tokenizer.ids["[SEP]"]
    n = len(rows)
    ids = np.zeros((n, seq_len), np.int32)
    types = np.zeros((n, seq_len), np.int32)
    valid = np.zeros((n,), np.int32)
    labels = np.zeros((n,), np.float32)
    n_classes = 0
    for r, (a, b, label) in enumerate(rows):
        ta = tokenizer.encode(a)
        tb = tokenizer.encode(b) if b else []
        budget = seq_len - (3 if tb else 2)
        while len(ta) + len(tb) > budget:
            (ta if len(ta) >= len(tb) else tb).pop()
        row = [cls_id] + ta + [sep_id] + (tb + [sep_id] if tb else [])
        ids[r, :len(row)] = row
        if tb:
            types[r, len(ta) + 2:len(row)] = 1
        valid[r] = len(row)
        labels[r] = label
        n_classes = max(n_classes, label + 1)
    return ids, types, labels, valid, max(n_classes, 2)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny",
                   choices=["tiny", "base", "large"])
    p.add_argument("--vocab-size", type=int, default=1000)
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=5e-5,
                   help="5e-5 suits warm-started fine-tuning; the "
                        "from-scratch synthetic demo wants ~2e-3")
    p.add_argument("--optimizer", default="adamw",
                   choices=["adamw", "adam", "sgd"])
    p.add_argument("--disp", type=int, default=10)
    p.add_argument("--data", default=None,
                   help="TSV of sentence_a[<TAB>sentence_b]<TAB>label")
    p.add_argument("--params", default=None,
                   help="pretraining checkpoint to warm-start the "
                        "backbone (save_parameters format)")
    p.add_argument("--wordpiece-vocab", type=int, default=4000)
    add_cpu_flag(p)
    args = p.parse_args()
    apply_backend(args)

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    data = None
    if args.data:
        from mxnet_tpu.data import WordPieceTokenizer

        with open(args.data) as f:
            sents = []
            for line in f:
                sents.extend(line.split("\t")[:-1])
        tok = WordPieceTokenizer.build(sents,
                                       vocab_size=args.wordpiece_vocab)
        args.vocab_size = len(tok)
        ids, types, labels, valid, n_cls = load_tsv(
            args.data, tok, args.seq_len)
        args.num_classes = max(args.num_classes, n_cls)
        data = (ids, types, labels, valid)
        print(f"tsv {args.data}: {len(ids)} rows, wordpiece vocab "
              f"{len(tok)}, {args.num_classes} classes")

    # fine-tune backbone: no MLM/NSP heads (gluonnlp convention)
    backbone = getattr(bert, f"bert_{args.model}")(
        vocab_size=args.vocab_size, use_decoder=False,
        use_classifier=False)
    net = BERTClassifier(backbone, num_classes=args.num_classes)
    net.initialize(mx.init.TruncNorm(stdev=0.02))
    if args.params:
        # warm start: load backbone weights (the checkpoint's MLM/NSP
        # head params are ignored), keep the fresh classifier
        net.backbone.load_parameters(args.params,
                                     allow_missing=True,
                                     ignore_extra=True)
        # verify tensors actually landed — allow_missing would let a
        # renamed checkpoint load as a silent no-op
        loaded = {k: v for k, v in nd.load(args.params).items()}
        own = net.backbone._collect_params_with_prefix()
        matched = sum(
            1 for k, v in loaded.items()
            if k in own and v.shape == own[k].data().shape
            and np.allclose(own[k].data().asnumpy(), v.asnumpy()))
        if matched == 0:
            raise SystemExit(
                f"{args.params}: no checkpoint tensor matched the "
                "backbone (renamed layers?); refusing a silent "
                "cold start")
        print(f"warm-started backbone from {args.params} "
              f"({matched} tensors)")

    from mxnet_tpu.parallel import data_parallel

    opt_params = {"learning_rate": args.lr}
    if args.optimizer == "adamw":
        opt_params["wd"] = 0.01
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), args.optimizer,
        opt_params)

    tic, seen, correct = time.time(), 0, 0
    for step in range(args.steps):
        if data is not None:
            pick = rng.randint(0, len(data[0]), args.batch_size)
            ids, types, labels, valid = (d[pick] for d in data)
        else:
            ids, types, labels, valid = synthetic_pair_batch(
                rng, args.batch_size, args.seq_len, args.vocab_size)
        loss = trainer.step((ids, types, valid), labels)
        if step % args.disp == 0 and step:
            loss.wait_to_read()
            print(f"step {step} loss {float(loss.asscalar()):.4f} "
                  f"{args.batch_size * step / (time.time() - tic):.0f} "
                  f"samples/s")
    loss.wait_to_read()

    # train-set accuracy probe through the block (eval path)
    trainer.sync_to_block()
    if data is not None:
        ids, types, labels, valid = (d[:256] for d in data)
    else:
        ids, types, labels, valid = synthetic_pair_batch(
            rng, 256, args.seq_len, args.vocab_size)
    logits = net(nd.array(ids), nd.array(types), nd.array(valid))
    pred = logits.asnumpy().argmax(-1)
    acc = float((pred == labels).mean())
    print(f"done: final loss {float(loss.asscalar()):.4f} "
          f"accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
