"""ZeRO-1 cross-replica weight-update sharding (arXiv 2004.13336).

The contract under test: with ``Trainer(..., zero_shard=True)`` (or
``MXTPU_ZERO_SHARD=1``) the gradient reduction becomes a reduce-scatter,
each replica runs the ``_fk_*`` update kernels only over its 1/world
flat shard, and updated weight shards allgather back — optimizer state
shrinks to ~1/world_size per replica at equal collective bandwidth,
BIT-identical within each tier (sharded whole-step ≡ unsharded
whole-step; sharded eager ≡ unsharded eager), with zero post-warmup
recompiles under LR decay, loud fallback for every ineligible
configuration, and state snapshots that round-trip sharded↔unsharded
through ``states_dict`` and ``CheckpointManager``.
"""
import json
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _imperative, gluon, nd, profiler
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import trainer as trainer_mod

X = np.random.RandomState(1).rand(8, 16).astype(np.float32)
Y = np.random.RandomState(2).rand(8, 4).astype(np.float32)

WORLD = 8
CTXS = [mx.xla(i) for i in range(WORLD)]


def loss_fn(out, y):
    return (out - y) ** 2


def build(zero, whole_step=True, opt="sgd", opt_args=None, ctx=None,
          layers=2, aggregate_num=None, **tkw):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    units = 16
    for _ in range(layers):
        # 13 units: every flat bucket is deliberately NOT a multiple of
        # the 8-rank world, so the zero-pad path is always exercised
        net.add(nn.Dense(13, in_units=units, activation="relu"))
        units = 13
    net.add(nn.Dense(4, in_units=units))
    net.initialize(mx.init.Xavier(), ctx=ctx)
    kwargs = dict(opt_args or {"learning_rate": 0.05, "momentum": 0.9,
                               "wd": 0.01})
    if aggregate_num is not None:
        kwargs["aggregate_num"] = aggregate_num
    tr = gluon.Trainer(net.collect_params(), opt, kwargs,
                       whole_step=whole_step, zero_shard=zero, **tkw)
    return net, tr


def weights(net, ctx=None):
    return [p.data(ctx).asnumpy() if ctx is not None
            else p.data().asnumpy()
            for p in net.collect_params().values()]


@pytest.mark.parametrize("opt,opt_args", [
    ("sgd", {"learning_rate": 0.05, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01}),
    ("adam", {"learning_rate": 0.01, "wd": 0.01}),
])
def test_whole_step_zero_bit_parity_vs_unsharded(opt, opt_args):
    """Sharded whole-step == unsharded whole-step, bit for bit, on the
    virtual 8-device mesh (psum_scatter shares psum's per-element
    reduction order; the update kernels are elementwise on the same
    flat bucket), with every replica context consistent after."""
    net_u, tr_u = build(False, opt=opt, opt_args=opt_args, ctx=CTXS)
    net_z, tr_z = build(True, opt=opt, opt_args=opt_args, ctx=CTXS)
    for _ in range(5):
        lu = tr_u.whole_step(net_u, loss_fn, X, Y)
        lz = tr_z.whole_step(net_z, loss_fn, X, Y)
    np.testing.assert_array_equal(lu.asnumpy(), lz.asnumpy())
    for a, b in zip(weights(net_u, CTXS[0]), weights(net_z, CTXS[0])):
        np.testing.assert_array_equal(a, b)
    for p in net_z.collect_params().values():
        ref = p.data(CTXS[0]).asnumpy()
        for c in CTXS[1:]:
            np.testing.assert_array_equal(p.data(c).asnumpy(), ref)
    assert tr_z.optimizer.num_update == tr_u.optimizer.num_update


def test_eager_zero_bit_parity_vs_eager_unsharded():
    """Sharded eager step == unsharded eager fused step, bit for bit
    (the per-shard pairwise reduce tree keeps the eager slot order)."""
    net_u, tr_u = build(False, whole_step=False, ctx=CTXS)
    net_z, tr_z = build(True, whole_step=False, ctx=CTXS)
    for _ in range(4):
        tr_u.whole_step(net_u, loss_fn, X, Y)
        tr_z.whole_step(net_z, loss_fn, X, Y)
    for a, b in zip(weights(net_u, CTXS[0]), weights(net_z, CTXS[0])):
        np.testing.assert_array_equal(a, b)
    for p in net_z.collect_params().values():
        ref = p.data(CTXS[0]).asnumpy()
        for c in CTXS[1:]:
            np.testing.assert_array_equal(p.data(c).asnumpy(), ref)
    stats = trainer_mod.trainer_step_stats()
    assert stats["zero_fallbacks"] == 0


def test_per_replica_state_bytes_shrink_about_world_size():
    net_u, tr_u = build(False, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    net_z, tr_z = build(True, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    tr_u.whole_step(net_u, loss_fn, X, Y)
    tr_z.whole_step(net_z, loss_fn, X, Y)
    full = tr_u.optimizer_state_bytes()["per_replica"]
    shard = tr_z.optimizer_state_bytes()["per_replica"]
    assert full > 0
    # 1/world plus per-chunk padding: comfortably under half, and
    # within 2x of the ideal 1/8
    assert shard < full / 2
    assert shard <= 2 * (full // WORLD + 64)


def test_zero_no_recompile_one_dispatch_under_lr_decay():
    from mxnet_tpu import lr_scheduler

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(4):
        net.add(nn.Dense(16, in_units=16))
    net.initialize(mx.init.Xavier(), ctx=CTXS)
    sched = lr_scheduler.FactorScheduler(step=3, factor=0.9, base_lr=0.1)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.1, "lr_scheduler": sched},
                       whole_step=True, zero_shard=True)
    y16 = np.random.RandomState(3).rand(8, 16).astype(np.float32)
    for _ in range(3):
        tr.whole_step(net, loss_fn, X, y16)
    nd.waitall()
    lr0 = tr.learning_rate
    trainer_mod.reset_trainer_step_stats()
    c0 = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    for _ in range(12):
        tr.whole_step(net, loss_fn, X, y16)
    nd.waitall()
    stats = trainer_mod.trainer_step_stats()
    assert _imperative.compiled_executable_count() == c0
    assert _imperative.device_dispatch_count() - d0 == 12
    assert stats["zero_steps"] == 12
    assert stats["whole_step_steps"] == 12
    assert stats["zero_fallbacks"] == 0
    assert stats["dispatches_per_step"] == 1.0
    assert tr.learning_rate < lr0


def test_traced_bucket_reduce_scatter_allgather_roundtrip(monkeypatch):
    """The kvstore companion pair vs traced_bucket_allreduce, bit for
    bit, over uneven tensor sizes AND a tiny bucket cap that forces
    multi-bucket packing with per-bucket zero padding."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import kvstore as kv
    from mxnet_tpu.parallel import mesh as mesh_mod

    monkeypatch.setenv("MXTPU_KVSTORE_BUCKET_MB", "0.0001")  # 104 bytes
    devs = jax.devices()[:WORLD]
    mesh = mesh_mod.make_mesh({"dp": len(devs)}, devs)
    shapes = [(13,), (7, 5), (3,), (11,)]
    rng = np.random.RandomState(0)
    per_rank = [[rng.randn(*s).astype(np.float32) for s in shapes]
                for _ in range(WORLD)]

    def rs_ag(*gs):
        shards, metas = kv.traced_bucket_reduce_scatter(
            list(gs), "dp", WORLD)
        assert len(metas) > 1  # the tiny cap split the bucket stream
        for _pos, _shp, total, padded in metas:
            assert padded % WORLD == 0 and padded >= total
        return tuple(kv.traced_allgather(shards, metas, "dp"))

    def ar(*gs):
        return tuple(kv.traced_bucket_allreduce(list(gs), "dp"))

    sharding = NamedSharding(mesh, P("dp"))
    gargs = [
        jax.make_array_from_single_device_arrays(
            (WORLD,) + s, sharding,
            [jax.device_put(per_rank[r][i][None], devs[r])
             for r in range(WORLD)])
        for i, s in enumerate(shapes)]

    sm = mesh_mod.shard_map()
    f1 = jax.jit(sm(lambda gs: rs_ag(*[g[0] for g in gs]), mesh=mesh,
                    in_specs=(P("dp"),), out_specs=P()))
    f2 = jax.jit(sm(lambda gs: ar(*[g[0] for g in gs]), mesh=mesh,
                    in_specs=(P("dp"),), out_specs=P()))
    r1 = f1(tuple(gargs))
    r2 = f2(tuple(gargs))
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_chunk_splitting_under_tiny_bucket_cap(monkeypatch):
    """A tiny MXTPU_KVSTORE_BUCKET_MB splits the zero plan into many
    single-collective chunks — parity must hold regardless."""
    monkeypatch.setenv("MXTPU_KVSTORE_BUCKET_MB", "0.0005")
    net_u, tr_u = build(False, ctx=CTXS, layers=3)
    net_z, tr_z = build(True, ctx=CTXS, layers=3)
    for _ in range(3):
        tr_u.whole_step(net_u, loss_fn, X, Y)
        tr_z.whole_step(net_z, loss_fn, X, Y)
    for a, b in zip(weights(net_u, CTXS[0]), weights(net_z, CTXS[0])):
        np.testing.assert_array_equal(a, b)
    assert len(tr_z._zero_states) > len(net_z.collect_params()) // 4


def test_single_replica_zero_is_silent_identity():
    """World size 1: sharding is the identity — the unsharded program
    runs, bit-identical, with NO fallback counted (not a bypass)."""
    net_u, tr_u = build(False)
    net_z, tr_z = build(True)
    trainer_mod.reset_trainer_step_stats()
    for _ in range(3):
        tr_u.whole_step(net_u, loss_fn, X, Y)
        tr_z.whole_step(net_z, loss_fn, X, Y)
    for a, b in zip(weights(net_u), weights(net_z)):
        np.testing.assert_array_equal(a, b)
    stats = trainer_mod.trainer_step_stats()
    assert stats["zero_steps"] == 0
    assert stats["zero_fallbacks"] == 0


@pytest.mark.parametrize("case", ["amp", "no_fused_kernel",
                                  "compression", "grad_add",
                                  "dist_eager", "sparse_grad",
                                  "sequential"])
def test_zero_bypass_matrix_falls_back_loudly(case):
    """Every ineligible configuration runs the unsharded path for that
    step, books zero_fallbacks, and still trains."""
    tkw = {}
    opt = "lamb" if case == "no_fused_kernel" else "sgd"
    agg = 1 if case == "sequential" else None
    if case == "compression":
        tkw = dict(compression_params={"type": "2bit"})
    if case == "dist_eager":
        tkw = dict(kvstore="dist_sync", update_on_kvstore=False)
    net, tr = build(True, whole_step=False, opt=opt, ctx=CTXS[:4],
                    layers=1, aggregate_num=agg,
                    opt_args={"learning_rate": 0.01}, **tkw)
    if case == "amp":
        from mxnet_tpu.amp import LossScaler

        tr._amp_loss_scaler = LossScaler(init_scale=2.0)
        tr._amp_original_scale = tr._scale
    if case == "grad_add":
        for p in net.collect_params().values():
            p.grad_req = "add"
    if case == "sparse_grad":
        next(iter(net.collect_params().values())).grad_stype = \
            "row_sparse"
    before = weights(net, CTXS[0])
    trainer_mod.reset_trainer_step_stats()
    tr.whole_step(net, loss_fn, X, Y)
    stats = trainer_mod.trainer_step_stats()
    assert stats["zero_steps"] == 0
    assert stats["zero_fallbacks"] >= 1
    after = weights(net, CTXS[0])
    assert any(not np.array_equal(a, b)
               for a, b in zip(before, after))


def test_states_dict_roundtrip_zero_to_unsharded_and_back():
    opt_args = {"learning_rate": 0.01, "wd": 0.01}

    def build_adam(zero):
        return build(zero, opt="adam", opt_args=opt_args, ctx=CTXS)

    cont_net, cont_tr = build_adam(True)
    for _ in range(5):
        cont_tr.whole_step(cont_net, loss_fn, X, Y)
    # zero 3 steps -> snapshot -> restart UNSHARDED for 2 more
    a_net, a_tr = build_adam(True)
    for _ in range(3):
        a_tr.whole_step(a_net, loss_fn, X, Y)
    blob = a_tr.states_dict()
    assert blob["zero"]["world"] == WORLD
    b_net, b_tr = build_adam(False)
    for src, dst in zip(a_net.collect_params().values(),
                        b_net.collect_params().values()):
        dst.set_data(src.data(CTXS[0]))
    b_tr.load_states_dict(blob)
    for _ in range(2):
        b_tr.whole_step(b_net, loss_fn, X, Y)
    for a, b in zip(weights(cont_net, CTXS[0]),
                    weights(b_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)
    # and back: unsharded snapshot resumed SHARDED
    blob2 = b_tr.states_dict()
    assert "zero" not in blob2
    c_net, c_tr = build_adam(True)
    for src, dst in zip(b_net.collect_params().values(),
                        c_net.collect_params().values()):
        dst.set_data(src.data(CTXS[0]))
    c_tr.load_states_dict(blob2)
    for _ in range(2):
        c_tr.whole_step(c_net, loss_fn, X, Y)
    cont2_net, cont2_tr = build_adam(True)
    for _ in range(7):
        cont2_tr.whole_step(cont2_net, loss_fn, X, Y)
    for a, b in zip(weights(cont2_net, CTXS[0]),
                    weights(c_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_manager_roundtrips_sharded_and_unsharded(tmp_path):
    from mxnet_tpu.checkpoint import CheckpointManager

    opt_args = {"learning_rate": 0.01}
    cont_net, cont_tr = build(True, opt="adam", opt_args=opt_args,
                              ctx=CTXS)
    for _ in range(5):
        cont_tr.whole_step(cont_net, loss_fn, X, Y)
    # sharded save -> unsharded restore
    a_net, a_tr = build(True, opt="adam", opt_args=opt_args, ctx=CTXS)
    for _ in range(3):
        a_tr.whole_step(a_net, loss_fn, X, Y)
    d1 = str(tmp_path / "z2u")
    CheckpointManager(d1, keep_n=2).save(3, params=a_net, trainer=a_tr,
                                         sync=True)
    b_net, b_tr = build(False, opt="adam", opt_args=opt_args, ctx=CTXS)
    meta = CheckpointManager(d1, keep_n=2).restore(params=b_net,
                                                   trainer=b_tr)
    assert meta["step"] == 3
    for _ in range(2):
        b_tr.whole_step(b_net, loss_fn, X, Y)
    for a, b in zip(weights(cont_net, CTXS[0]),
                    weights(b_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)
    # unsharded save -> sharded restore
    c_net, c_tr = build(False, opt="adam", opt_args=opt_args, ctx=CTXS)
    for _ in range(3):
        c_tr.whole_step(c_net, loss_fn, X, Y)
    d2 = str(tmp_path / "u2z")
    CheckpointManager(d2, keep_n=2).save(3, params=c_net, trainer=c_tr,
                                         sync=True)
    d_net, d_tr = build(True, opt="adam", opt_args=opt_args, ctx=CTXS)
    CheckpointManager(d2, keep_n=2).restore(params=d_net, trainer=d_tr)
    for _ in range(2):
        d_tr.whole_step(d_net, loss_fn, X, Y)
    for a, b in zip(weights(cont_net, CTXS[0]),
                    weights(d_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_checkpoint_gathers_shards_across_rank_files(tmp_path):
    """The gather-on-restore path: ZeRO shards split across multiple
    trainer-shard<r>.states files (the multi-process layout) are merged
    back before the load."""
    from mxnet_tpu.checkpoint import CheckpointManager

    a_net, a_tr = build(True, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    for _ in range(3):
        a_tr.whole_step(a_net, loss_fn, X, Y)
    d = str(tmp_path)
    CheckpointManager(d, keep_n=2).save(3, params=a_net, trainer=a_tr,
                                        sync=True)
    ckpt = os.path.join(d, "ckpt-00000003")
    tfile = os.path.join(ckpt, "trainer-shard0.states")
    with open(tfile, "rb") as f:
        blob = pickle.load(f)
    shards = blob["zero"]["shards"]
    low = {r: v for r, v in shards.items() if int(r) < WORLD // 2}
    high = {r: v for r, v in shards.items() if int(r) >= WORLD // 2}
    blob["zero"]["shards"] = low
    with open(tfile, "wb") as f:
        pickle.dump(blob, f)
    peer = dict(blob)
    peer["zero"] = dict(blob["zero"], shards=high)
    with open(os.path.join(ckpt, "trainer-shard1.states"), "wb") as f:
        pickle.dump(peer, f)
    b_net, b_tr = build(False, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    CheckpointManager(d, keep_n=2).restore(params=b_net, trainer=b_tr)
    # continue and compare against the uninterrupted sharded run
    for _ in range(2):
        b_tr.whole_step(b_net, loss_fn, X, Y)
    cont_net, cont_tr = build(True, opt="adam",
                              opt_args={"learning_rate": 0.01},
                              ctx=CTXS)
    for _ in range(5):
        cont_tr.whole_step(cont_net, loss_fn, X, Y)
    for a, b in zip(weights(cont_net, CTXS[0]),
                    weights(b_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_partial_shard_blob_raises_actionable_error():
    net, tr = build(True, opt="adam",
                    opt_args={"learning_rate": 0.01}, ctx=CTXS)
    for _ in range(2):
        tr.whole_step(net, loss_fn, X, Y)
    blob = tr.states_dict()
    blob["zero"]["shards"] = {0: blob["zero"]["shards"][0]}
    net2, tr2 = build(False, opt="adam",
                      opt_args={"learning_rate": 0.01}, ctx=CTXS)
    with pytest.raises(mx.MXNetError, match="CheckpointManager"):
        tr2.load_states_dict(blob)


def test_world_size_mismatch_strict_topology_names_sizes(tmp_path):
    """A world-size mismatch RESHARDS by default now (elastic restore);
    strict_topology=True restores the loud rejection, naming both
    sizes and the escape hatch."""
    from mxnet_tpu.checkpoint import CheckpointManager

    net, tr = build(True, ctx=CTXS)
    tr.whole_step(net, loss_fn, X, Y)
    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save(1, params=net, trainer=tr, sync=True)
    mpath = os.path.join(str(tmp_path), "ckpt-00000001",
                         "MANIFEST.json")
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["num_processes"] = 16
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    net2, tr2 = build(True, ctx=CTXS)
    with pytest.raises(mx.MXNetError) as ei:
        CheckpointManager(str(tmp_path), keep_n=2).restore(
            step=1, params=net2, trainer=tr2, strict_topology=True)
    msg = str(ei.value)
    assert "16-process" in msg or "by a 16" in msg
    assert "1 process" in msg
    assert "strict_topology" in msg
    # default: the SAME restore reshards instead of raising (rank 0
    # reads saved shard 0 — the rank-replicated remap)
    net3, tr3 = build(True, ctx=CTXS)
    meta = CheckpointManager(str(tmp_path), keep_n=2).restore(
        step=1, params=net3, trainer=tr3)
    assert meta["step"] == 1
    for a, b in zip(weights(net, CTXS[0]), weights(net3, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_unsharded_snapshot_supersedes_live_shards():
    """Loading an UNSHARDED states blob into a trainer with live ZeRO
    shards must drop the shards (review finding): the loaded snapshot,
    not the stale shard momentum, drives the next steps."""
    opt_args = {"learning_rate": 0.01, "wd": 0.01}
    src_net, src_tr = build(False, opt="adam", opt_args=opt_args,
                            ctx=CTXS)
    src_tr.whole_step(src_net, loss_fn, X, Y)
    blob = src_tr.states_dict()
    tgt_net, tgt_tr = build(True, opt="adam", opt_args=opt_args,
                            ctx=CTXS)
    for _ in range(3):
        tgt_tr.whole_step(tgt_net, loss_fn, X, Y)
    assert tgt_tr._zero_states
    for src, dst in zip(src_net.collect_params().values(),
                        tgt_net.collect_params().values()):
        dst.set_data(src.data(CTXS[0]))
    tgt_tr.load_states_dict(blob)
    assert not tgt_tr._zero_states  # stale shards dropped
    for _ in range(2):
        tgt_tr.whole_step(tgt_net, loss_fn, X, Y)
    ref_net, ref_tr = build(False, opt="adam", opt_args=opt_args,
                            ctx=CTXS)
    for _ in range(3):
        ref_tr.whole_step(ref_net, loss_fn, X, Y)
    for a, b in zip(weights(ref_net, CTXS[0]),
                    weights(tgt_net, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_unsharded_fallback_after_sharded_steps_unshards_state():
    """When an unsharded update path engages after sharded steps (a
    bypass mid-run), the live shards are gathered back into canonical
    states — the SAME trajectory continues bit-exactly instead of a
    silently re-zeroed momentum (review finding)."""
    net_z, tr_z = build(True, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    net_u, tr_u = build(False, opt="adam",
                        opt_args={"learning_rate": 0.01}, ctx=CTXS)
    for _ in range(3):
        tr_z.whole_step(net_z, loss_fn, X, Y)
        tr_u.whole_step(net_u, loss_fn, X, Y)
    assert tr_z._zero_states
    # force the unsharded eager path mid-run on the sharded trainer
    tr_z._zero_shard = False
    tr_z._whole_step = False
    tr_u._zero_shard = False
    tr_u._whole_step = False
    for _ in range(2):
        tr_z.whole_step(net_z, loss_fn, X, Y)
        tr_u.whole_step(net_u, loss_fn, X, Y)
    assert not tr_z._zero_states  # gathered back, not duplicated
    for a, b in zip(weights(net_u, CTXS[0]), weights(net_z, CTXS[0])):
        np.testing.assert_array_equal(a, b)


def test_env_knob_precedence(monkeypatch):
    monkeypatch.setenv("MXTPU_ZERO_SHARD", "1")
    _, tr = build(None)
    assert tr._zero_shard
    monkeypatch.setenv("MXTPU_ZERO_SHARD", "0")
    _, tr2 = build(None)
    assert not tr2._zero_shard
    monkeypatch.setenv("MXTPU_ZERO_SHARD", "1")
    _, tr3 = build(False)
    assert not tr3._zero_shard  # explicit ctor arg beats env


def test_profiler_zero_counters_window_scoped():
    trainer_mod.reset_trainer_step_stats()
    net, tr = build(True, ctx=CTXS)
    tr.whole_step(net, loss_fn, X, Y)
    tr.whole_step(net, loss_fn, X, Y)
    out = json.loads(profiler.dumps(reset=True))
    ts = out["trainerStep"]
    assert ts["zero_steps"] == 2
    assert ts["zero_fallbacks"] == 0
    again = json.loads(profiler.dumps(reset=True))["trainerStep"]
    assert again["zero_steps"] == 0
