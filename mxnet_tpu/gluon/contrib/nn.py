"""Contrib neural-network blocks (ref: python/mxnet/gluon/contrib/nn/
basic_layers.py)."""
from __future__ import annotations

from .. import nn as _nn
from ..block import HybridBlock
from ..nn import Embedding


class Concurrent(HybridBlock):
    """Run children on the same input, concat outputs
    (ref: contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            setattr(self, f"c{len(self._layers)}", b)
            self._layers.append(b)

    def hybrid_forward(self, F, x):
        return F.concat(*[b(x) for b in self._layers], dim=self.axis)


class HybridConcurrent(Concurrent):
    """Hybridizable Concurrent (ref: contrib.nn.HybridConcurrent)."""


class Identity(HybridBlock):
    """Pass-through block, useful in Concurrent branches
    (ref: contrib.nn.Identity)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Embedding):
    """Embedding with row_sparse gradient (ref: contrib.nn.SparseEmbedding
    — here simply Embedding(sparse_grad=True), the lazy row-update path)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class MoEFFN(HybridBlock):
    """Mixture-of-Experts feed-forward (Switch top-1 or GShard top-2
    routing via ``top_k``, static capacity; GShard einsum dispatch —
    see parallel/moe.py for the expert-parallel sharded form).

    Input (batch, d_model) -> (output (batch, d_model), aux_loss (1,)).
    Add ``aux_weight * aux_loss`` to the training objective for load
    balancing.
    """

    def __init__(self, num_experts, d_model, d_hidden,
                 capacity_factor=1.25, top_k=1, weight_initializer=None,
                 **kwargs):
        super().__init__(**kwargs)
        if num_experts < 2:
            raise ValueError("MoEFFN needs >= 2 experts")
        self._cf = float(capacity_factor)
        self._top_k = int(top_k)
        self.router_weight = self.params.get(
            "router_weight", shape=(d_model, num_experts),
            init=weight_initializer)
        self.w1 = self.params.get(
            "w1", shape=(num_experts, d_model, d_hidden),
            init=weight_initializer)
        self.b1 = self.params.get("b1", shape=(num_experts, d_hidden),
                                  init="zeros")
        self.w2 = self.params.get(
            "w2", shape=(num_experts, d_hidden, d_model),
            init=weight_initializer)
        self.b2 = self.params.get("b2", shape=(num_experts, d_model),
                                  init="zeros")

    def hybrid_forward(self, F, x, router_weight, w1, b1, w2, b2):
        return F._contrib_MoEFFN(x, router_weight, w1, b1, w2, b2,
                                 capacity_factor=self._cf,
                                 top_k=self._top_k)


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device Batch Normalization (ref: contrib.nn.SyncBatchNorm,
    src/operator/contrib/sync_batch_norm.cc).

    TPU-native: under the compiled SPMD step the batch axis is sharded,
    so the stats reductions are already global — this block then equals
    BatchNorm.  Pass ``axis_name`` to pmean the per-shard statistics
    when running under an explicit ``shard_map``/``pmap`` axis instead.
    ``num_devices`` is accepted for API parity (the reference uses it to
    size the key-value reduction); it does not change the math here.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", axis_name=None,
                 **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._kwargs = {"eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats,
                        "ndev": num_devices or 1}
        if axis_name is not None:
            self._kwargs["axis_name"] = axis_name

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.contrib.SyncBatchNorm(x, gamma, beta, running_mean,
                                       running_var, **self._kwargs)


class PixelShuffle1D(HybridBlock):
    """Upsample 1D by rearranging channels into length
    (ref: contrib.nn.PixelShuffle1D).  (N, C*f, W) -> (N, C, W*f)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        x = F.reshape(x, shape=(0, -4, -1, f, 0))      # (N, C, f, W)
        x = F.transpose(x, axes=(0, 1, 3, 2))          # (N, C, W, f)
        return F.reshape(x, shape=(0, 0, -3))          # (N, C, W*f)

    def __repr__(self):
        return f"PixelShuffle1D({self._factor})"


class PixelShuffle2D(HybridBlock):
    """Upsample 2D: (N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2)
    (ref: contrib.nn.PixelShuffle2D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            f1, f2 = factor
        except TypeError:
            f1 = f2 = factor
        self._factors = (int(f1), int(f2))

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        # (N, C, f1, f2, H, W) -> (N, C, H, f1, W, f2) -> merge
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return F.reshape(x, shape=(0, 0, -3, -3))

    def __repr__(self):
        return f"PixelShuffle2D({self._factors})"


class PixelShuffle3D(HybridBlock):
    """Upsample 3D: (N, C*f1*f2*f3, D, H, W) -> (N, C, D*f1, H*f2, W*f3)
    (ref: contrib.nn.PixelShuffle3D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            f1, f2, f3 = factor
        except TypeError:
            f1 = f2 = f3 = factor
        self._factors = (int(f1), int(f2), int(f3))

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, -4, f1, f2 * f3, 0, 0, 0))
        x = F.reshape(x, shape=(0, 0, 0, -4, f2, f3, 0, 0, 0))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(0, 0, -3, -3, -3))

    def __repr__(self):
        return f"PixelShuffle3D({self._factors})"
