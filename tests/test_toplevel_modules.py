"""Top-level compat modules: name / model / executor / libinfo / log /
util / rtc (ref: python/mxnet/{name,model,executor,libinfo,log,util,
rtc}.py)."""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_name_manager_prefix_scopes_symbol_names():
    from mxnet_tpu.name import NameManager, Prefix

    with NameManager():  # fresh counters, hermetic w.r.t. other tests
        a = mx.sym.Variable("x") + 1
        base = a.name
        with Prefix("enc_"):
            b = mx.sym.Variable("y") + 1
            assert b.name.startswith("enc_")
        c = mx.sym.Variable("z") + 1
        assert not c.name.startswith("enc_")
        assert c.name != base  # counter advanced in the outer scope


def test_name_manager_explicit_name_wins():
    from mxnet_tpu.name import NameManager, Prefix

    with Prefix("p_"):
        assert NameManager.current().get("explicit", "hint") == "explicit"
        assert NameManager.current().get(None, "hint") == "p_hint0"


def test_model_checkpoint_roundtrip(tmp_path):
    x = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(x, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg = {"fc_weight": mx.nd.ones((3, 4)), "fc_bias": mx.nd.zeros((3,))}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 7, net, arg, {})
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert sorted(arg2) == sorted(arg)
    np.testing.assert_allclose(arg2["fc_weight"].asnumpy(),
                               np.ones((3, 4)))
    assert sym2.tojson() == net.tojson()


def test_feedforward_fit_predict(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    w = rng.randn(8, 2).astype(np.float32)
    y = (X @ w).argmax(axis=1).astype(np.float32)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    ff = mx.model.FeedForward(net, num_epoch=8, optimizer="adam",
                              learning_rate=0.01, numpy_batch_size=16)
    ff.fit(X, y)
    preds = ff.predict(X)
    assert preds.shape == (64, 2)
    acc = float((preds.argmax(axis=1) == y).mean())
    assert acc > 0.8, f"FeedForward failed to fit a linear task: {acc}"

    prefix = str(tmp_path / "ff")
    ff.save(prefix)
    ff2 = mx.model.FeedForward.load(prefix, 8, numpy_batch_size=16)
    preds2 = ff2.predict(X)
    np.testing.assert_allclose(preds2, preds, atol=1e-5)


def test_feedforward_score_after_load(tmp_path):
    rng = np.random.RandomState(2)
    X = rng.randn(32, 4).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=2, name="fc"),
        name="softmax")
    ff = mx.model.FeedForward(net, num_epoch=4, numpy_batch_size=8,
                              learning_rate=0.1)
    ff.fit(X, y)
    prefix = str(tmp_path / "sc")
    ff.save(prefix)
    ff2 = mx.model.FeedForward.load(prefix, 4, numpy_batch_size=8)
    # raw arrays work (dummy labels injected for the loss head)...
    acc = ff2.score(mx.io.NDArrayIter(X, y, batch_size=8))
    assert 0.0 <= acc <= 1.0
    # ...but a label-less DataIter after load() raises pointedly
    ff3 = mx.model.FeedForward.load(prefix, 4, numpy_batch_size=8)
    with pytest.raises(mx.MXNetError, match="label"):
        ff3.predict(mx.io.NDArrayIter(X, batch_size=8))


def test_log_reconfigure_to_file(tmp_path):
    lg = mx.log.get_logger("mxtpu_file_logger", level=mx.log.INFO)
    f = str(tmp_path / "train.log")
    lg2 = mx.log.get_logger("mxtpu_file_logger", filename=f,
                            level=mx.log.INFO)
    lg2.info("to file")
    lg2.handlers[0].flush()
    assert lg2 is lg and len(lg.handlers) == 1
    with open(f) as fh:
        assert "to file" in fh.read()


def test_executor_module_alias():
    from mxnet_tpu.executor import Executor
    from mxnet_tpu.symbol.symbol import Executor as SymExecutor

    assert Executor is SymExecutor
    assert mx.executor.Executor is SymExecutor


def test_libinfo_find_lib_path():
    paths = mx.libinfo.find_lib_path()
    assert paths and all(os.path.exists(p) for p in paths)
    assert any(p.endswith("libmxtpu_engine.so") for p in paths)
    assert os.path.isdir(mx.libinfo.find_include_path())
    assert mx.libinfo.__version__


def test_log_get_logger(capsys):
    lg = mx.log.get_logger("mxtpu_test_logger", level=mx.log.INFO)
    assert lg.level == logging.INFO
    lg2 = mx.log.get_logger("mxtpu_test_logger", level=mx.log.DEBUG)
    assert lg2 is lg and lg2.level == logging.DEBUG
    assert len(lg.handlers) == 1  # reconfigure does not stack handlers


def test_util_helpers(tmp_path):
    d = tmp_path / "a" / "b"
    mx.util.makedirs(str(d))
    mx.util.makedirs(str(d))  # idempotent
    assert d.is_dir()
    assert mx.util.is_np_array() is False
    assert mx.util.is_np_shape() is False

    @mx.util.use_np_shape
    def f(v):
        return v + 1

    assert f(1) == 2


def test_rtc_raises_pointed_error():
    with pytest.raises(mx.MXNetError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void k() {}")


def test_nd_sym_linalg_namespace():
    """mx.nd.linalg.X / mx.sym.linalg.X (ref: python/mxnet/ndarray/
    linalg.py) resolve registry ops under either alias spelling."""
    import numpy as np

    out = mx.nd.linalg.gemm2(mx.nd.ones((2, 3)), mx.nd.ones((3, 4)))
    assert out.shape == (2, 4)
    assert float(out.asnumpy()[0, 0]) == 3.0
    chol = mx.nd.linalg.potrf(mx.nd.array([[4.0, 0.0], [0.0, 9.0]]))
    assert np.allclose(chol.asnumpy().diagonal(), [2.0, 3.0])
    s = mx.sym.linalg.gemm2(mx.sym.var("a"), mx.sym.var("b"))
    assert s is not None
    try:
        mx.nd.linalg.no_such_op
        raise AssertionError("expected AttributeError")
    except AttributeError as e:
        assert "linalg namespace" in str(e)


def test_one_hot_positional_depth():
    """mx.nd.one_hot(indices, depth) — depth positional, the reference
    signature (indexing_op.cc OneHotParam)."""
    import numpy as np

    oh = mx.nd.one_hot(mx.nd.array([1, 2]), 4)
    assert oh.shape == (2, 4)
    assert np.allclose(oh.asnumpy()[0], [0, 1, 0, 0])
    oh2 = mx.nd.one_hot(mx.nd.array([0]), depth=3, on_value=5.0)
    assert oh2.asnumpy()[0, 0] == 5.0
    assert mx.sym.one_hot(mx.sym.var("i"), 4) is not None


def test_mixed_initializer():
    """mx.init.Mixed: first-matching-pattern dispatch; each matched
    sub-initializer still applies its own name conventions (bias→0),
    exactly as the reference's Mixed does."""
    import numpy as np

    mixed = mx.init.Mixed([".*weight", ".*"],
                          [mx.init.One(), mx.init.Zero()])
    net = mx.gluon.nn.Dense(3, in_units=2)
    net.initialize(mixed)
    assert (net.weight.data().asnumpy() == 1).all()
    assert (net.bias.data().asnumpy() == 0).all()
    with pytest.raises(ValueError, match="pair up"):
        mx.init.Mixed(["x"], [])


def test_nd_image_namespace():
    """mx.nd.image.* (ref: python/mxnet/ndarray/image.py): functional
    forms of the vision transforms."""
    img = mx.nd.array((np.random.rand(32, 24, 3) * 255)
                      .astype(np.uint8))
    t = mx.nd.image.to_tensor(img)
    assert t.shape == (3, 32, 24)
    assert float(t.asnumpy().max()) <= 1.0
    n = mx.nd.image.normalize(
        t, mean=np.array([0.5] * 3, np.float32),
        std=np.array([0.2] * 3, np.float32))
    assert n.shape == (3, 32, 24)
    r = mx.nd.image.resize(img, (16, 16))
    assert r.shape[:2] == (16, 16)
    nb = mx.nd.image.normalize(
        mx.nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32)),
        mean=np.array([0.5] * 3, np.float32),
        std=np.array([0.2] * 3, np.float32))
    assert nb.shape == (2, 3, 8, 8)
