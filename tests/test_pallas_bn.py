"""Pallas BN-stats kernel parity (interpret mode on CPU)."""
import numpy as np

import jax
import jax.numpy as jnp



def test_bn_stats_matches_jnp(interpret_pallas):
    from mxnet_tpu.ops.pallas import batch_norm as pbn

    rng = np.random.RandomState(0)
    x = rng.randn(512, 64).astype(np.float32) * 3 + 1
    s, q = pbn.bn_stats(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(s), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(q), (x * x).sum(0), rtol=1e-5)


def test_bn_stats_bf16_accumulates_f32(interpret_pallas):
    from mxnet_tpu.ops.pallas import batch_norm as pbn

    rng = np.random.RandomState(1)
    x = rng.randn(2048, 128).astype(np.float32)
    s, q = pbn.bn_stats(jnp.asarray(x, jnp.bfloat16))
    assert s.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(s),
                               x.astype(jnp.bfloat16).astype(np.float32)
                               .sum(0), rtol=2e-2)


def test_bn_stats_gradient(interpret_pallas):
    from mxnet_tpu.ops.pallas import batch_norm as pbn

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(64, 8).astype(np.float32))

    def loss_pallas(x):
        s, q = pbn.bn_stats(x)
        return (s * 0.5).sum() + (q * 0.25).sum()

    def loss_ref(x):
        return (x.sum(0) * 0.5).sum() + ((x * x).sum(0) * 0.25).sum()

    g1 = jax.grad(loss_pallas)(x)
    g2 = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


def test_batch_norm_pallas_path_parity(interpret_pallas, monkeypatch):
    """_k_batch_norm with MXTPU_BN_STATS=pallas equals the jnp path."""
    monkeypatch.setenv("MXTPU_BN_STATS", "pallas")
    from mxnet_tpu.ops.nn import _k_batch_norm

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 6, 6, 32).astype(np.float32))
    gamma = jnp.asarray(rng.rand(32).astype(np.float32))
    beta = jnp.asarray(rng.rand(32).astype(np.float32))
    mm = jnp.zeros(32)
    mv = jnp.ones(32)
    out_p = _k_batch_norm(x, gamma, beta, mm, mv, axis=-1,
                          fix_gamma=False, _train=True)
    monkeypatch.setenv("MXTPU_BN_STATS", "jnp")
    out_j = _k_batch_norm(x, gamma, beta, mm, mv, axis=-1,
                          fix_gamma=False, _train=True)
    for a, b in zip(out_p, out_j):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_stats_supported_gate():
    from mxnet_tpu.ops.pallas import batch_norm as pbn

    assert pbn.stats_supported(4096, 256)
    assert not pbn.stats_supported(7, 256)  # no dividing block
