"""ModelServer: dynamic-batching inference serving for hybridized blocks.

Request path (docs/serving.md has the full workflow)::

    submit(example) -> bounded queue -> batcher thread coalesces
    -> pad into a (batch, length) bucket -> ONE compiled forward
    -> split + unpad -> per-request Future resolves with numpy output

The compiled surface is closed by construction: every bucket in the
:class:`~mxnet_tpu.serve.buckets.BucketSpec` grid is compiled once at
``start()`` (AOT warmup), after which a mixed-shape request stream runs
with zero new XLA compilations — verified through the CachedOp
compile/reuse counters this server surfaces in ``stats()``.

Production hardening:

- **backpressure** — the queue is bounded; ``submit()`` on a full queue
  raises :class:`ServerOverloadedError` immediately (fail fast beats
  silent latency collapse).
- **deadlines** — ``submit(..., deadline_ms=)``; a request whose
  deadline passes while queued fails with
  :class:`DeadlineExceededError` and never wastes device time.
- **graceful drain** — ``shutdown(drain=True)`` stops admissions,
  finishes every queued request, and leaves zero in-flight work.
- **hot reload** — ``reload_weights()`` swaps parameters from
  ``CheckpointManager.latest()`` between batches; in-flight and queued
  requests are never dropped, and no recompile happens (parameters are
  runtime inputs of the compiled graph, not constants).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np

from .. import profiler
from ..base import MXNetError, getenv
from ..ndarray.ndarray import NDArray, array as _nd_array
from ..telemetry import tracer as _tracer
from .batcher import (Batcher, DeadlineExceededError, _Request,
                      ServerClosedError, ServerOverloadedError)
from .buckets import BucketSpec
from .stats import ServerStats

#: compute + readback allowance added to a deadline-derived predict()
#: wait: the deadline bounds QUEUE time (checked at dequeue), so an
#: admitted batch still needs room to execute before the caller-side
#: wait may conclude the server is wedged
PREDICT_GRACE_S = 5.0


def _int8_batch_hook(block):
    """The `quantize`-section booking hook for a served net, or None
    for fp32 nets (call sites guard on the server's ``_int8`` flag).
    Resolved once per server: a ``quantize_net`` output's construction
    already imported the quantization tier, so serve stays free of the
    import otherwise."""
    if not getattr(block, "_int8_quantized", False):
        return None
    from ..contrib.quantization import note_int8_serve_batch

    return note_int8_serve_batch


class ModelServer:
    """Serve a gluon block behind an async dynamically-batched queue.

    Parameters
    ----------
    block : gluon.Block
        The model.  HybridBlocks are hybridized (one compiled XLA
        computation per bucket); SymbolBlocks loaded from an exported
        checkpoint work unchanged.  Must be initialized.
    spec : BucketSpec
        The closed set of padded shapes to compile and serve.
    max_queue : int
        Bound on queued requests before submit() fails fast.
    linger_ms : float, optional
        How long the batcher waits for concurrent submitters to
        coalesce once the first request of a batch arrives.  Defaults
        to ``MXTPU_SERVE_LINGER_MS`` (2.0) — env-backed so the
        autotuner's ``serve_linger_ms`` knob reaches servers built
        after a recommendation is applied.
    ctx : Context, optional
        Device for the padded input batches.
    checkpoint : CheckpointManager or str, optional
        Source for ``reload_weights()``; a str is a checkpoint
        directory wrapped in a manager.
    """

    def __init__(self, block, spec, max_queue=256, linger_ms=None,
                 ctx=None, checkpoint=None):
        if not isinstance(spec, BucketSpec):
            raise MXNetError("spec must be a serve.BucketSpec")
        if linger_ms is None:
            linger_ms = getenv("SERVE_LINGER_MS", 2.0, float)
        self._net = block
        self._spec = spec
        self._ctx = ctx
        # quantize_net marks its output; an int8 net books its batches
        # into the `quantize` profiler section and hot-reloads fp32
        # training checkpoints via re-quantization
        self._int8 = bool(getattr(block, "_int8_quantized", False))
        self._note_int8 = _int8_batch_hook(block)
        self._batcher = Batcher(max_queue=max_queue, linger_ms=linger_ms)
        self._stats = ServerStats()
        self._exec_lock = threading.Lock()   # batch exec XOR reload
        self._if_lock = threading.Lock()
        self._in_flight = 0
        self._started = False
        self._closing = False
        self._abort = False
        self._worker = None
        self._warmup_compiles = 0
        self._metrics_collector = None
        if isinstance(checkpoint, str):
            from ..checkpoint import CheckpointManager

            checkpoint = CheckpointManager(checkpoint)
        self._ckpt = checkpoint

    # -- lifecycle ----------------------------------------------------------

    def start(self, warmup=True):
        """Hybridize, AOT-compile every bucket, start the batcher thread.

        A drained/shut-down server can be start()ed again: the request
        queue reopens and the bucket executables compiled the first time
        around are reused, so a restart does zero new XLA compiles.
        """
        if self._started:
            raise MXNetError("ModelServer already started")
        self._abort = False
        self._batcher.reopen()
        if hasattr(self._net, "hybridize") and \
                not getattr(self._net, "_active", False):
            self._net.hybridize()
        if warmup:
            self._warmup()
        self._warmup_compiles = self._graph_stats().get("compiles", 0)
        self._started = True
        self._closing = False
        if self._metrics_collector is None:
            # export stats() on the /metrics endpoint (weakly held:
            # a dropped server leaves the scrape automatically)
            from ..telemetry import metrics as _metrics

            self._metrics_collector = _metrics.register_server(self)
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="mxtpu-serve-batcher",
                                        daemon=True)
        self._worker.start()
        return self

    def _warmup(self):
        """Run one dummy batch per bucket so every executable exists
        before traffic arrives (smallest shape first: a broken model
        fails fast, not after the big compiles)."""
        with profiler.op_scope("serve.warmup", cat="serve"):
            for shape in self._spec.bucket_shapes():
                x = _nd_array(
                    np.full(shape, self._spec.pad_value,
                            dtype=self._spec.dtype), ctx=self._ctx)
                out = self._net(x)
                for o in (out if isinstance(out, (list, tuple)) else [out]):
                    if isinstance(o, NDArray):
                        o.wait_to_read()
                self._stats.incr("warmup_batches")

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc == (None, None, None))
        return False

    def drain(self, timeout=None):
        """Stop admissions and block until every accepted request has
        resolved; the server ends with zero queued/in-flight work."""
        self._closing = True
        self._batcher.close()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                raise MXNetError("drain timed out with work still queued")
            self._worker = None
        self._started = False

    def shutdown(self, drain=True, timeout=None):
        if not self._started and self._worker is None:
            return
        if drain:
            self.drain(timeout)
            return
        # abrupt: fail whatever is still queued
        self._closing = True
        self._abort = True
        self._batcher.close()
        if self._worker is not None:
            self._worker.join(timeout)
            self._worker = None
        self._started = False
        while True:
            group, expired = self._batcher.next_group(
                self._spec.max_batch, timeout=0)
            if not group and not expired:
                break
            for req in group + expired:
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(
                        ServerClosedError("server shut down"))
                self._stats.incr("cancelled")
                _tracer.request_end("serve.request", req.trace_id,
                                    cat="serve", outcome="cancelled")

    # -- request path -------------------------------------------------------

    def submit(self, example, deadline_ms=None):
        """Queue one request (shape = spec.example_shape, no batch dim);
        returns a Future resolving to the request's numpy output(s)."""
        if not self._started or self._closing:
            raise ServerClosedError(
                "ModelServer is not accepting requests (not started, "
                "draining, or shut down)")
        if isinstance(example, NDArray):
            example = example.asnumpy()
        example = np.asarray(example, dtype=self._spec.dtype)
        length = self._spec.validate(example)
        self._stats.record_request_shape(length)
        req = _Request(example, length, Future(), deadline_ms=deadline_ms)
        # request-shape attrs ride on the span: the autotuner's
        # observed-traffic histogram (ROADMAP item 5) reads them back
        # out of exported traces
        req.trace_id = _tracer.request_begin(
            "serve.request", cat="serve", length=length if length
            is not None else -1, shape=str(example.shape),
            deadline_ms=deadline_ms if deadline_ms is not None else -1)
        # count before put(): once queued, the batcher may serve the
        # request immediately, and "submitted" must never trail "served"
        self._stats.incr("submitted")
        try:
            self._batcher.put(req)
        except MXNetError as e:
            self._stats.incr("submitted", -1)
            if isinstance(e, ServerOverloadedError):
                self._stats.incr("rejected_overload")
            _tracer.request_end("serve.request", req.trace_id,
                                cat="serve", outcome="rejected")
            raise
        return req.future

    def predict(self, example, deadline_ms=None, timeout=None):
        """Synchronous convenience wrapper around submit().

        A caller-side ``timeout`` expiry CANCELS the queued request —
        without that, the abandoned request would still consume a batch
        slot when it finally dequeues (the caller stopped listening, so
        computing its answer is pure waste, exactly like an expired
        deadline).  The batcher thread voids cancelled requests at
        dequeue, counted as ``cancelled``.

        With only ``deadline_ms`` given, the wait derives its bound
        from the deadline (``deadline_ms/1e3 + PREDICT_GRACE_S``)
        instead of blocking indefinitely — a wedged server then fails
        the call instead of hanging a caller who explicitly said how
        long the answer is worth waiting for.  An explicit ``timeout``
        always wins.
        """
        fut = self.submit(example, deadline_ms=deadline_ms)
        if timeout is None and deadline_ms is not None:
            timeout = deadline_ms / 1e3 + PREDICT_GRACE_S
        try:
            return fut.result(timeout)
        except _FutureTimeout:
            fut.cancel()
            raise

    # -- batcher thread -----------------------------------------------------

    def _worker_loop(self):
        while not self._abort:
            group, expired = self._batcher.next_group(
                self._spec.max_batch, timeout=0.05,
                on_pop=self._take_in_flight)
            for req in expired:
                self._stats.incr("expired_deadline")
                _tracer.request_end("serve.request", req.trace_id,
                                    cat="serve", outcome="expired")
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(DeadlineExceededError(
                        "deadline passed while queued"))
            if group:
                # void requests whose caller already cancelled (e.g. a
                # predict(timeout=) expiry): they must not consume a
                # batch row — the expired-deadline rule, applied to
                # caller-side give-ups
                live = []
                for req in group:
                    if req.future.cancelled():
                        self._finish(req)
                        self._stats.incr("cancelled")
                        _tracer.request_end("serve.request", req.trace_id,
                                            cat="serve",
                                            outcome="cancelled")
                    else:
                        live.append(req)
                group = live
            if group:
                with self._exec_lock:
                    self._run_batch(group)
            elif group is None and self._batcher.drained():
                return

    def _take_in_flight(self, n):
        # runs under the batcher's queue lock: a request leaves
        # queue_depth and enters in_flight in one critical section
        with self._if_lock:
            self._in_flight += n

    def _run_batch(self, group):
        spec = self._spec
        pending = list(group)   # not yet resolved, for the failure path
        t_exec = time.monotonic()   # queue-vs-compute attribution split
        try:
            for req in group:
                _tracer.request_instant("serve.dequeue", req.trace_id,
                                        cat="serve")
            max_len = max((r.length for r in group), default=None) \
                if spec.var_axis is not None else None
            batch, length = spec.pick(len(group), max_len)
            key = spec.key(batch, length)
            with profiler.op_scope("serve.pad", cat="serve"):
                padded = spec.pad_batch([r.example for r in group],
                                        batch, length)
            with profiler.op_scope(f"serve.batch.{key}", cat="serve"):
                out = self._net(_nd_array(padded, ctx=self._ctx))
                outs = list(out) if isinstance(out, (list, tuple)) \
                    else [out]
                # one synchronous readback per output: the d2h wait is
                # the request's real completion time, so latency
                # includes it
                host = [o.asnumpy() if isinstance(o, NDArray) else
                        np.asarray(o) for o in outs]
            self._stats.record_batch(
                key, n_real=len(group), n_rows=batch,
                real_elems=sum(int(np.prod(r.example.shape))
                               for r in group),
                padded_elems=batch * int(np.prod(padded.shape[1:])))
            if self._int8:
                self._note_int8()
            now = time.monotonic()
            with profiler.op_scope("serve.split", cat="serve"):
                for i, req in enumerate(group):
                    res = [self._unpad_row(o[i], length, req.length)
                           for o in host]
                    pending.remove(req)
                    self._finish(req)
                    self._stats.incr("served")
                    self._stats.record_latency(
                        (now - req.enqueued_at) * 1e3)
                    _tracer.request_end(
                        "serve.request", req.trace_id, cat="serve",
                        outcome="served", bucket=key,
                        queue_ms=round((t_exec - req.enqueued_at) * 1e3,
                                       3),
                        compute_ms=round((now - t_exec) * 1e3, 3))
                    if req.future.set_running_or_notify_cancel():
                        req.future.set_result(res[0] if len(res) == 1
                                              else tuple(res))
        except Exception as e:  # noqa: BLE001 — EVERY failure is
            # forwarded to the affected callers; the batcher thread must
            # survive (a dead worker strands all queued futures forever)
            for req in pending:
                self._finish(req)
                self._stats.incr("failed")
                _tracer.request_end("serve.request", req.trace_id,
                                    cat="serve", outcome="failed")
                if req.future.set_running_or_notify_cancel():
                    req.future.set_exception(e)

    def _unpad_row(self, row, padded_len, orig_len):
        """Strip length padding when the output kept the variable axis
        (same axis index, same padded size); reductions that consumed
        the axis pass through untouched."""
        ax = self._spec.var_axis
        if (ax is None or orig_len is None or row.ndim <= ax
                or row.shape[ax] != padded_len or orig_len == padded_len):
            return row
        return row[(slice(None),) * ax + (slice(0, orig_len),)]

    def _finish(self, req):
        with self._if_lock:
            self._in_flight -= 1

    # -- hot reload ---------------------------------------------------------

    def reload_weights(self, step=None):
        """Swap parameters from the checkpoint manager (default:
        ``latest()``) without dropping queued or in-flight requests.

        Serialized with batch execution via the exec lock: the current
        batch finishes on the old weights, the next starts on the new —
        no torn reads, no recompile (parameters are runtime graph
        inputs, so the bucket executables are reused as-is).

        A QUANTIZED net (``contrib.quantization.quantize_net`` output)
        accepts both checkpoint flavors: int8-native checkpoints (saved
        from the quantized net) restore directly, fp32 training
        checkpoints are re-quantized in place against the stored scales
        — still no recompile, since every scale/range is a runtime
        graph input.
        """
        if self._ckpt is None:
            raise MXNetError(
                "no checkpoint manager: construct ModelServer("
                "checkpoint=...) to enable reload_weights()")
        with self._exec_lock:
            with profiler.op_scope("serve.reload", cat="serve"):
                if self._int8:
                    meta = self._ckpt.restore(step=step,
                                              restore_rng=False)
                    from ..contrib.quantization import \
                        load_serving_params

                    load_serving_params(self._net,
                                        meta.get("params") or {})
                else:
                    meta = self._ckpt.restore(step=step, params=self._net,
                                              restore_rng=False)
        self._stats.incr("reloads")
        return {"step": meta["step"], "epoch": meta.get("epoch")}

    # -- observability ------------------------------------------------------

    def pending(self):
        """Live load gauge for the router's least-loaded dispatch:
        queued + in-flight requests (cheap — no graph-stats walk)."""
        with self._if_lock:
            in_flight = self._in_flight
        return len(self._batcher) + in_flight

    def probe_example(self):
        """A minimal valid request (the smallest bucket's shape, pad
        values) — the router's health-probe payload."""
        shape = self._spec.bucket_shapes()[0][1:]
        return np.full(shape, self._spec.pad_value,
                       dtype=self._spec.dtype)

    def _graph_stats(self):
        op = getattr(self._net, "_cached_op", None)
        if op is not None and hasattr(op, "stats"):
            return dict(op.stats)
        return {}

    def stats(self, reset=False):
        """Snapshot of every serving counter.

        Invariants (asserted by ``make serve-smoke``)::

            submitted == served + expired_deadline + failed + cancelled
                         + queue_depth + in_flight
            graph.post_warmup_compiles == 0   # on a warmed server

        The identity is exact whenever the server is quiescent (idle,
        drained, or shut down).  Under live traffic a snapshot may be
        transiently off by requests mid-handoff: the queue, the
        in-flight gauge, and the counters are not read under one global
        lock, so alert on the drained value, not per-poll deltas.

        ``reset=True`` atomically starts a new accounting window
        (counters, fill/pad ratios, bucket hits, the latency ring AND
        its histogram) — the same window-scoping contract as
        ``profiler.dumps(reset=True)``; gauges (queue depth, in-flight,
        graph compile counters) read live and are unaffected.  The
        ``latency.histogram`` readout carries cumulative Prometheus
        ``le`` buckets — what the ``/metrics`` endpoint exports.
        """
        g = self._graph_stats()
        graph = {
            "compiles": g.get("compiles", 0),
            "reuses": g.get("reuses", 0),
            "post_warmup_compiles":
                g.get("compiles", 0) - self._warmup_compiles,
        }
        return self._stats.snapshot(
            queue_depth=len(self._batcher), in_flight=self._in_flight,
            reset=reset,
            extra={"graph": graph, "buckets": repr(self._spec)})
