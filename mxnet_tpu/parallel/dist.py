"""Distributed runtime: multi-process coordination + DCN collectives.

Ref: 3rdparty/ps-lite (Postoffice/Van — node management, barrier) and
src/kvstore/kvstore_dist.h.  TPU-native design: process groups come from
``jax.distributed`` (coordinator service = the Postoffice role); cross-
process reductions ride XLA collectives over ICI/DCN via
``multihost_utils``-style jitted psums on process-spanning meshes.

In a single process (no DMLC_/JAX coordinator env), everything degrades
to identity so kvstore('dist_sync') behaves like 'device' — the same
trick the reference's `local` launcher uses to run nightly dist tests on
one machine (SURVEY §4).
"""
from __future__ import annotations

import os

from ..base import getenv

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize the process group (ref: Postoffice::Start; modern form
    of the DMLC_PS_ROOT_URI env protocol set by tools/launch.py)."""
    global _initialized
    if _initialized:
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "MXTPU_COORDINATOR") or os.environ.get("DMLC_PS_ROOT_URI")
    if coordinator_address and num_processes is None:
        num_processes = int(os.environ.get(
            "MXTPU_NUM_WORKER", os.environ.get("DMLC_NUM_WORKER", "1")))
        process_id = int(os.environ.get(
            "MXTPU_WORKER_ID", os.environ.get("DMLC_WORKER_ID", "0")))
        port = os.environ.get("DMLC_PS_ROOT_PORT")
        if port and ":" not in coordinator_address:
            coordinator_address = f"{coordinator_address}:{port}"
    if coordinator_address:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    _initialized = True


def is_multiprocess():
    import jax

    return jax.process_count() > 1


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


def allreduce(value):
    """Sum an NDArray across processes (ref: KVStoreDist push+pull pair →
    DCN all-reduce).  Single-process: identity."""
    import jax

    if jax.process_count() <= 1:
        return value
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from ..engine import track
    from ..ndarray.ndarray import _wrap

    gathered = multihost_utils.process_allgather(value._data)
    return _wrap(track(jnp.asarray(gathered).sum(axis=0)))


def barrier(name="kvstore"):
    """Ref: Postoffice barrier."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
