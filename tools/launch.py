#!/usr/bin/env python
"""Multi-process cluster launcher (ref: tools/launch.py + dmlc-tracker).

Spawns one worker process per host/slot with coordinator env set so
mxnet_tpu.parallel.dist (jax.distributed) rendezvous, replacing the
ps-lite scheduler/server roles (SURVEY §3.4 TPU translation).

  python tools/launch.py -n 4 --launcher local python train.py
  python tools/launch.py -n 8 -H hosts.txt python train.py   # ssh

Env protocol per process (both spellings exported for compat):
  MXTPU_COORDINATOR / DMLC_PS_ROOT_URI (+PORT)
  MXTPU_NUM_WORKER  / DMLC_NUM_WORKER
  MXTPU_WORKER_ID   / DMLC_WORKER_ID
"""
import argparse
import os
import signal
import subprocess
import sys


def launch_local(n, cmd, port, num_servers=0):
    common = {
        "MXTPU_COORDINATOR": f"127.0.0.1:{port}",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "MXTPU_NUM_WORKER": str(n), "DMLC_NUM_WORKER": str(n),
        "DMLC_NUM_SERVER": str(num_servers),
    }
    if num_servers:
        common["DMLC_PS_SERVER_PORT"] = str(port + 1)
    servers, procs = [], []
    for sid in range(num_servers):
        # dedicated PS role (ref: dmlc-tracker server procs); serves the
        # dist_async transport (mxnet_tpu/parallel/ps.py). Each server
        # binds its own port (base + DMLC_SERVER_ID); clients shard keys
        # across the group.
        env = dict(os.environ)
        env.update(common)
        env["DMLC_ROLE"] = "server"
        env["DMLC_SERVER_ID"] = str(sid)
        servers.append(subprocess.Popen(
            [sys.executable, "-m", "mxnet_tpu.kvstore_server"], env=env))
    for i in range(n):
        env = dict(os.environ)
        env.update(common)
        env.update({"MXTPU_WORKER_ID": str(i), "DMLC_WORKER_ID": str(i),
                    "DMLC_ROLE": "worker"})
        procs.append(subprocess.Popen(cmd, env=env))
    code = 0
    try:
        for p in procs:
            code |= p.wait()
        for s in servers:
            # a server that died mid-job (port clash, crash) fails the
            # job even if workers limped through
            if s.poll() is not None and s.returncode not in (0, -15):
                code |= 1
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        code = 1
    finally:
        for s in servers:
            if s.poll() is None:
                s.send_signal(signal.SIGTERM)
    return code


def launch_mpi(n, cmd, port, hostfile=None, mpirun="mpirun"):
    """mpirun transport (ref: dmlc_tracker/mpi.py): mpirun fans out the
    ranks; each rank derives its worker id from the MPI rank env var via
    the --mpi-shim re-entry below, then execs the real command with the
    DMLC env protocol complete."""
    proto = {
        "MXTPU_COORDINATOR": f"127.0.0.1:{port}",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "MXTPU_NUM_WORKER": str(n), "DMLC_NUM_WORKER": str(n),
        "DMLC_NUM_SERVER": "0", "DMLC_ROLE": "worker",
    }
    if hostfile:
        # multi-host: the coordinator must be reachable from every rank
        first = [h.strip().split()[0] for h in open(hostfile)
                 if h.strip()][0]
        proto["MXTPU_COORDINATOR"] = f"{first}:{port}"
        proto["DMLC_PS_ROOT_URI"] = first
    env = dict(os.environ)
    env.update(proto)
    # --oversubscribe lets single-core hosts run n>1 ranks and OpenMPI
    # under root needs --allow-run-as-root (container default); probe
    # flag combos richest-first and keep the first that mpirun accepts
    extra = []
    for flags in (["--oversubscribe", "--allow-run-as-root"],
                  ["--allow-run-as-root"], ["--oversubscribe"], []):
        p = subprocess.run([mpirun] + flags + ["-n", "1", "true"],
                           capture_output=True)
        if p.returncode == 0:
            extra = flags
            break
    mpi_cmd = [mpirun] + extra + ["-n", str(n)]
    if hostfile:
        mpi_cmd += ["--hostfile", hostfile]
    # carry the protocol vars on the COMMAND LINE (/usr/bin/env), not in
    # mpirun's own environment: remote ranks don't inherit arbitrary env
    # vars (OpenMPI would need -x per var, MPICH -envlist — dmlc-tracker
    # mpi.py has the same workaround), and `env` works under both
    mpi_cmd += ["env"] + [f"{k}={v}" for k, v in proto.items()]
    mpi_cmd += [sys.executable, os.path.abspath(__file__),
                "--mpi-shim", "--"] + cmd
    return subprocess.call(mpi_cmd, env=env)


def mpi_shim(cmd):
    """Per-rank re-entry under mpirun: translate the MPI rank variable
    (OpenMPI/PMI/MPICH spellings) into the worker-id env protocol, then
    exec the user command in place."""
    rank = None
    for var in ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK",
                "MV2_COMM_WORLD_RANK", "SLURM_PROCID"):
        if os.environ.get(var) is not None:
            rank = os.environ[var]
            break
    if rank is None:
        sys.stderr.write("launch.py --mpi-shim: no MPI rank variable "
                         "found in the environment\n")
        sys.exit(2)
    os.environ["MXTPU_WORKER_ID"] = rank
    os.environ["DMLC_WORKER_ID"] = rank
    os.execvp(cmd[0], cmd)


K8S_MANIFEST = """\
# Generated by tools/launch.py --launcher k8s (ref: dmlc_tracker's yarn/
# k8s transports). A headless Service gives worker-0 a stable DNS name
# for the jax.distributed coordinator; an indexed Job runs one worker
# per pod with the DMLC env protocol derived from the completion index.
apiVersion: v1
kind: Service
metadata:
  name: {name}
spec:
  clusterIP: None
  selector:
    job-name: {name}
  ports:
  - port: {port}
---
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  completions: {n}
  parallelism: {n}
  completionMode: Indexed
  template:
    metadata:
      labels:
        job-name: {name}
    spec:
      subdomain: {name}
      restartPolicy: Never
      containers:
      - name: worker
        image: {image}
        command: {cmd_json}
        env:
        - name: MXTPU_WORKER_ID
          valueFrom:
            fieldRef:
              fieldPath: metadata.annotations['batch.kubernetes.io/job-completion-index']
        - name: DMLC_WORKER_ID
          valueFrom:
            fieldRef:
              fieldPath: metadata.annotations['batch.kubernetes.io/job-completion-index']
        - name: MXTPU_COORDINATOR
          value: "{name}-0.{name}:{port}"
        - name: DMLC_PS_ROOT_URI
          value: "{name}-0.{name}"
        - name: DMLC_PS_ROOT_PORT
          value: "{port}"
        - name: MXTPU_NUM_WORKER
          value: "{n}"
        - name: DMLC_NUM_WORKER
          value: "{n}"
        - name: DMLC_ROLE
          value: worker
"""


def k8s_manifest(n, cmd, port, image, name="mxtpu-job"):
    """Render the k8s Job+Service manifest for `kubectl apply -f -`.
    A generator, not an applier: no cluster access is assumed here."""
    import json

    return K8S_MANIFEST.format(n=n, port=port, image=image, name=name,
                               cmd_json=json.dumps(cmd))


def launch_ssh(hosts, n, cmd, port):
    coordinator = hosts[0]
    procs = []
    per_host = max(1, n // len(hosts))
    wid = 0
    for host in hosts:
        for _ in range(per_host):
            if wid >= n:
                break
            envs = " ".join([
                f"MXTPU_COORDINATOR={coordinator}:{port}",
                f"DMLC_PS_ROOT_URI={coordinator}",
                f"DMLC_PS_ROOT_PORT={port}",
                f"MXTPU_NUM_WORKER={n}", f"DMLC_NUM_WORKER={n}",
                f"MXTPU_WORKER_ID={wid}", f"DMLC_WORKER_ID={wid}",
                "DMLC_ROLE=worker",
            ] + ([f"DMLC_PS_BIND_HOST={os.environ['DMLC_PS_BIND_HOST']}"]
                 if os.environ.get("DMLC_PS_BIND_HOST") else []))
            remote = f"cd {os.getcwd()} && env {envs} {' '.join(cmd)}"
            procs.append(subprocess.Popen(["ssh", "-o",
                                           "StrictHostKeyChecking=no",
                                           host, remote]))
            wid += 1
    code = 0
    for p in procs:
        code |= p.wait()
    return code


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--mpi-shim":
        cmd = sys.argv[2:]
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        mpi_shim(cmd)
        return  # unreachable (execvp)
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="dedicated parameter-server processes for the "
                         "dist_async transport (dist_sync uses in-graph "
                         "DCN all-reduce and needs none)")
    ap.add_argument("--launcher", choices=["local", "ssh", "mpi", "k8s"],
                    default="local")
    ap.add_argument("-H", "--hostfile", default=None)
    ap.add_argument("-p", "--port", type=int, default=9099)
    ap.add_argument("--image", default="mxnet-tpu:latest",
                    help="container image for --launcher k8s")
    ap.add_argument("--job-name", default="mxtpu-job",
                    help="Job/Service name for --launcher k8s")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args.num_workers, cmd, args.port,
                              args.num_servers))
    if args.num_servers:
        # fail loudly rather than silently dropping the PS processes the
        # dist_async transport needs (only the local launcher spawns
        # DMLC_ROLE=server processes)
        ap.error(f"--num-servers is not supported by the "
                 f"{args.launcher} launcher (use --launcher local)")
    if args.launcher == "mpi":
        sys.exit(launch_mpi(args.num_workers, cmd, args.port,
                            hostfile=args.hostfile))
    if args.launcher == "k8s":
        sys.stdout.write(k8s_manifest(args.num_workers, cmd, args.port,
                                      args.image, args.job_name))
        sys.exit(0)
    hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
    sys.exit(launch_ssh(hosts, args.num_workers, cmd, args.port))


if __name__ == "__main__":
    main()
