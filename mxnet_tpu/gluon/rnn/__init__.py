"""gluon.rnn (ref: python/mxnet/gluon/rnn/)."""
from .rnn_layer import RNN, LSTM, GRU  # noqa: F401
from .rnn_cell import (RecurrentCell, RNNCell, LSTMCell, GRUCell,  # noqa: F401
                       SequentialRNNCell, HybridSequentialRNNCell,
                       BidirectionalCell, DropoutCell, ResidualCell,
                       ModifierCell, ZoneoutCell)
