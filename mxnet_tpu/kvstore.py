"""KVStore: string-keyed parameter/gradient store.

Ref: src/kvstore/ (kvstore_local.h, comm.h, kvstore_nccl.h,
kvstore_dist.h) + python/mxnet/kvstore.py.

TPU-native design (BASELINE north star): every type maps to XLA
collectives instead of device-copy trees / NCCL / ps-lite —
- 'local'/'device'/'nccl': single-process multi-device aggregation.
  Eager path reduces across the per-device replicas with XLA add (the
  CommDevice equivalent); inside a compiled step the same push+pull pair
  becomes an in-graph psum over the ICI mesh axis (see parallel/).
- 'dist_sync'/'dist_async'/'dist_device_sync': multi-process path over
  jax.distributed (DCN collectives); single-process fallback degrades to
  'device' so the nightly-style local-launcher tests run anywhere.
Server-side optimizer (`update_on_kvstore`) runs the Updater on the
reduced gradient once, then broadcasts — semantically identical to the
reference's KVStoreDistServer sync-mode update.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .ndarray.ndarray import NDArray, _wrap
from .ndarray import ndarray as _nd
from . import optimizer as _opt


class KVStore:
    """Ref: include/mxnet/kvstore.h KVStore::Create."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}          # key -> canonical NDArray (merged value)
        self._updater = None
        self._optimizer = None
        self._compression = None  # GradientCompression when enabled
        self._ps = None           # PSClient for the dist_async transport

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        from .parallel import dist

        return dist.rank()

    @property
    def num_workers(self):
        from .parallel import dist

        return dist.num_workers()

    # -- init ---------------------------------------------------------------

    def init(self, key, value):
        keys, values = _normalize(key, value)
        from .ndarray.sparse import BaseSparseNDArray

        for k, vlist in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            v = vlist[0]
            # canonical stored value is dense: every pull/push path reads
            # ._data (sparse stays sparse only on the wire, ref: comm.h)
            self._store[k] = (v.todense() if isinstance(v, BaseSparseNDArray)
                              else v.copy())
            if self._is_async():
                # set-if-absent on the server: every worker sends, first
                # one wins (ref: KVStoreDist::InitImpl push to servers)
                self._ps_client().init(str(k), self._store[k].asnumpy())

    # -- push / pull --------------------------------------------------------

    def push(self, key, value, priority=0):
        """Aggregate values (sum over devices, ref: CommDevice reduce; and
        over workers for dist_*)."""
        keys, values = _normalize(key, value)
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} has not been initialized")
            if self._compression is not None:
                vlist = [self._compression.compress(k, slot, v)
                         for slot, v in enumerate(vlist)]
            reduced = _reduce_sum(vlist, self._store[k].context)
            if self._is_async():
                # no barrier, no cross-worker reduce: the server merges
                # (or optimizer-updates) THIS worker's push immediately
                self._ps_client().push(str(k), reduced.asnumpy())
                continue
            if self._is_dist():
                reduced = self._dist_allreduce(k, reduced)
            if self._updater is not None:
                # server-side optimizer (update_on_kvstore=True)
                self._updater(_key_index(k), reduced, self._store[k])
            else:
                self._store[k]._data = reduced._data

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .ndarray.sparse import BaseSparseNDArray

        keys, outs = _normalize(key, out)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} has not been initialized")
            if self._is_async():
                # fetch the server's CURRENT value — may not yet include
                # other workers' in-flight pushes (async semantics)
                import jax.numpy as jnp

                self._store[k]._data = jnp.asarray(
                    self._ps_client().pull(str(k)))
            src = self._store[k]
            for o in olist:
                if isinstance(o, BaseSparseNDArray):
                    # ref: KVStoreLocal::PullImpl only serves dense outs;
                    # sparse outs must go through row_sparse_pull
                    raise MXNetError(
                        "pull with a sparse out is not supported; use "
                        "row_sparse_pull(key, out, row_ids=...)")
                o._data = src.as_in_context(o.context)._data

    def pushpull(self, key, value, out=None, priority=0):
        """push+pull in one call.  The multi-key form takes the fused
        path: dense same-dtype values are packed into size-capped flat
        buckets (``MXTPU_KVSTORE_BUCKET_MB``, default 32), each bucket is
        reduced/allreduced as ONE flat buffer, and the results are
        unpacked into the existing out holders — one collective per
        bucket instead of one per key (ref: the reference's fused
        aggregate pushes; "Memory-efficient array redistribution"
        motivates the many-small→few-large collective rewrite).
        Bit-compatible with the sequential per-key path: the pairwise
        reduce order over device slots is identical, and every remaining
        op is elementwise.  Sparse values, gradient compression, the
        server-side-optimizer and dist_async paths all fall through to
        the sequential form unchanged."""
        from . import engine as _engine

        _engine.fault_point("kvstore.pushpull")
        if isinstance(key, (list, tuple)) and len(key) > 1 \
                and self._fusion_eligible():
            keys, values = _normalize(key, value)
            outs = _normalize(key, out)[1] if out is not None else values
            fused, rest = self._split_fusable(keys, values, outs)
            stats = {"buckets": 0, "dispatches": 0}
            if fused:
                self._pushpull_fused(fused, stats)
            for k, vlist, olist in rest:
                self.push(k, vlist, priority)
                self.pull(k, olist, priority)
                stats["dispatches"] += 2 * len(vlist)
            return stats
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)
        return None

    def _fusion_eligible(self):
        # compression quantizes per (key, slot) with error feedback;
        # update_on_kvstore applies the optimizer inside push; dist_async
        # pushes per key to the PS — none of these compose with packing.
        return (self._updater is None and self._compression is None
                and not self._is_async())

    def _split_fusable(self, keys, values, outs):
        from .ndarray.sparse import BaseSparseNDArray

        fused, rest = [], []
        for k, vlist, olist in zip(keys, values, outs):
            ok = (k in self._store and len(vlist) == len(olist) > 0
                  and all(isinstance(v, NDArray)
                          and not isinstance(v, BaseSparseNDArray)
                          for v in vlist)
                  and all(isinstance(o, NDArray)
                          and not isinstance(o, BaseSparseNDArray)
                          for o in olist)
                  and len({str(v.dtype) for v in vlist}) == 1)
            (fused if ok else rest).append((k, vlist, olist))
        return fused, rest

    def _pushpull_fused(self, items, stats):
        import jax.numpy as jnp

        from . import engine
        from .base import getenv

        cap = max(int(getenv("KVSTORE_BUCKET_MB", 32.0, float) * (1 << 20)),
                  1)
        if not self._is_dist():
            # single replica + no cross-worker reduce: there is nothing
            # to sum, so packing would be pure overhead — mirror
            # push+pull's rebind exactly (zero device work when value,
            # store and outs share one device)
            multi = []
            for k, vlist, olist in items:
                if len(vlist) > 1:
                    multi.append((k, vlist, olist))
                    continue
                store = self._store[k]
                if vlist[0].context != store.context:
                    stats["dispatches"] += 1
                store._data = vlist[0].as_in_context(store.context)._data
                for o in olist:
                    if o.context != store.context:
                        stats["dispatches"] += 1
                    o._data = store.as_in_context(o.context)._data
            items = multi
            if not items:
                return
        # one bucket stream per (dtype, slot-count, slot-device layout);
        # the fingerprint covers the VALUE slots — those are what gets
        # packed into one flatten call, so every bucket member's slot s
        # must live on the same device (outs may land anywhere: the
        # unpack side transfers per destination device)
        groups = {}
        for item in items:
            _, vlist, _olist = item
            fp = (str(vlist[0].dtype), len(vlist),
                  tuple(str(next(iter(v._data.devices()))) for v in vlist))
            groups.setdefault(fp, []).append(item)
        for members in groups.values():
            bucket, size = [], 0
            for item in members:
                nbytes = item[1][0].size * item[1][0].dtype.itemsize
                if bucket and size + nbytes > cap:
                    self._reduce_bucket(bucket, stats, jnp, engine)
                    bucket, size = [], 0
                bucket.append(item)
                size += nbytes
            if bucket:
                self._reduce_bucket(bucket, stats, jnp, engine)

    def _reduce_bucket(self, bucket, stats, jnp, engine):
        """ONE flat allreduce for every key in `bucket`; results land in
        the canonical store and every out holder."""
        ks = [b[0] for b in bucket]
        shapes = [tuple(b[1][0].shape) for b in bucket]
        n_slots = len(bucket[0][1])
        single = len(bucket) == 1
        if single:
            # a lone key (e.g. one tensor bigger than the bucket cap)
            # gains nothing from pack/unpack: reduce it directly
            flats = [bucket[0][1][s]._data for s in range(n_slots)]
        else:
            # pack: one flat buffer per device slot
            flats = [engine.flatten_arrays([b[1][s]._data for b in bucket])
                     for s in range(n_slots)]
            stats["dispatches"] += n_slots
        # pairwise tree reduce across slots — same pair order as
        # _reduce_sum, so the per-element sum order (and therefore the
        # bits) match the sequential per-key path exactly
        reduced = _pairwise_tree_reduce(flats, stats, jnp, engine)
        target_dev = self._store[ks[0]].context.jax_device()
        if next(iter(reduced.devices())) != target_dev:
            reduced = engine.track(jax.device_put(reduced, target_dev))
            stats["dispatches"] += 1
        if self._is_dist():
            from .parallel import dist

            reduced = dist.allreduce(_wrap(reduced))._data
            stats["dispatches"] += 1
        # unpack once per distinct destination device
        per_dev = {}

        def pieces_for(dev):
            got = per_dev.get(dev)
            if got is None:
                flat = reduced
                if next(iter(reduced.devices())) != dev:
                    flat = engine.track(jax.device_put(reduced, dev))
                    stats["dispatches"] += 1
                if single:
                    got = per_dev[dev] = [flat]
                else:
                    got = per_dev[dev] = engine.unflatten_array(flat,
                                                                shapes)
                    stats["dispatches"] += 1
            return got

        for i, (k, _vlist, olist) in enumerate(bucket):
            # each key's canonical buffer stays on ITS OWN store
            # context (keys in one bucket may live on different
            # devices), matching the sequential per-key path — a write
            # to ks[0]'s device would stick and relocate every later
            # per-key reduce for that key
            self._store[k]._data = pieces_for(
                self._store[k].context.jax_device())[i]
            for o in olist:
                o._data = pieces_for(next(iter(o._data.devices())))[i]
        stats["buckets"] += 1

    # -- whole-step (traced) form ------------------------------------------

    def traced_pushpull(self, g_raws, axis_name):
        """The multi-key ``pushpull`` lowered INTO a compiled step
        (ROADMAP item 4): called while tracing the whole-step closure,
        it returns the cross-replica-summed gradients as traced buffers
        with the reduction expressed as in-program collectives, so XLA
        schedules it (overlapped with backward) instead of Python
        stitching eager collectives between dispatches.

        Fusion-ineligible stores (compression, server-side optimizer,
        dist_async) must not reach here — the whole-step compiler
        bypasses to the eager path first, mirroring
        ``_fusion_eligible``."""
        if not self._fusion_eligible():
            raise MXNetError(
                "traced_pushpull on a fusion-ineligible kvstore "
                "(compression / update_on_kvstore / dist_async); the "
                "whole-step compiler must bypass to the eager path")
        return traced_bucket_allreduce(g_raws, axis_name)

    # -- ZeRO-1 eager multi-key forms (fused-but-not-whole-step tier) ------

    def zero_reduce_scatter(self, vlists, padded, devices, stats):
        """Eager reduce-scatter of one flat bucket (ZeRO-1, arXiv
        2004.13336): ``vlists`` is a list of per-key NDArray slot lists
        (one slot per replica device, same dtype), packed per slot into
        ONE zero-padded flat buffer of ``padded`` elements; each rank
        ``r`` then receives the cross-slot sum of flat chunk ``r`` on
        ``devices[r]``.  The per-element add order is the same pairwise
        tree ``_reduce_bucket`` uses, so a sharded eager step stays
        bit-identical to the unsharded eager step.  Returns one raw
        shard buffer per rank."""
        import jax.numpy as jnp

        from . import engine

        if not self._fusion_eligible() or self._is_dist():
            raise MXNetError(
                "zero_reduce_scatter on an ineligible kvstore "
                "(compression / update_on_kvstore / dist); the trainer "
                "must bypass to the unsharded path")
        n = len(devices)
        shard_n = int(padded) // n
        flats = [engine.flatten_pad([v[s]._data for v in vlists], padded)
                 for s in range(n)]
        pieces = [engine.unflatten_array(f, [(shard_n,)] * n)
                  for f in flats]
        stats["dispatches"] += 2 * n
        shards = []
        for r, dev in enumerate(devices):
            parts = [pieces[s][r] for s in range(n)]
            # the shared tree keeps the exact _reduce_bucket pair
            # order, elementwise, so bits match the unsharded reduce
            shard = _pairwise_tree_reduce(parts, stats, jnp, engine)
            if next(iter(shard.devices())) != dev:
                shard = engine.track(jax.device_put(shard, dev))
                stats["dispatches"] += 1
            shards.append(shard)
        stats["buckets"] += 1
        return shards

    def zero_allgather(self, shard_raws, shapes, devices, stats):
        """Eager allgather: every rank's updated weight shard lands on
        every device, re-concatenated and unpacked into per-tensor
        buffers of ``shapes`` (the zero pad tail is never read).
        Returns ``{rank: [tensor raws]}``."""
        from . import engine

        out = {}
        for r, dev in enumerate(devices):
            moved = []
            for s in shard_raws:
                if next(iter(s.devices())) != dev:
                    s = engine.track(jax.device_put(s, dev))
                    stats["dispatches"] += 1
                moved.append(s)
            flat = engine.flatten_arrays(moved)
            out[r] = engine.unflatten_array(flat, shapes)
            stats["dispatches"] += 2
        return out

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (ref: KVStoreLocal::PullRowSparse).

        `out` row_sparse → filled with the selected rows; dense out gets
        the full value (rows outside row_ids zeroed)."""
        if row_ids is None:
            # ref: kvstore.py asserts row_ids is not None
            raise MXNetError("row_sparse_pull requires row_ids")
        import numpy as np
        import jax.numpy as jnp

        from .ndarray.sparse import RowSparseNDArray

        keys, outs = _normalize(key, out)
        rids = list(row_ids) if isinstance(row_ids, (list, tuple)) \
            else [row_ids]
        # row_ids align with outs the same way the reference's
        # kvstore.py zips them: either one rid per flattened out, one rid
        # per key (broadcast over that key's outs), or a single rid for
        # everything. (Round-1 bug: `rids * len(olist)` restarted at
        # rids[0] for every key, silently pulling key 0's rows.)
        n_flat = sum(len(olist) for olist in outs)
        if len(rids) == n_flat:
            per_key, off = [], 0
            for olist in outs:
                per_key.append(rids[off:off + len(olist)])
                off += len(olist)
        elif len(rids) == len(keys):
            per_key = [[r] * len(olist) for r, olist in zip(rids, outs)]
        elif len(rids) == 1:
            per_key = [rids * len(olist) for olist in outs]
        else:
            raise MXNetError(
                f"row_ids length {len(rids)} matches neither the number "
                f"of outs ({n_flat}) nor the number of keys ({len(keys)})")
        for k, olist, krids in zip(keys, outs, per_key):
            if k not in self._store:
                raise MXNetError(f"key {k} has not been initialized")
            src = self._store[k]
            for o, rid in zip(olist, krids):
                ids = np.unique(np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid
                ).astype(np.int64))
                if ids.size and (ids[0] < 0 or ids[-1] >= src.shape[0]):
                    raise MXNetError(
                        f"row_ids out of range for key {k}: "
                        f"[{ids[0]}, {ids[-1]}] vs {src.shape[0]} rows")
                rows = src._data[jnp.asarray(ids)]
                if isinstance(o, RowSparseNDArray):
                    o._values, o._indices = rows, jnp.asarray(ids)
                else:
                    dense = jnp.zeros(src.shape, src._data.dtype)
                    o._data = dense.at[jnp.asarray(ids)].set(rows)

    # -- broadcast (newer API parity) --------------------------------------

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out=out, priority=priority)

    # -- optimizer ----------------------------------------------------------

    def set_optimizer(self, optimizer):
        """Run the optimizer on the (reduced) push'ed grads —
        ref: kvstore_dist_server.h set_optimizer."""
        self._optimizer = optimizer
        if self._is_async():
            # serialized to the server; updates happen per-push there.
            # Only rank 0 sends (ref: python/mxnet/kvstore.py — a late
            # worker re-sending would wipe server-side Adam state
            # accrued from earlier pushes)
            from .parallel import dist

            if dist.rank() == 0:
                self._ps_client().set_optimizer(optimizer)
            return
        self._updater = _opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit gradient compression with error feedback
        (ref: src/kvstore/gradient_compression.cc Quantize2BitImpl).

        On TPU the ICI all-reduce needs no compression — this matters for
        the DCN (cross-slice) path, and is kept semantically faithful:
        each pushed gradient is quantized to {-t, 0, +t} with the
        quantization error accumulated into a per-(key, slot) residual
        added to the next push."""
        params = dict(compression_params or {})
        ctype = params.get("type", "2bit")
        if ctype == "none":
            self._compression = None
            return
        if ctype != "2bit":
            raise MXNetError(f"unsupported compression type {ctype!r}")
        self._compression = GradientCompression(
            threshold=float(params.get("threshold", 0.5)))

    # -- dist ---------------------------------------------------------------

    def _is_dist(self):
        return self._type.startswith("dist")

    def _is_async(self):
        """dist_async rides the PS transport: per-push server update, no
        barrier (ref: kvstore_dist_server.h sync_mode_=false)."""
        from .parallel import dist

        return self._type == "dist_async" and dist.is_multiprocess()

    def _ps_client(self):
        if self._ps is None:
            import os
            import time

            from .parallel import dist, ps

            if dist.rank() == 0 and "DMLC_PS_SERVER_PORT" not in os.environ:
                ps.ensure_local_server()
            endpoints = ps.server_endpoints()
            last = None
            for _ in range(60):  # servers may still be starting
                try:
                    self._ps = ps.PSClient(endpoints)
                    break
                except OSError as e:
                    last = e
                    time.sleep(0.25)
            else:
                raise MXNetError(
                    f"cannot reach parameter servers {endpoints}: {last}")
        return self._ps

    def _dist_allreduce(self, key, value):
        from .parallel import dist

        return dist.allreduce(value)

    def barrier(self):
        if self._is_dist():
            from .parallel import dist

            dist.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _pairwise_tree_reduce(parts, stats, jnp, engine):
    """Pairwise tree reduce over device slots IN SLOT ORDER — the ONE
    definition of the eager reduction order.  Both the unsharded
    flat-bucket allreduce (``_reduce_bucket``) and the ZeRO-1 eager
    reduce-scatter (``zero_reduce_scatter``) run THIS loop, so their
    per-element sum order (and therefore sharded/unsharded bit parity)
    can never drift apart.  Operands are moved to the left operand's
    device; every transfer and add is booked in ``stats``."""
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            a, b = parts[i], parts[i + 1]
            dev_a = next(iter(a.devices()))
            if next(iter(b.devices())) != dev_a:
                b = jax.device_put(b, dev_a)
                stats["dispatches"] += 1
            nxt.append(engine.track(jnp.add(a, b)))
            stats["dispatches"] += 1
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def _key_index(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _normalize(key, value):
    if isinstance(key, (list, tuple)):
        out_v = []
        for v in value:
            out_v.append(list(v) if isinstance(v, (list, tuple)) else [v])
        return list(key), out_v
    return [key], [list(value) if isinstance(value, (list, tuple))
                   else [value]]


def _reduce_sum(vlist, target_ctx):
    """Sum NDArrays living on (possibly) different devices.

    Eager CommDevice equivalent: gather to the target device and add —
    XLA handles the transfers; inside jit this is a psum.
    """
    from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
    from .ndarray import sparse as _sparse

    if all(isinstance(v, RowSparseNDArray) for v in vlist):
        # row_sparse aggregation stays sparse (ref: comm.h ReduceRowSparse);
        # align every shard on the target device first — sparse add
        # concatenates indices/values and jax rejects mixed-device inputs
        acc = vlist[0].as_in_context(target_ctx)
        for v in vlist[1:]:
            acc = _sparse.add(acc, v.as_in_context(target_ctx))
        return acc.todense()
    vlist = [v.todense() if isinstance(v, BaseSparseNDArray) else v
             for v in vlist]
    if len(vlist) == 1:
        return vlist[0].as_in_context(target_ctx)
    # pairwise tree reduce (ref: comm_tree.h CommDeviceTree): log2(N)
    # dependency depth instead of a serial N-add chain, so independent
    # partial sums overlap across devices under the async dispatcher
    while len(vlist) > 1:
        nxt = []
        for i in range(0, len(vlist) - 1, 2):
            nxt.append(vlist[i] + vlist[i + 1].as_in_context(
                vlist[i].context))
        if len(vlist) % 2:
            nxt.append(vlist[-1])
        vlist = nxt
    return vlist[0].as_in_context(target_ctx)


_VALID = ("local", "device", "nccl", "dist", "dist_sync", "dist_async",
          "dist_device_sync", "dist_device_async", "horovod", "teststore")


def create(name="local"):
    """Ref: mx.kv.create — all single-process types share the XLA
    collective path; dist types add the multi-process DCN allreduce."""
    if isinstance(name, KVStore):
        return name
    if name not in _VALID:
        raise MXNetError(f"unknown kvstore type {name!r}; valid: {_VALID}")
    return KVStore(name)


# ---------------------------------------------------------------------------
# Whole-step (traced) gradient reduction — the in-program twin of the
# eager flat-bucket pushpull above.


def traced_bucket_allreduce(g_raws, axis_name):
    """In-program twin of the eager flat-bucket reduction
    (``_pushpull_fused``): pack same-dtype gradients into size-capped
    flat buckets (``MXTPU_KVSTORE_BUCKET_MB``, the same knob), one
    ``lax.psum`` over ``axis_name`` per bucket, unpack into per-tensor
    views.  Runs only under a trace (shard_map over the replica/world
    mesh); with ``axis_name=None`` (single replica, nothing to sum) it
    is the identity, mirroring the eager path's rebind-only case.

    The pack/unpack kernels are the engine's shared flat-buffer staging
    kernels (``_k_flatten``/``_k_unflatten``), so the comm-fusion tier
    has one implementation eager and traced."""
    if axis_name is None:
        return list(g_raws)
    from . import engine
    from .base import getenv

    cap = max(int(getenv("KVSTORE_BUCKET_MB", 32.0, float) * (1 << 20)), 1)
    # one bucket stream per dtype, members in arrival order (the same
    # grouping fingerprint the eager path uses, minus the slot layout —
    # inside SPMD there is exactly one slot per shard)
    groups = {}
    order = []  # (group_key, index within group) per input position
    for g in g_raws:
        k = str(g.dtype)
        groups.setdefault(k, []).append(g)
        order.append((k, len(groups[k]) - 1))
    reduced = {}
    for k, members in groups.items():
        outs, bucket, size = [], [], 0
        for g in members:
            nbytes = g.size * g.dtype.itemsize
            if bucket and size + nbytes > cap:
                outs.extend(_psum_bucket(bucket, axis_name, engine))
                bucket, size = [], 0
            bucket.append(g)
            size += nbytes
        if bucket:
            outs.extend(_psum_bucket(bucket, axis_name, engine))
        reduced[k] = outs
    return [reduced[k][i] for k, i in order]


def _psum_bucket(bucket, axis_name, engine):
    """ONE in-program collective for every gradient in ``bucket``."""
    shapes = [tuple(int(d) for d in g.shape) for g in bucket]
    if len(bucket) == 1:
        # a lone tensor (e.g. bigger than the cap) gains nothing from
        # pack/unpack — reduce it directly, like the eager single case
        return [jax.lax.psum(bucket[0], axis_name)]
    flat = engine._k_flatten(list(bucket))
    red = jax.lax.psum(flat, axis_name)
    return list(engine._k_unflatten(red, shapes=tuple(shapes)))


# ---------------------------------------------------------------------------
# ZeRO-1 traced collectives (arXiv 2004.13336 "Automatic Cross-Replica
# Sharding of Weight Update in Data-Parallel Training"): the allreduce
# above rewritten as reduce-scatter (each rank receives the sum of ONE
# 1/world slice of the flat bucket) + allgather (updated slices
# broadcast back) — equal collective bandwidth, but the optimizer
# update and its state now touch only shard-sized buffers.  The
# portable psum_scatter/all_gather idioms follow arXiv 2112.01075.


def zero_padded_size(total, world):
    """Flat-bucket element count rounded up to a multiple of ``world``
    so every rank's shard is equal-sized.  The padding is part of the
    bucket fingerprint (plan tuples / closure keys carry it), so two
    layouts that differ only in pad never share an executable."""
    world = max(int(world), 1)
    return ((int(total) + world - 1) // world) * world


def traced_reduce_scatter_flat(ts, padded, axis_name):
    """ONE in-program collective: pack ``ts`` (same dtype) into a flat
    buffer zero-padded to ``padded`` elements and ``lax.psum_scatter``
    it over ``axis_name`` — this rank's equal-sized shard of the
    cross-replica sum.  Bit-identical per element to ``lax.psum`` of
    the same flat bucket (same reduction order over the axis)."""
    from . import engine

    flat = engine._k_flatten_pad(list(ts), padded=int(padded))
    return jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                tiled=True)


def traced_shard_slice(ts, padded, world, axis_name):
    """This rank's shard of the flat concatenation of ``ts`` (the
    weight-side twin of :func:`traced_reduce_scatter_flat`: weights are
    replicated, so the shard is a local dynamic slice at
    ``axis_index``, no collective)."""
    from . import engine

    flat = engine._k_flatten_pad(list(ts), padded=int(padded))
    shard_n = int(padded) // int(world)
    r = jax.lax.axis_index(axis_name)
    return jax.lax.dynamic_slice(flat, (r * shard_n,), (shard_n,))


def traced_allgather_flat(shard, shapes, axis_name):
    """ONE in-program collective: gather every rank's shard back into
    the full flat bucket and unpack into per-tensor views of
    ``shapes`` (the zero-pad tail is never read)."""
    from . import engine

    full = jax.lax.all_gather(shard, axis_name, axis=0, tiled=True)
    return list(engine._k_unflatten(
        full, shapes=tuple(tuple(int(d) for d in s) for s in shapes)))


def traced_bucket_reduce_scatter(g_raws, axis_name, world):
    """In-program ZeRO twin of :func:`traced_bucket_allreduce`: pack
    same-dtype gradients into size-capped flat buckets
    (``MXTPU_KVSTORE_BUCKET_MB``, the same knob), pad each bucket to a
    multiple of ``world`` (padding rides in the returned meta — the
    bucket fingerprint), one ``lax.psum_scatter`` per bucket.  Returns
    ``(shards, metas)`` with ``metas[i] = (positions, shapes, total,
    padded)`` mapping bucket ``i`` back to the input order; feed the
    updated shards to :func:`traced_bucket_allgather` to recover
    per-tensor arrays."""
    from .base import getenv

    cap = max(int(getenv("KVSTORE_BUCKET_MB", 32.0, float) * (1 << 20)), 1)
    groups = {}
    for pos, g in enumerate(g_raws):
        groups.setdefault(str(g.dtype), []).append((pos, g))
    shards, metas = [], []
    for members in groups.values():
        bucket, size = [], 0
        for pos, g in members:
            nbytes = g.size * g.dtype.itemsize
            if bucket and size + nbytes > cap:
                shards.append(_scatter_bucket(bucket, axis_name, world,
                                              metas))
                bucket, size = [], 0
            bucket.append((pos, g))
            size += nbytes
        if bucket:
            shards.append(_scatter_bucket(bucket, axis_name, world,
                                          metas))
    return shards, metas


def _scatter_bucket(bucket, axis_name, world, metas):
    positions = tuple(p for p, _g in bucket)
    shapes = tuple(tuple(int(d) for d in g.shape) for _p, g in bucket)
    total = sum(int(g.size) for _p, g in bucket)
    padded = zero_padded_size(total, world)
    metas.append((positions, shapes, total, padded))
    return traced_reduce_scatter_flat([g for _p, g in bucket], padded,
                                      axis_name)


def traced_bucket_allgather(shards, metas, axis_name):
    """Inverse of :func:`traced_bucket_reduce_scatter`: one
    ``lax.all_gather`` per bucket, results returned in the original
    input order."""
    out = {}
    for shard, (positions, shapes, _total, _padded) in zip(shards, metas):
        for pos, arr in zip(positions,
                            traced_allgather_flat(shard, shapes,
                                                  axis_name)):
            out[pos] = arr
    return [out[i] for i in range(len(out))]


# the issue-facing alias: "allgather" pairs with "reduce_scatter" in
# the public companion API
traced_allgather = traced_bucket_allgather


# ---------------------------------------------------------------------------
# 2-bit gradient compression (ref: src/kvstore/gradient_compression.{cc,h})


class GradientCompression:
    """Threshold quantization to {-t, 0, +t} with error-feedback residual
    (ref: GradientCompression::Quantize2BitImpl + dequantize — here the
    quantize/dequantize pair is fused since the wire format on TPU is the
    already-dequantized ternary tensor; what matters semantically is the
    information loss + residual accumulation, which match the reference
    exactly)."""

    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise MXNetError("compression threshold must be positive")
        self.threshold = threshold
        self._residuals = {}  # (key, slot) -> raw residual array

    def get_params(self):
        return {"type": "2bit", "threshold": self.threshold}

    def compress(self, key, slot, grad):
        import jax.numpy as jnp

        from .ndarray.sparse import BaseSparseNDArray

        if isinstance(grad, BaseSparseNDArray):
            grad = grad.todense()
        t = jnp.asarray(self.threshold, grad._data.dtype)
        resid = self._residuals.get((key, slot))
        g = grad._data if resid is None else grad._data + resid
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t,
                                           jnp.zeros_like(g)))
        self._residuals[(key, slot)] = g - q
        return _wrap(q)
