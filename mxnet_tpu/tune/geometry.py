"""Traffic-derived serving geometry: stop guessing the bucket grid.

A ``BucketSpec`` grid is a bet about future traffic: every request pads
up to the smallest compiled shape that covers it, so a grid that
mismatches the real length distribution burns flops on padding, and a
grid with too many entries burns warmup compiles on shapes nobody
sends.  ``ServerStats`` already tallies the actual distributions —
``request_lengths`` (variable-axis length of every submitted request)
and ``group_sizes`` (real size of every executed batch group).  This
module turns those histograms into geometry:

* :func:`derive_lengths` — optimal ≤k-entry length ladder for a
  measured histogram (exact dynamic program minimising padded
  elements, O(n²k) over n distinct observed lengths);
* :func:`derive_batches` — batch-size ladder covering the observed
  group sizes;
* :func:`derive_bucket_spec` — both of the above as a ready
  ``BucketSpec``;
* :func:`derive_decode_geometry` — decode arena ``max_len`` (covers
  p99 prompt + generation budget) and ``max_slots`` (sized to measured
  slot occupancy); with ``paged=True`` also the page-pool geometry
  (``page_tokens`` / ``num_pages`` sized to MEAN tokens in flight,
  not the worst case);
* :func:`parse_grid` / :func:`format_grid` — the
  ``"1,2,4,8x32,64,128"`` string form the ``serve_buckets`` env knob
  carries, so a derived grid can ride an env var into a fresh server.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..serve.buckets import BucketSpec

__all__ = ["parse_grid", "format_grid", "padding_overhead",
           "derive_lengths", "derive_batches", "derive_bucket_spec",
           "derive_decode_geometry"]


# ---------------------------------------------------------------------------
# grid string form (the serve_buckets knob's value)


def parse_grid(s):
    """``"1,2,4,8x32,64,128" -> ((1,2,4,8), (32,64,128))``; the length
    side may be empty (``"1,2,4x"`` = fixed-shape spec)."""
    try:
        batch_s, _, len_s = str(s).partition("x")
        batches = tuple(sorted({int(b) for b in batch_s.split(",") if b}))
        lengths = tuple(sorted({int(l) for l in len_s.split(",") if l}))
    except ValueError:
        raise MXNetError(
            f"bad bucket grid {s!r}; want 'b1,b2,..xl1,l2,..'") from None
    if not batches:
        raise MXNetError(f"bucket grid {s!r} has no batch sizes")
    return batches, lengths or None


def format_grid(batches, lengths=None):
    """Inverse of :func:`parse_grid` (canonical ascending order)."""
    b = ",".join(str(int(x)) for x in sorted(set(batches)))
    l = ",".join(str(int(x)) for x in sorted(set(lengths or ())))
    return f"{b}x{l}"


# ---------------------------------------------------------------------------
# padding accounting


def _align_up(v, align):
    return int(-(-int(v) // align) * align)


def padding_overhead(lengths, hist):
    """Padded-elements overhead of a length ladder over a measured
    ``{length: count}`` histogram: ``padded/real - 1`` (0.0 = no
    waste).  Lengths beyond the top bucket pad to the top bucket (the
    server would reject them; charging the top keeps comparisons
    total)."""
    ladder = sorted(int(l) for l in lengths)
    if not ladder or not hist:
        raise MXNetError("padding_overhead needs a ladder and a "
                         "non-empty histogram")
    real = padded = 0
    for length, count in hist.items():
        length, count = int(length), int(count)
        bucket = next((b for b in ladder if b >= length), ladder[-1])
        real += length * count
        padded += bucket * count
    return padded / real - 1.0


def derive_lengths(hist, max_buckets=4, align=8):
    """Optimal ≤``max_buckets`` length ladder for a measured
    ``{length: count}`` histogram — exact DP minimising total padded
    elements.  Bucket boundaries are observed lengths rounded up to
    ``align`` (TPU lane alignment; odd boundaries waste tiles)."""
    if not hist:
        raise MXNetError("derive_lengths: empty length histogram — "
                         "serve some traffic first")
    max_buckets = max(1, int(max_buckets))
    items = sorted((int(l), int(c)) for l, c in hist.items() if c > 0)
    lengths = [l for l, _c in items]
    counts = [c for _l, c in items]
    cand = [_align_up(l, align) for l in lengths]
    n = len(items)

    # seg[i][j] = padded elements covering items i..j with one bucket
    # at cand[j]
    pre = np.cumsum([0] + counts)
    def seg(i, j):
        return cand[j] * (pre[j + 1] - pre[i])

    INF = float("inf")
    k = min(max_buckets, n)
    # dp[m][j] = min padded elements covering items 0..j with m buckets,
    # the m-th ending at item j
    dp = [[INF] * n for _ in range(k + 1)]
    back = [[-1] * n for _ in range(k + 1)]
    for j in range(n):
        dp[1][j] = seg(0, j)
    for m in range(2, k + 1):
        for j in range(m - 1, n):
            for i in range(m - 2, j):
                c = dp[m - 1][i] + seg(i + 1, j)
                if c < dp[m][j]:
                    dp[m][j] = c
                    back[m][j] = i
    best_m = min(range(1, k + 1), key=lambda m: dp[m][n - 1])
    ladder, j, m = [], n - 1, best_m
    while m >= 1:
        ladder.append(cand[j])
        j, m = back[m][j], m - 1
    return tuple(sorted(set(ladder)))


def derive_batches(group_hist, max_batch=None):
    """Batch-size ladder from the measured ``{group size: batches}``
    histogram: 1 plus powers of two up to the observed (or capped)
    maximum — group sizes are coalescing outcomes, not a stable
    distribution, so a dense optimal ladder would overfit one burst."""
    if not group_hist:
        raise MXNetError("derive_batches: empty group-size histogram")
    top = max(int(g) for g, c in group_hist.items() if c > 0)
    if max_batch is not None:
        top = min(top, int(max_batch))
    out, b = [1], 1
    while b < top:
        b *= 2
        out.append(b)
    return tuple(out)


def derive_bucket_spec(snapshot, example_shape, max_buckets=4,
                       align=8, max_batch=None, pad_value=0.0,
                       dtype="float32"):
    """Build a traffic-derived :class:`BucketSpec` from a
    ``ModelServer.stats()`` snapshot (needs its ``request_lengths`` /
    ``group_sizes`` histograms)."""
    lengths = None
    if any(s is None for s in tuple(example_shape)):
        lengths = derive_lengths(snapshot.get("request_lengths") or {},
                                 max_buckets=max_buckets, align=align)
    batches = derive_batches(snapshot.get("group_sizes") or {},
                             max_batch=max_batch)
    return BucketSpec(batches, example_shape, lengths=lengths,
                      pad_value=pad_value, dtype=dtype)


def derive_decode_geometry(request_lengths, max_new_tokens=32,
                           slot_occupancy=None, max_slots=8, align=8,
                           paged=False, page_tokens=16):
    """Decode arena geometry from measured traffic.

    ``max_len`` covers the p99 observed prompt length plus the
    generation budget, aligned up — big enough that long requests
    don't overflow, no bigger (cache memory is ``max_slots x max_len``
    per layer).  ``max_slots`` resizes toward the measured
    ``slot_occupancy`` (token-step-weighted mean live/max from the
    ``decodeServe`` section): sustained >75% occupancy doubles the
    arena (admission is queuing), <25% halves it (cache memory idles).
    Returns ``{"max_len": ..., "max_slots": ...}``.

    With ``paged=True`` the dict also carries page-pool geometry for
    the paged arena (``DecodeServer(page_tokens=...)``): the per-slot
    worst case stays ``max_len`` (the logical range still has to cover
    the p99 request), but the PHYSICAL pool is sized to the MEAN
    length plus budget — tokens actually in flight — instead of
    ``max_slots x max_len``: ``num_pages = max_slots *
    ceil((mean_len + max_new_tokens) / page_tokens)`` (floored at one
    slot's worst case so a lone p99 request still fits).  That is the
    whole point of paging: heavy-tailed traffic pays HBM for its mean,
    not its tail.
    """
    if not request_lengths:
        raise MXNetError("derive_decode_geometry: empty length "
                         "histogram")
    lens = np.repeat([int(l) for l in sorted(request_lengths)],
                     [int(request_lengths[l]) for l
                      in sorted(request_lengths)])
    p99 = float(np.percentile(lens, 99))
    max_len = _align_up(int(np.ceil(p99)) + int(max_new_tokens), align)
    slots = int(max_slots)
    if slot_occupancy is not None:
        if slot_occupancy > 0.75:
            slots = max_slots * 2
        elif slot_occupancy < 0.25:
            slots = max(1, max_slots // 2)
    out = {"max_len": max_len, "max_slots": slots}
    if paged:
        t = int(page_tokens)
        if t < 1:
            raise MXNetError("derive_decode_geometry: page_tokens "
                             "must be >= 1 when paged=True")
        pages_per_slot = -(-max_len // t)
        mean_span = -(-int(np.ceil(float(np.mean(lens)))
                           + int(max_new_tokens)) // t)
        out["page_tokens"] = t
        out["num_pages"] = max(slots * mean_span, pages_per_slot)
        out["pages_per_slot"] = pages_per_slot
    return out
