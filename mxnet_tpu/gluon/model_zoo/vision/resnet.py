"""ResNet v1/v2 (ref: python/mxnet/gluon/model_zoo/vision/resnet.py).

The BASELINE ResNet-50 workload model.  NCHW layout; bf16-friendly
(cast via net.cast('bfloat16') — BatchNorm stats stay fp32 via the op's
internal math).
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(
                nn.Conv2D(channels, 1, stride, use_bias=False,
                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x_out, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(
                nn.Conv2D(channels, 1, stride, use_bias=False,
                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x_out = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(residual + x_out, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes)

    def _make_layer(self, block, num_layers, channels, stride,
                    in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride,
                        downsample=(channels != in_channels or stride != 1),
                        in_channels=in_channels))
        for _ in range(num_layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                        use_bias=False))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=channels[i]))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


_blocks = {1: {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
           2: {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2}}
_nets = {1: ResNetV1, 2: ResNetV2}


def get_resnet(version, num_layers, pretrained=False, ctx=None,
               classes=1000, **kwargs):
    if num_layers not in resnet_spec:
        raise MXNetError(f"unsupported resnet depth {num_layers}")
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress); "
                         "load_parameters from a local file instead")
    block_type, layers, channels = resnet_spec[num_layers]
    return _nets[version](_blocks[version][block_type], layers, channels,
                          classes=classes, **kwargs)


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)
