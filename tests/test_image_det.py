"""ImageDetIter + detection augmenters (ref:
python/mxnet/image/detection.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img


def _write_images(tmp_path, n=6, size=(40, 30)):
    from PIL import Image

    rng = np.random.RandomState(0)
    paths = []
    for i in range(n):
        arr = rng.randint(0, 255, (size[1], size[0], 3), dtype=np.uint8)
        p = str(tmp_path / f"im{i}.jpg")
        Image.fromarray(arr).save(p)
        paths.append(p)
    return paths


def _labels(n):
    rng = np.random.RandomState(1)
    labs = []
    for i in range(n):
        k = 1 + (i % 3)
        objs = []
        for _ in range(k):
            x0, y0 = rng.uniform(0, 0.5, 2)
            objs.append([float(rng.randint(0, 4)), x0, y0,
                         x0 + rng.uniform(0.2, 0.45),
                         y0 + rng.uniform(0.2, 0.45)])
        labs.append(np.array(objs, np.float32))
    return labs


def _write_lst(tmp_path, paths, labs):
    lst = str(tmp_path / "det.lst")
    with open(lst, "w") as f:
        for i, (p, lab) in enumerate(zip(paths, labs)):
            fields = [str(i), "2", "5"]
            for obj in lab:
                fields += [f"{v:.6f}" for v in obj]
            fields.append(os.path.basename(p))
            f.write("\t".join(fields) + "\n")
    return lst


def test_image_det_iter_lst(tmp_path):
    paths = _write_images(tmp_path)
    labs = _labels(len(paths))
    lst = _write_lst(tmp_path, paths, labs)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                          path_imglist=lst, path_root=str(tmp_path),
                          aug_list=[])
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4, 3, 5)  # max 3 objects
    lab0 = batch.label[0].asnumpy()[0]
    np.testing.assert_allclose(lab0[:1], labs[0], atol=1e-5)
    assert (lab0[1:] == -1.0).all()  # padded rows
    # second batch pads past the end, then StopIteration
    b2 = it.next()
    assert b2.pad == 2
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert it.next().pad == 0


def test_image_det_iter_rec(tmp_path):
    from mxnet_tpu import recordio

    paths = _write_images(tmp_path, n=4)
    labs = _labels(4)
    rec_path = str(tmp_path / "det.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    from PIL import Image

    for i, (p, lab) in enumerate(zip(paths, labs)):
        flat = np.concatenate([[2, 5], lab.ravel()]).astype(np.float32)
        header = recordio.IRHeader(0, flat, i, 0)
        rec.write(recordio.pack_img(header, np.asarray(Image.open(p))))
    rec.close()
    it = img.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                          path_imgrec=rec_path, aug_list=[])
    b = it.next()
    assert b.data[0].shape == (2, 3, 32, 32)
    assert b.label[0].shape[2] == 5


def test_det_horizontal_flip():
    src = mx.nd.array(np.random.uniform(0, 255, (16, 16, 3))
                      .astype(np.float32))
    lab = np.array([[1.0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    out, flipped = img.DetHorizontalFlipAug(1.0)(src, lab)
    np.testing.assert_allclose(flipped[0],
                               [1.0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    np.testing.assert_allclose(out.asnumpy(),
                               src.asnumpy()[:, ::-1], atol=1e-6)
    # flip twice = identity on boxes
    _, twice = img.DetHorizontalFlipAug(1.0)(src, flipped)
    np.testing.assert_allclose(twice, lab, atol=1e-6)


def test_det_random_crop_boxes_stay_valid():
    np.random.seed(0)
    src = mx.nd.array(np.random.uniform(0, 255, (64, 64, 3))
                      .astype(np.float32))
    lab = np.array([[0.0, 0.3, 0.3, 0.7, 0.7],
                    [2.0, 0.05, 0.05, 0.15, 0.15]], np.float32)
    aug = img.DetRandomCropAug(min_object_covered=0.3,
                               area_range=(0.3, 1.0))
    for _ in range(10):
        out, nl = aug(src, lab)
        valid = nl[nl[:, 0] >= 0]
        assert (valid[:, 1:] >= -1e-6).all()
        assert (valid[:, 1:] <= 1 + 1e-6).all()
        assert (valid[:, 3] >= valid[:, 1]).all()
        assert (valid[:, 4] >= valid[:, 2]).all()


def test_det_create_augmenter_runs():
    src = mx.nd.array(np.random.uniform(0, 255, (48, 48, 3))
                      .astype(np.float32))
    lab = np.array([[1.0, 0.2, 0.2, 0.8, 0.8]], np.float32)
    augs = img.CreateDetAugmenter((3, 32, 32), rand_crop=0.5,
                                  rand_mirror=True, brightness=0.2,
                                  contrast=0.2, saturation=0.2, hue=0.1,
                                  pca_noise=0.02, rand_gray=0.1,
                                  mean=True, std=True)
    x, l = src, lab
    for a in augs:
        x, l = a(x, l)
    assert x.shape[2] == 3 and l.shape[1] == 5


def test_image_det_iter_roll_over(tmp_path):
    paths = _write_images(tmp_path)
    labs = _labels(len(paths))
    lst = _write_lst(tmp_path, paths, labs)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 16, 16),
                          path_imglist=lst, path_root=str(tmp_path),
                          aug_list=[], last_batch_handle="roll_over")
    assert it.next().pad == 0
    with pytest.raises(StopIteration):
        it.next()  # 2 leftovers carried, not padded
    it.reset()
    assert it.next().pad == 0  # leftovers lead the new epoch
    with pytest.raises(mx.MXNetError, match="last_batch_handle"):
        img.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                         path_imglist=lst, path_root=str(tmp_path),
                         last_batch_handle="bogus")


def test_contrast_jitter_preserves_uniform_level():
    """Reference invariant: pure contrast change leaves a uniform image
    at its own level (offset = (1-alpha) * mean luminance)."""
    uni = mx.nd.array(np.full((8, 8, 3), 100.0, np.float32))
    for _ in range(5):
        out = img.ContrastJitterAug(0.9)(uni).asnumpy()
        np.testing.assert_allclose(out, 100.0, atol=0.2)


def test_create_augmenter_imagenet_norm():
    """mean=True/std=True select the ImageNet constants."""
    augs = img.CreateAugmenter((3, 8, 8), mean=True, std=True)
    x = mx.nd.array(np.broadcast_to(
        img.IMAGENET_MEAN, (8, 8, 3)).astype(np.float32).copy())
    for a in augs:
        x = a(x)
    np.testing.assert_allclose(x.asnumpy(), 0.0, atol=1e-4)


def test_image_det_iter_indexed_rec_lazy(tmp_path):
    from PIL import Image

    from mxnet_tpu import recordio

    paths = _write_images(tmp_path, n=4)
    labs = _labels(4)
    rec_path = str(tmp_path / "deti.rec")
    idx_path = str(tmp_path / "deti.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i, (p, lab) in enumerate(zip(paths, labs)):
        flat = np.concatenate([[2, 5], lab.ravel()]).astype(np.float32)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, flat, i, 0), np.asarray(Image.open(p))))
    rec.close()
    it = img.ImageDetIter(batch_size=2, data_shape=(3, 24, 24),
                          path_imgrec=rec_path, aug_list=[])
    # payloads are fetched lazily through the open indexed reader
    from mxnet_tpu.image.detection import _LazyRecKey

    assert all(isinstance(src, _LazyRecKey) for _, src in it._items)
    b = it.next()
    assert b.data[0].shape == (2, 3, 24, 24)
    np.testing.assert_allclose(b.label[0].asnumpy()[0][:1], labs[0],
                               atol=1e-5)


def test_det_random_pad_boxes_shrink():
    np.random.seed(3)
    src = mx.nd.array(np.random.uniform(0, 255, (32, 32, 3))
                      .astype(np.float32))
    lab = np.array([[1.0, 0.2, 0.2, 0.8, 0.8]], np.float32)
    aug = img.DetRandomPadAug(area_range=(1.5, 2.5))
    out, nl = aug(src, lab)
    assert out.shape[0] >= 32 and out.shape[1] >= 32
    w0 = (lab[0, 3] - lab[0, 1]) * 32
    w1 = (nl[0, 3] - nl[0, 1]) * out.shape[1]
    np.testing.assert_allclose(w1, w0, atol=1e-3)  # absolute size kept


def test_det_random_select_probability():
    np.random.seed(0)
    src = mx.nd.array(np.zeros((16, 16, 3), np.float32))
    lab = np.array([[1.0, 0.2, 0.2, 0.8, 0.8]], np.float32)

    class MarkAug(img.DetAugmenter):
        def __call__(self, s, l):
            return s + 1, l

    hits = 0
    sel = img.DetRandomSelectAug([MarkAug()], skip_prob=0.7)
    for _ in range(300):
        out, _ = sel(src, lab)
        hits += int(float(out.asnumpy().max()) > 0)
    assert 50 <= hits <= 130  # ~30% of 300


def test_label_pad_width_too_small_raises(tmp_path):
    paths = _write_images(tmp_path)
    labs = _labels(len(paths))
    lst = _write_lst(tmp_path, paths, labs)
    with pytest.raises(mx.MXNetError, match="label_pad_width"):
        img.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                         path_imglist=lst, path_root=str(tmp_path),
                         label_pad_width=1)  # dataset max is 3


def test_custom_aug_chain_without_resize_is_float_safe(tmp_path):
    """Normalized (negative) float data must survive the shape fixup."""
    paths = _write_images(tmp_path, n=2)
    labs = _labels(2)
    lst = _write_lst(tmp_path, paths, labs)

    class NegAug(img.DetAugmenter):
        def __call__(self, s, l):
            return s.astype("float32") * 0 - 1.5, l

    it = img.ImageDetIter(batch_size=2, data_shape=(3, 16, 16),
                          path_imglist=lst, path_root=str(tmp_path),
                          aug_list=[NegAug()])
    d = it.next().data[0].asnumpy()
    np.testing.assert_allclose(d, -1.5, atol=1e-5)  # not uint8-wrapped


def test_det_label_parse_errors(tmp_path):
    paths = _write_images(tmp_path, n=1)
    with open(str(tmp_path / "bad.lst"), "w") as f:
        f.write("0\t2\t3\t1.0\t0.1\t0.1\t" +
                os.path.basename(paths[0]) + "\n")  # obj_width 3 < 5
    with pytest.raises(mx.MXNetError, match="object_width"):
        img.ImageDetIter(batch_size=1, data_shape=(3, 16, 16),
                         path_imglist=str(tmp_path / "bad.lst"),
                         path_root=str(tmp_path))


def test_im2rec_pack_label_roundtrip(tmp_path):
    """tools/im2rec.py --pack-label → ImageDetIter reads it back."""
    import subprocess
    import sys

    paths = _write_images(tmp_path, n=3)
    labs = _labels(3)
    _write_lst(tmp_path, paths, labs)
    prefix = str(tmp_path / "det")
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "im2rec.py"),
         prefix, str(tmp_path), "--pack-label"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".rec")
    it = img.ImageDetIter(batch_size=3, data_shape=(3, 24, 24),
                          path_imgrec=prefix + ".rec", aug_list=[])
    b = it.next()
    np.testing.assert_allclose(b.label[0].asnumpy()[0][:len(labs[0])],
                               labs[0], atol=1e-5)


def test_pack_single_element_label_vector_roundtrip(tmp_path):
    """flag=1 packed vectors must unpack cleanly (ref unpack strips for
    flag > 0; a size-1 label previously corrupted the image payload)."""
    from PIL import Image

    from mxnet_tpu import recordio

    arr = np.random.RandomState(0).randint(0, 255, (10, 12, 3),
                                           dtype=np.uint8)
    s = recordio.pack_img(
        recordio.IRHeader(0, np.array([7.0], np.float32), 3, 0), arr)
    header, img2 = recordio.unpack_img(s, iscolor=1)
    assert header.flag == 1
    np.testing.assert_allclose(np.asarray(header.label), [7.0])
    assert img2.shape == (10, 12, 3)  # payload decodes — not corrupted


def test_image_det_record_iter_kwarg_translation(tmp_path):
    from mxnet_tpu import io as mio

    paths = _write_images(tmp_path, n=4)
    labs = _labels(4)
    lst = _write_lst(tmp_path, paths, labs)
    it = mio.ImageDetRecordIter(
        batch_size=2, data_shape=(3, 24, 24), path_imglist=lst,
        path_root=str(tmp_path), rand_crop_prob=0.5, rand_pad_prob=0.3,
        rand_mirror_prob=0.5, mean_r=123.68, mean_g=116.28, mean_b=103.53,
        std_r=58.4, std_g=57.1, std_b=57.4, min_object_covered=0.3)
    b = it.next()
    assert b.data[0].shape == (2, 3, 24, 24)
    # normalization was applied: values are roughly zero-centered
    assert abs(float(b.data[0].asnumpy().mean())) < 2.0
    with pytest.raises(mx.MXNetError, match="unsupported kwargs"):
        mio.ImageDetRecordIter(batch_size=2, data_shape=(3, 24, 24),
                               path_imglist=lst, path_root=str(tmp_path),
                               bogus_kwarg=1)


def test_draw_next(tmp_path):
    paths = _write_images(tmp_path, n=2)
    labs = _labels(2)
    lst = _write_lst(tmp_path, paths, labs)
    it = img.ImageDetIter(batch_size=1, data_shape=(3, 32, 32),
                          path_imglist=lst, path_root=str(tmp_path),
                          aug_list=[])
    drawn = list(it.draw_next())
    assert len(drawn) == 2 and drawn[0].shape == (32, 32, 3)


def test_image_det_iter_num_parts(tmp_path):
    paths = _write_images(tmp_path)
    labs = _labels(len(paths))
    lst = _write_lst(tmp_path, paths, labs)
    tot = 0
    for part in range(2):
        it = img.ImageDetIter(batch_size=3, data_shape=(3, 16, 16),
                              path_imglist=lst, path_root=str(tmp_path),
                              aug_list=[], num_parts=2, part_index=part)
        tot += sum(b.data[0].shape[0] - b.pad for b in it)
    assert tot == 6  # exact partition of the 6 images
