/* Pure-C LeNet training driver for the trainable C ABI (VERDICT r3 #4).
 *
 * Ref: the role of cpp-package/example/lenet.cpp — a non-Python
 * frontend training LeNet on MNIST end-to-end through the flat C API
 * (symbol compose, InferShape, executor bind/forward/backward,
 * optimizer update, MNISTIter, kvstore push/pull, CachedOp inference,
 * autograd record/backward).  tests/test_capi.py synthesizes the MNIST
 * idx files, compiles this file, runs it, and asserts the printed
 * losses decrease.
 *
 * Usage: capi_train_lenet <train-images.idx> <train-labels.idx>
 */
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* CachedOpHandle;
typedef void* OptimizerHandle;
typedef void* DataIterHandle;
typedef void* KVStoreHandle;

extern const char* MXTPUGetLastError(void);
extern int MXTPUCAPIInit(const char* platform);
extern int MXTPUNDArrayCreate(const void* data, const int64_t* shape,
                              int ndim, int dtype, const char* ctx,
                              NDArrayHandle* out);
extern int MXTPUNDArrayFree(NDArrayHandle h);
extern int MXTPUNDArraySyncCopyToCPU(NDArrayHandle h, void* out,
                                     int64_t nbytes);
extern int MXTPUNDArrayCopyFrom(NDArrayHandle dst, NDArrayHandle src);
extern int MXTPUNDArrayGetGrad(NDArrayHandle h, NDArrayHandle* out);
extern int MXTPUImperativeInvoke(const char* op_name, NDArrayHandle* in,
                                 int num_in, const char** keys,
                                 const char** vals, int num_kwargs,
                                 NDArrayHandle* out, int* num_out);
extern int MXTPUSymbolCreateVariable(const char* name, SymbolHandle* out);
extern int MXTPUSymbolInvoke(const char* op_name, SymbolHandle* inputs,
                             int num_inputs, const char** in_keys,
                             const char** keys, const char** vals,
                             int num_kwargs, const char* name,
                             SymbolHandle* out);
extern int MXTPUSymbolListArguments(SymbolHandle sym, int* out_size,
                                    const char*** out);
extern int MXTPUSymbolInferShape(SymbolHandle sym, int num_known,
                                 const char** known_names,
                                 const int* known_ndims,
                                 const int64_t* known_dims_concat,
                                 int* out_num_args, int* out_num_aux,
                                 const int** out_ndims,
                                 const int64_t** out_dims_concat);
extern int MXTPUSymbolFree(SymbolHandle h);
extern int MXTPUExecutorBind(SymbolHandle sym, const char* ctx,
                             NDArrayHandle* args, int num_args,
                             const char* grad_req, NDArrayHandle* auxs,
                             int num_aux, ExecutorHandle* out);
extern int MXTPUExecutorForward(ExecutorHandle ex, int is_train,
                                NDArrayHandle* outputs, int* num_outputs);
extern int MXTPUExecutorBackward(ExecutorHandle ex,
                                 NDArrayHandle* out_grads, int n);
extern int MXTPUExecutorArgGrad(ExecutorHandle ex, const char* name,
                                NDArrayHandle* out);
extern int MXTPUExecutorFree(ExecutorHandle h);
extern int MXTPUCreateCachedOp(SymbolHandle sym, CachedOpHandle* out);
extern int MXTPUInvokeCachedOp(CachedOpHandle op, NDArrayHandle* inputs,
                               int num_inputs, int is_train,
                               NDArrayHandle* outputs, int* num_outputs);
extern int MXTPUCachedOpFree(CachedOpHandle h);
extern int MXTPUAutogradSetIsRecording(int rec, int* prev);
extern int MXTPUAutogradSetIsTraining(int train, int* prev);
extern int MXTPUAutogradMarkVariables(int n, NDArrayHandle* vars,
                                      NDArrayHandle* grads);
extern int MXTPUAutogradBackward(int n, NDArrayHandle* heads,
                                 NDArrayHandle* head_grads, int retain);
extern int MXTPUOptimizerCreate(const char* name, const char** keys,
                                const char** vals, int nkw,
                                OptimizerHandle* out);
extern int MXTPUOptimizerUpdate(OptimizerHandle opt, int index,
                                NDArrayHandle weight, NDArrayHandle grad);
extern int MXTPUOptimizerFree(OptimizerHandle h);
extern int MXTPUDataIterCreate(const char* name, const char** keys,
                               const char** vals, int nkw,
                               DataIterHandle* out);
extern int MXTPUDataIterNext(DataIterHandle it, int* more);
extern int MXTPUDataIterGetData(DataIterHandle it, NDArrayHandle* out);
extern int MXTPUDataIterGetLabel(DataIterHandle it, NDArrayHandle* out);
extern int MXTPUDataIterBeforeFirst(DataIterHandle it);
extern int MXTPUDataIterFree(DataIterHandle h);
extern int MXTPUKVStoreCreate(const char* type, KVStoreHandle* out);
extern int MXTPUKVStoreInit(KVStoreHandle kv, int n, const int* keys,
                            NDArrayHandle* vals);
extern int MXTPUKVStorePush(KVStoreHandle kv, int n, const int* keys,
                            NDArrayHandle* vals, int priority);
extern int MXTPUKVStorePull(KVStoreHandle kv, int n, const int* keys,
                            NDArrayHandle* outs, int priority);
extern int MXTPUKVStorePushPull(KVStoreHandle kv, int n, const int* keys,
                                NDArrayHandle* vals, NDArrayHandle* outs,
                                int priority);
extern int MXTPUKVStoreFree(KVStoreHandle h);

#define CHECK(cond, msg)                                            \
  do {                                                              \
    if (!(cond)) {                                                  \
      fprintf(stderr, "FAIL %s: %s\n", msg, MXTPUGetLastError());   \
      return 1;                                                     \
    }                                                               \
  } while (0)

#define BATCH 32
#define NCLASS 10

/* deterministic param init: tiny LCG uniform in [-scale, scale] */
static uint32_t lcg_state = 12345;
static float lcg_uniform(float scale) {
  lcg_state = lcg_state * 1664525u + 1013904223u;
  return scale * (2.0f * ((lcg_state >> 8) / 16777216.0f) - 1.0f);
}

static int64_t shape_size(const int64_t* dims, int nd) {
  int64_t s = 1;
  for (int i = 0; i < nd; ++i) s *= dims[i];
  return s;
}

/* ---- imperative autograd smoke: linear regression converges ---- */
static int autograd_linreg(void) {
  /* w starts at 0; target y = 2x; loss = mean((w*x - y)^2) must drop */
  float xs[8] = {1, 2, 3, 4, -1, -2, 0.5f, 1.5f};
  float ys[8];
  for (int i = 0; i < 8; ++i) ys[i] = 2.0f * xs[i];
  int64_t shp[1] = {8}, wshp[1] = {1};
  float w0[1] = {0.0f}, z0[1] = {0.0f};
  NDArrayHandle x, y, w, wg;
  if (MXTPUNDArrayCreate(xs, shp, 1, 0, "", &x) != 0) return -1;
  if (MXTPUNDArrayCreate(ys, shp, 1, 0, "", &y) != 0) return -1;
  if (MXTPUNDArrayCreate(w0, wshp, 1, 0, "", &w) != 0) return -1;
  if (MXTPUNDArrayCreate(z0, wshp, 1, 0, "", &wg) != 0) return -1;
  if (MXTPUAutogradMarkVariables(1, &w, &wg) != 0) return -1;
  OptimizerHandle opt;
  const char* ok[] = {"learning_rate"};
  const char* ov[] = {"0.05"};
  if (MXTPUOptimizerCreate("sgd", ok, ov, 1, &opt) != 0) return -1;
  float first = -1, last = -1;
  for (int step = 0; step < 25; ++step) {
    int prev;
    if (MXTPUAutogradSetIsRecording(1, &prev) != 0) return -1;
    if (MXTPUAutogradSetIsTraining(1, &prev) != 0) return -1;
    NDArrayHandle pred, diff, sq, loss, tmp[2];
    int n_out = 2;
    NDArrayHandle bm[2] = {x, w};
    if (MXTPUImperativeInvoke("broadcast_mul", bm, 2, NULL, NULL, 0, tmp,
                              &n_out) != 0) return -1;
    pred = tmp[0];
    NDArrayHandle bs[2] = {pred, y};
    n_out = 2;
    if (MXTPUImperativeInvoke("broadcast_sub", bs, 2, NULL, NULL, 0, tmp,
                              &n_out) != 0) return -1;
    diff = tmp[0];
    n_out = 2;
    if (MXTPUImperativeInvoke("square", &diff, 1, NULL, NULL, 0, tmp,
                              &n_out) != 0) return -1;
    sq = tmp[0];
    n_out = 2;
    if (MXTPUImperativeInvoke("mean", &sq, 1, NULL, NULL, 0, tmp,
                              &n_out) != 0) return -1;
    loss = tmp[0];
    if (MXTPUAutogradSetIsRecording(0, &prev) != 0) return -1;
    if (MXTPUAutogradBackward(1, &loss, NULL, 0) != 0) return -1;
    float lv;
    if (MXTPUNDArraySyncCopyToCPU(loss, &lv, sizeof(lv)) != 0) return -1;
    if (step == 0) first = lv;
    last = lv;
    NDArrayHandle g;
    if (MXTPUNDArrayGetGrad(w, &g) != 0) return -1;
    if (MXTPUOptimizerUpdate(opt, 0, w, g) != 0) return -1;
    MXTPUNDArrayFree(g);
    MXTPUNDArrayFree(pred);
    MXTPUNDArrayFree(diff);
    MXTPUNDArrayFree(sq);
    MXTPUNDArrayFree(loss);
  }
  MXTPUOptimizerFree(opt);
  MXTPUNDArrayFree(x);
  MXTPUNDArrayFree(y);
  MXTPUNDArrayFree(w);
  MXTPUNDArrayFree(wg);
  printf("autograd_linreg first=%.4f last=%.4f\n", first, last);
  return (last < first * 0.1f && last < 0.5f) ? 0 : -1;
}

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s train-images.idx train-labels.idx\n",
            argv[0]);
    return 2;
  }
  CHECK(MXTPUCAPIInit("cpu") == 0, "init");

  /* imperative autograd + optimizer path first (cheap) */
  CHECK(autograd_linreg() == 0, "autograd linreg converges");

  /* ---- LeNet symbol (classic geometry, narrowed for CPU CI) ---- */
  SymbolHandle data, label, c1, a1, p1, c2, a2, p2, fl, f1, a3, f2, net;
  CHECK(MXTPUSymbolCreateVariable("data", &data) == 0, "var data");
  CHECK(MXTPUSymbolCreateVariable("softmax_label", &label) == 0,
        "var label");
  {
    const char* k[] = {"kernel", "num_filter"};
    const char* v[] = {"(5,5)", "8"};
    CHECK(MXTPUSymbolInvoke("Convolution", &data, 1, NULL, k, v, 2,
                            "conv1", &c1) == 0, "conv1");
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"tanh"};
    CHECK(MXTPUSymbolInvoke("Activation", &c1, 1, NULL, k, v, 1, "",
                            &a1) == 0, "act1");
  }
  {
    const char* k[] = {"pool_type", "kernel", "stride"};
    const char* v[] = {"max", "(2,2)", "(2,2)"};
    CHECK(MXTPUSymbolInvoke("Pooling", &a1, 1, NULL, k, v, 3, "",
                            &p1) == 0, "pool1");
  }
  {
    const char* k[] = {"kernel", "num_filter"};
    const char* v[] = {"(5,5)", "16"};
    CHECK(MXTPUSymbolInvoke("Convolution", &p1, 1, NULL, k, v, 2,
                            "conv2", &c2) == 0, "conv2");
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"tanh"};
    CHECK(MXTPUSymbolInvoke("Activation", &c2, 1, NULL, k, v, 1, "",
                            &a2) == 0, "act2");
  }
  {
    const char* k[] = {"pool_type", "kernel", "stride"};
    const char* v[] = {"max", "(2,2)", "(2,2)"};
    CHECK(MXTPUSymbolInvoke("Pooling", &a2, 1, NULL, k, v, 3, "",
                            &p2) == 0, "pool2");
  }
  CHECK(MXTPUSymbolInvoke("Flatten", &p2, 1, NULL, NULL, NULL, 0, "",
                          &fl) == 0, "flatten");
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"64"};
    CHECK(MXTPUSymbolInvoke("FullyConnected", &fl, 1, NULL, k, v, 1,
                            "fc1", &f1) == 0, "fc1");
  }
  {
    const char* k[] = {"act_type"};
    const char* v[] = {"tanh"};
    CHECK(MXTPUSymbolInvoke("Activation", &f1, 1, NULL, k, v, 1, "",
                            &a3) == 0, "act3");
  }
  {
    const char* k[] = {"num_hidden"};
    const char* v[] = {"10"};
    CHECK(MXTPUSymbolInvoke("FullyConnected", &a3, 1, NULL, k, v, 1,
                            "fc2", &f2) == 0, "fc2");
  }
  {
    SymbolHandle ins[2] = {f2, label};
    CHECK(MXTPUSymbolInvoke("SoftmaxOutput", ins, 2, NULL, NULL, NULL, 0,
                            "softmax", &net) == 0, "softmax output");
  }

  /* ---- argument shapes via InferShape ---- */
  int n_args = 0;
  const char** arg_names = NULL;
  CHECK(MXTPUSymbolListArguments(net, &n_args, &arg_names) == 0,
        "list arguments");
  /* copy names: the thread-local list is invalidated by later calls */
  char names_buf[32][64];
  CHECK(n_args <= 32, "arg count sane");
  for (int i = 0; i < n_args; ++i) {
    strncpy(names_buf[i], arg_names[i], 63);
    names_buf[i][63] = 0;
  }

  const char* known_names[] = {"data", "softmax_label"};
  int known_ndims[] = {4, 1};
  int64_t known_dims[] = {BATCH, 1, 28, 28, BATCH};
  int got_args = 0, got_aux = 0;
  const int* ndims = NULL;
  const int64_t* dims = NULL;
  CHECK(MXTPUSymbolInferShape(net, 2, known_names, known_ndims,
                              known_dims, &got_args, &got_aux, &ndims,
                              &dims) == 0, "infer shape");
  CHECK(got_args == n_args, "arg shape count");
  CHECK(got_aux == 0, "no aux states for lenet");

  /* ---- allocate args (deterministic small-uniform init) ---- */
  NDArrayHandle args[32];
  int64_t arg_dims[32][8];
  int arg_nd[32];
  {
    int64_t off = 0;
    for (int i = 0; i < n_args; ++i) {
      arg_nd[i] = ndims[i];
      for (int d = 0; d < ndims[i]; ++d) arg_dims[i][d] = dims[off + d];
      off += ndims[i];
    }
  }
  for (int i = 0; i < n_args; ++i) {
    int64_t sz = shape_size(arg_dims[i], arg_nd[i]);
    float* buf = (float*)malloc(sz * sizeof(float));
    /* fan-in-ish scale: 1/sqrt(fan_in) with fan_in from the shape */
    int64_t fan = arg_nd[i] > 1 ? sz / arg_dims[i][0] : sz;
    float scale = 1.0f / sqrtf((float)fan);
    for (int64_t j = 0; j < sz; ++j)
      buf[j] = strcmp(names_buf[i], "data") == 0 ||
                       strcmp(names_buf[i], "softmax_label") == 0
                   ? 0.0f
                   : lcg_uniform(scale);
    CHECK(MXTPUNDArrayCreate(buf, arg_dims[i], arg_nd[i], 0, "",
                             &args[i]) == 0, "create arg");
    free(buf);
  }

  /* per-arg grad_req (MXExecutorBindEX form): params train, data and
   * label bind as 'null' so backward skips input gradients */
  int data_idx = -1, label_idx = -1;
  char grad_req[512] = "";
  for (int i = 0; i < n_args; ++i) {
    if (strcmp(names_buf[i], "data") == 0) data_idx = i;
    if (strcmp(names_buf[i], "softmax_label") == 0) label_idx = i;
  }
  CHECK(data_idx >= 0 && label_idx >= 0, "data/label args present");
  for (int i = 0; i < n_args; ++i) {
    if (i) strcat(grad_req, ",");
    strcat(grad_req, (i == data_idx || i == label_idx) ? "null"
                                                       : "write");
  }

  ExecutorHandle ex;
  CHECK(MXTPUExecutorBind(net, "", args, n_args, grad_req, NULL, 0,
                          &ex) == 0, "executor bind");

  /* grad handles update in place across backward calls: fetch once */
  NDArrayHandle grads[32];
  for (int i = 0; i < n_args; ++i) {
    grads[i] = NULL;
    if (i == data_idx || i == label_idx) continue;
    CHECK(MXTPUExecutorArgGrad(ex, names_buf[i], &grads[i]) == 0,
          "arg grad");
  }

  /* ---- MNISTIter over the synthesized idx files ---- */
  DataIterHandle it;
  {
    char bs[16];
    snprintf(bs, sizeof bs, "%d", BATCH);
    const char* k[] = {"image", "label", "batch_size", "shuffle"};
    const char* v[] = {argv[1], argv[2], bs, "True"};
    CHECK(MXTPUDataIterCreate("MNISTIter", k, v, 4, &it) == 0,
          "MNISTIter create");
  }

  OptimizerHandle opt;
  {
    char rs[32];
    snprintf(rs, sizeof rs, "%.8f", 1.0 / BATCH);
    const char* k[] = {"learning_rate", "momentum", "rescale_grad"};
    const char* v[] = {"0.1", "0.9", rs};
    CHECK(MXTPUOptimizerCreate("sgd", k, v, 3, &opt) == 0, "sgd create");
  }

  /* ---- training loop: 3 epochs over the synthetic set ---- */
  float epoch_loss[3] = {0, 0, 0};
  for (int epoch = 0; epoch < 3; ++epoch) {
    CHECK(MXTPUDataIterBeforeFirst(it) == 0, "reset iter");
    int more = 0, batches = 0;
    double total = 0;
    CHECK(MXTPUDataIterNext(it, &more) == 0, "first next");
    while (more) {
      NDArrayHandle bd, bl;
      CHECK(MXTPUDataIterGetData(it, &bd) == 0, "get data");
      CHECK(MXTPUDataIterGetLabel(it, &bl) == 0, "get label");
      CHECK(MXTPUNDArrayCopyFrom(args[data_idx], bd) == 0, "feed data");
      CHECK(MXTPUNDArrayCopyFrom(args[label_idx], bl) == 0,
            "feed label");
      NDArrayHandle outs[2];
      int n_out = 2;
      CHECK(MXTPUExecutorForward(ex, 1, outs, &n_out) == 0, "forward");
      CHECK(n_out == 1, "one output");
      CHECK(MXTPUExecutorBackward(ex, NULL, 0) == 0, "backward");
      /* cross-entropy from the softmax probabilities */
      float probs[BATCH * NCLASS], labels[BATCH];
      CHECK(MXTPUNDArraySyncCopyToCPU(outs[0], probs, sizeof(probs))
                == 0, "copy probs");
      CHECK(MXTPUNDArraySyncCopyToCPU(bl, labels, sizeof(labels)) == 0,
            "copy labels");
      for (int b = 0; b < BATCH; ++b) {
        float p = probs[b * NCLASS + (int)labels[b]];
        total += -logf(p < 1e-8f ? 1e-8f : p);
      }
      batches += 1;
      for (int i = 0; i < n_args; ++i) {
        if (i == data_idx || i == label_idx) continue;
        CHECK(MXTPUOptimizerUpdate(opt, i, args[i], grads[i]) == 0,
              "sgd update");
      }
      MXTPUNDArrayFree(outs[0]);
      MXTPUNDArrayFree(bd);
      MXTPUNDArrayFree(bl);
      CHECK(MXTPUDataIterNext(it, &more) == 0, "next");
    }
    CHECK(batches > 0, "saw batches");
    epoch_loss[epoch] = (float)(total / (batches * BATCH));
    printf("epoch %d loss %.4f\n", epoch, epoch_loss[epoch]);
  }
  CHECK(epoch_loss[2] < epoch_loss[0] * 0.7f,
        "loss decreased over training");

  /* ---- kvstore: the trainer's push/pull path on a real param ---- */
  {
    KVStoreHandle kv;
    CHECK(MXTPUKVStoreCreate("local", &kv) == 0, "kvstore create");
    int key = 7;
    CHECK(MXTPUKVStoreInit(kv, 1, &key, &args[1]) == 0, "kv init");
    CHECK(MXTPUKVStorePush(kv, 1, &key, &grads[1], 0) == 0, "kv push");
    int64_t sz = shape_size(arg_dims[1], arg_nd[1]);
    float* pulled = (float*)malloc(sz * sizeof(float));
    float* gbuf = (float*)malloc(sz * sizeof(float));
    NDArrayHandle out_nd;
    float* zeros = (float*)calloc(sz, sizeof(float));
    CHECK(MXTPUNDArrayCreate(zeros, arg_dims[1], arg_nd[1], 0, "",
                             &out_nd) == 0, "kv out array");
    free(zeros);
    CHECK(MXTPUKVStorePull(kv, 1, &key, &out_nd, 0) == 0, "kv pull");
    CHECK(MXTPUNDArraySyncCopyToCPU(out_nd, pulled,
                                    sz * (int64_t)sizeof(float)) == 0,
          "copy pulled");
    CHECK(MXTPUNDArraySyncCopyToCPU(grads[1], gbuf,
                                    sz * (int64_t)sizeof(float)) == 0,
          "copy grad");
    int match = 1;
    for (int64_t j = 0; j < sz; ++j)
      if (fabsf(pulled[j] - gbuf[j]) > 1e-5f) match = 0;
    CHECK(match, "pull returns pushed gradient");
    /* fused all-reduce spelling (MXKVStorePushPullEx role) */
    CHECK(MXTPUKVStorePushPull(kv, 1, &key, &grads[1], &out_nd, 0) == 0,
          "kv pushpull");
    CHECK(MXTPUNDArraySyncCopyToCPU(out_nd, pulled,
                                    sz * (int64_t)sizeof(float)) == 0,
          "copy pushpulled");
    match = 1;
    for (int64_t j = 0; j < sz; ++j)
      if (fabsf(pulled[j] - gbuf[j]) > 1e-5f) match = 0;
    CHECK(match, "pushpull returns reduced gradient");
    free(pulled);
    free(gbuf);
    MXTPUNDArrayFree(out_nd);
    MXTPUKVStoreFree(kv);
  }

  /* ---- CachedOp inference with the trained params ---- */
  {
    CachedOpHandle co;
    CHECK(MXTPUCreateCachedOp(net, &co) == 0, "cached op create");
    NDArrayHandle outs[2];
    int n_out = 2;
    CHECK(MXTPUInvokeCachedOp(co, args, n_args, 0, outs, &n_out) == 0,
          "cached op invoke");
    CHECK(n_out == 1, "cached op one output");
    float probs[BATCH * NCLASS];
    CHECK(MXTPUNDArraySyncCopyToCPU(outs[0], probs, sizeof(probs)) == 0,
          "cached op copy");
    /* rows are probability distributions */
    for (int b = 0; b < 2; ++b) {
      float s = 0;
      for (int c = 0; c < NCLASS; ++c) s += probs[b * NCLASS + c];
      CHECK(fabsf(s - 1.0f) < 1e-3f, "cached op softmax rows sum to 1");
    }
    MXTPUNDArrayFree(outs[0]);
    MXTPUCachedOpFree(co);
  }

  for (int i = 0; i < n_args; ++i) {
    MXTPUNDArrayFree(args[i]);
    if (grads[i]) MXTPUNDArrayFree(grads[i]);
  }
  MXTPUOptimizerFree(opt);
  MXTPUDataIterFree(it);
  MXTPUExecutorFree(ex);
  MXTPUSymbolFree(net);
  {
    SymbolHandle syms[] = {data, label, c1, a1, p1, c2, a2, p2, fl, f1,
                           a3, f2};
    for (unsigned i = 0; i < sizeof(syms) / sizeof(syms[0]); ++i)
      MXTPUSymbolFree(syms[i]);
  }
  printf("CAPI_TRAIN_OK final_loss=%.4f\n", epoch_loss[2]);
  return 0;
}
