"""Pipeline parallelism: GPipe-style microbatching over a 'pp' mesh axis.

Ref capability: ABSENT in the reference (SURVEY §2.3 'PP: ABSENT —
closest: group2ctx manual staging, no microbatching'); this is a
capability upgrade alongside TP/SP.

TPU-native design: stage parameters are STACKED on a leading axis of
size P and sharded over the 'pp' mesh axis, so each device holds one
stage.  Inside shard_map, a fori_loop runs the classic GPipe schedule:
at tick t, device 0 feeds microbatch t, every device applies its stage
to its current activation, and activations rotate one hop along the
pipeline with ppermute (ICI neighbour exchange).  After P-1 warmup
ticks the pipe is full; outputs stream off the last device and are
broadcast with a masked psum.  Backward is jax autodiff through the
whole schedule — ppermute transposes to the reverse rotation, giving
the mirrored fill/drain automatically.

Constraints (the standard stacked-pipeline contract): all stages share
one jittable ``stage_fn(params_slice, x) -> y`` with x and y of the
same shape, and the number of microbatches must be >= 1.  Wall-clock
efficiency is n_micro / (n_micro + P - 1) (the GPipe bubble).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError


def _pipeline_sharded(params, xs_local, *, stage_fn, axis_name, n_micro,
                      P):
    """Runs INSIDE shard_map: params leaves are the local (1, ...)
    stage slice; xs_local is the replicated (n_micro, mb, ...) batch."""
    idx = jax.lax.axis_index(axis_name)
    local = jax.tree.map(lambda p: p[0], params)
    T = n_micro + P - 1
    # carries vary across the 'pp' axis (per-device state) — mark them
    # so shard_map's vma check accepts the fori_loop carry
    from . import mesh as _mesh_mod

    acts, outs = _mesh_mod.pcast(
        (jnp.zeros_like(xs_local[0]), jnp.zeros_like(xs_local)),
        axis_name, to="varying")

    def tick(t, carry):
        acts, outs = carry
        # device 0 ingests microbatch t (zeros once drained)
        feed = jnp.where(t < n_micro, xs_local[jnp.minimum(
            t, n_micro - 1)], jnp.zeros_like(acts))
        inp = jnp.where(idx == 0, feed, acts)
        out = stage_fn(local, inp)
        # last device emits microbatch t-(P-1) at tick t
        emit_t = t - (P - 1)
        outs = jnp.where(
            (idx == P - 1) & (emit_t >= 0),
            outs.at[jnp.maximum(emit_t, 0)].set(out), outs)
        # rotate activations one hop down the pipe
        acts = jax.lax.ppermute(
            out, axis_name, [(j, (j + 1) % P) for j in range(P)])
        return acts, outs

    _, outs = jax.lax.fori_loop(0, T, tick, (acts, outs))
    # broadcast the last device's outputs to every device
    mask = (idx == P - 1).astype(outs.dtype)
    return jax.lax.psum(outs * mask, axis_name)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   n_micro=None):
    """Run x through P pipelined stages.

    stage_fn: (params_slice, x_mb) -> y_mb, same shape in/out.
    stacked_params: pytree whose leaves have leading dim P (one slice
      per stage) — shard leading dim over `axis` for real PP.
    x: (B, ...) with B divisible by n_micro (n_micro >= 1; default P).
    Returns (B, ...) outputs (the composition of all stages).
    """
    from jax.sharding import PartitionSpec

    from . import mesh as mesh_mod

    shard_map = mesh_mod.shard_map()

    P = mesh.shape[axis]
    n_micro = P if n_micro is None else int(n_micro)
    if n_micro < 1:
        raise MXNetError(f"n_micro must be >= 1, got {n_micro}")
    B = x.shape[0]
    if B % n_micro:
        raise MXNetError(f"batch {B} must divide into n_micro={n_micro}")
    mb = B // n_micro
    xs = x.reshape((n_micro, mb) + x.shape[1:])

    pspec = jax.tree.map(lambda _: PartitionSpec(axis), stacked_params)
    in_specs = (pspec, PartitionSpec())
    try:
        # cached jit(shard_map) keyed on (stage_fn, mesh, specs, attrs)
        # — a fresh closure per call would retrace every training step
        fn = mesh_mod.spmd_jit(
            _pipeline_sharded, mesh, in_specs, PartitionSpec(),
            stage_fn=stage_fn, axis_name=axis, n_micro=n_micro, P=P)
    except TypeError:
        # unhashable param pytree (dict specs): uncached fallback
        import functools

        fn = jax.jit(shard_map(
            functools.partial(_pipeline_sharded, stage_fn=stage_fn,
                              axis_name=axis, n_micro=n_micro, P=P),
            mesh=mesh, in_specs=in_specs, out_specs=PartitionSpec()))
    out = fn(stacked_params, xs)
    return out.reshape((B,) + x.shape[1:])
