"""INT8 quantization operator family (the fork's specialty).

Ref: src/operator/quantization/ — quantize{,_v2}-inl.h, dequantize-inl.h,
requantize-inl.h, quantized_conv.{cc,cu}, quantized_fully_connected.*,
quantized_pooling.*, quantization_utils.h.

TPU-native design: int8 × int8 → int32 matmul/conv runs natively on the
MXU (``preferred_element_type=jnp.int32``), so the quantized compute ops
are real int8 kernels, not emulation.  Range bookkeeping follows the
reference: a quantized tensor travels as (q, min_range, max_range) with
  int8  (signed, symmetric):  real = q * max(|min|,|max|) / 127
  uint8 (affine):             real = min + q * (max-min) / 255
  int32 (accumulator):        real = q * max(|min|,|max|) / (2^31 - 1)
and the int32 output range of a s8·s8 product is
INT32_MAX/(127*127) * r_data * r_weight (ref: quantization_utils.h
QuantizedRangeForS8S8MultiplicationStruct).  All ops are inference-only
(nondiff), matching the reference.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register

_INT32_MAX = float(2**31 - 1)


def _abs_range(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


def _q8(x, real_range):
    scale = 127.0 / jnp.maximum(real_range, 1e-30)
    return jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)


# ---------------------------------------------------------------------------
# quantize / quantize_v2 (ref: quantize-inl.h, quantize_v2-inl.h)


def _k_quantize(data, min_range, max_range, *, out_type="int8"):
    mn = jnp.asarray(min_range, jnp.float32).reshape(())
    mx = jnp.asarray(max_range, jnp.float32).reshape(())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((data - mn) * scale), 0, 255).astype(jnp.uint8)
        return q, mn, mx
    r = _abs_range(mn, mx)
    return _q8(data, r), -r, r

register("_contrib_quantize", _k_quantize,
         arg_names=("data", "min_range", "max_range"),
         aliases=("quantize",), num_outputs=3, nondiff=True)


def _k_quantize_v2(data, *, out_type="int8", min_calib_range=None,
                   max_calib_range=None):
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    return _k_quantize(data, mn, mx, out_type=out_type)

register("_contrib_quantize_v2", _k_quantize_v2, arg_names=("data",),
         aliases=("quantize_v2",), num_outputs=3, nondiff=True)


# ---------------------------------------------------------------------------
# dequantize (ref: dequantize-inl.h)


def _k_dequantize(data, min_range, max_range, *, out_type="float32"):
    # ranges BROADCAST against data instead of being forced scalar: the
    # per-channel int8 kernels below thread (C,)-shaped (or (C,1,...))
    # range vectors through the same (q, min, max) triple protocol, so
    # one dequantize serves per-tensor and per-channel alike
    mn = jnp.asarray(min_range, jnp.float32)
    mx = jnp.asarray(max_range, jnp.float32)
    if data.dtype == jnp.uint8:
        return mn + data.astype(jnp.float32) * (mx - mn) / 255.0
    if data.dtype == jnp.int32:
        return data.astype(jnp.float32) * _abs_range(mn, mx) / _INT32_MAX
    return data.astype(jnp.float32) * _abs_range(mn, mx) / 127.0

register("_contrib_dequantize", _k_dequantize,
         arg_names=("data", "min_range", "max_range"),
         aliases=("dequantize",), nondiff=True)


# ---------------------------------------------------------------------------
# requantize: int32 accumulator → calibrated int8 (ref: requantize-inl.h)


def _k_requantize(data, min_range, max_range, *, min_calib_range=None,
                  max_calib_range=None):
    real = _k_dequantize(data, min_range, max_range)
    if min_calib_range is not None and max_calib_range is not None:
        r = _abs_range(jnp.float32(min_calib_range),
                       jnp.float32(max_calib_range))
    else:
        r = jnp.max(jnp.abs(real))
    return _q8(real, r), -r, r

register("_contrib_requantize", _k_requantize,
         arg_names=("data", "min_range", "max_range"),
         aliases=("requantize",), num_outputs=3, nondiff=True)


def _k_requantize_v2(data, min_range, max_range, min_calib, max_calib, *,
                     act=None):
    """Array-calibrated requantize — the fold op.

    Same math as ``_k_requantize`` (dequantize at the incoming — possibly
    per-channel — range, re-quantize symmetric int8 at the calibrated
    range), but the calibrated range arrives as ARRAYS so it can live as
    a runtime parameter of a compiled graph (re-calibration needs no
    recompile), and an optional relu is applied IN int8: symmetric
    scaling commutes with relu (``dequant(max(q,0)) == relu(dequant(q))``),
    so a calibrated relu layer keeps its activations int8 end-to-end.
    """
    real = _k_dequantize(data, min_range, max_range)
    r = _abs_range(jnp.asarray(min_calib, jnp.float32).reshape(()),
                   jnp.asarray(max_calib, jnp.float32).reshape(()))
    q = _q8(real, r)
    if act == "relu":
        q = jnp.maximum(q, jnp.int8(0))
    return q, -r, r

register("_contrib_requantize_v2", _k_requantize_v2,
         arg_names=("data", "min_range", "max_range", "min_calib",
                    "max_calib"),
         aliases=("requantize_v2",), num_outputs=3, nondiff=True)


# ---------------------------------------------------------------------------
# quantized compute ops: FC / conv / pooling / flatten
# Bias handling follows the reference: bias is re-quantized to the
# accumulator scale s_data*s_weight and added in int32.


def _s8s8_out_range(min_d, max_d, min_w, max_w):
    r = (_abs_range(min_d, max_d) * _abs_range(min_w, max_w)
         * (_INT32_MAX / (127.0 * 127.0)))
    return -r, r


def _bias_to_i32(bias, min_b, max_b, min_d, max_d, min_w, max_w):
    real_b = _k_dequantize(bias, min_b, max_b)
    s_d = 127.0 / jnp.maximum(_abs_range(min_d, max_d), 1e-30)
    s_w = 127.0 / jnp.maximum(_abs_range(min_w, max_w), 1e-30)
    return jnp.round(real_b * s_d * s_w).astype(jnp.int32)


def _parse_q_inputs(no_bias, rest):
    """Arity-aware input parsing shared by the int8 FC/conv kernels.

    The reference's C++ ops adjust their EXPECTED input list on
    ``no_bias`` (quantized_conv.cc/quantized_fully_connected.cc): with
    a bias the inputs are (bias, min_data, max_data, min_weight,
    max_weight, min_bias, max_bias); without, the bias slot and its
    ranges are absent entirely — which is how the symbolic
    quantize_model pass wires the graph.  The eager frontend instead
    passes an explicit ``None`` placeholder in the bias slot; accept
    both spellings."""
    if no_bias:
        # strip the bias slot by ARITY, not by None-ness: the eager
        # frontend passes an explicit None there (5 trailing inputs),
        # and a symbolically built call can carry a bound-but-ignored
        # implicit bias variable (5 or, with bias ranges, 7); the
        # 4-input form from quantize_model has no slot to strip
        if len(rest) in (5, 7):
            rest = rest[1:]
        min_data, max_data, min_weight, max_weight = rest[:4]
        return None, min_data, max_data, min_weight, max_weight, None, None
    bias, min_data, max_data, min_weight, max_weight = rest[:5]
    min_bias, max_bias = rest[5:7] if len(rest) >= 7 else (None, None)
    return (bias, min_data, max_data, min_weight, max_weight, min_bias,
            max_bias)


def _k_quantized_fully_connected(data, weight, *rest, num_hidden,
                                 no_bias=False, flatten=True):
    (bias, min_data, max_data, min_weight, max_weight, min_bias,
     max_bias) = _parse_q_inputs(no_bias, rest)
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    out = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if not no_bias and bias is not None:
        out = out + _bias_to_i32(bias, min_bias, max_bias, min_data,
                                 max_data, min_weight, max_weight)
    mn, mx = _s8s8_out_range(min_data, max_data, min_weight, max_weight)
    return out, mn, mx

register("_contrib_quantized_fully_connected", _k_quantized_fully_connected,
         arg_names=("data", "weight", "bias", "min_data", "max_data",
                    "min_weight", "max_weight", "min_bias", "max_bias"),
         aliases=("quantized_fully_connected",), num_outputs=3, nondiff=True)


_CONV_DIMS = {1: ("NCW", "OIW", "NCW"),
              2: ("NCHW", "OIHW", "NCHW"),
              3: ("NCDHW", "OIDHW", "NCDHW")}


def _k_quantized_conv(data, weight, *rest, kernel,
                      stride=(), dilate=(), pad=(), num_filter=0,
                      num_group=1, no_bias=False, layout=None):
    (bias, min_data, max_data, min_weight, max_weight, min_bias,
     max_bias) = _parse_q_inputs(no_bias, rest)
    nd = len(kernel)
    stride = stride or (1,) * nd
    dilate = dilate or (1,) * nd
    pad = pad or (0,) * nd
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMS[nd])
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, preferred_element_type=jnp.int32)
    if not no_bias and bias is not None:
        b = _bias_to_i32(bias, min_bias, max_bias, min_data, max_data,
                         min_weight, max_weight)
        out = out + b.reshape((1, -1) + (1,) * nd)
    mn, mx = _s8s8_out_range(min_data, max_data, min_weight, max_weight)
    return out, mn, mx

register("_contrib_quantized_conv", _k_quantized_conv,
         arg_names=("data", "weight", "bias", "min_data", "max_data",
                    "min_weight", "max_weight", "min_bias", "max_bias"),
         aliases=("quantized_conv",), num_outputs=3, nondiff=True)


# ---------------------------------------------------------------------------
# per-channel compute ops: the compile-native quantize_net path.
# Weight ranges arrive as a PER-OUTPUT-CHANNEL vector (shape (C,), or
# (1,) for per-tensor) instead of scalar min/max — per-channel scaling
# closes most of the accuracy gap symmetric per-tensor scaling leaves
# (one outlier row no longer wrecks every other row's resolution).  The
# fp32 bias is re-quantized to the per-channel accumulator scale
# s_data*s_weight_c INSIDE the kernel (ref: quantization_utils.h bias
# handling, generalized per channel), and the int32 output's range rides
# the triple protocol as a broadcastable vector so the stock
# dequantize/requantize_v2 close the chain.


def _ranges_i32_pc(min_data, max_data, wrange, bcast_shape):
    """Scalar data range x per-channel weight range -> (r_d, r_w, r_out)
    with r_out shaped to broadcast against the int32 accumulator."""
    r_d = _abs_range(jnp.asarray(min_data, jnp.float32).reshape(()),
                     jnp.asarray(max_data, jnp.float32).reshape(()))
    r_w = jnp.maximum(jnp.asarray(wrange, jnp.float32).reshape(-1), 1e-30)
    r_o = (r_d * r_w * (_INT32_MAX / (127.0 * 127.0))).reshape(bcast_shape)
    return r_d, r_w, r_o


def _bias_to_i32_pc(bias, r_d, r_w):
    s = (127.0 / jnp.maximum(r_d, 1e-30)) * (127.0 / r_w)
    return jnp.round(bias.astype(jnp.float32) * s).astype(jnp.int32)


def _k_quantized_dense_pc(data, weight, wrange, *rest, num_hidden,
                          no_bias=False, flatten=True):
    if no_bias:
        bias = None
        min_data, max_data = rest[:2]
    else:
        bias, min_data, max_data = rest[:3]
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    out = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    r_d, r_w, r_o = _ranges_i32_pc(min_data, max_data, wrange, (-1,))
    if not no_bias and bias is not None:
        out = out + _bias_to_i32_pc(bias, r_d, r_w)
    return out, -r_o, r_o

register("_contrib_quantized_dense_pc", _k_quantized_dense_pc,
         arg_names=("data", "weight", "wrange", "bias", "min_data",
                    "max_data"),
         aliases=("quantized_dense_pc",), num_outputs=3, nondiff=True)


def _k_quantized_conv_pc(data, weight, wrange, *rest, kernel, stride=(),
                         dilate=(), pad=(), num_filter=0, num_group=1,
                         no_bias=False):
    if no_bias:
        bias = None
        min_data, max_data = rest[:2]
    else:
        bias, min_data, max_data = rest[:3]
    nd = len(kernel)
    stride = stride or (1,) * nd
    dilate = dilate or (1,) * nd
    pad = pad or (0,) * nd
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMS[nd])
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group, preferred_element_type=jnp.int32)
    r_d, r_w, r_o = _ranges_i32_pc(min_data, max_data, wrange,
                                   (-1,) + (1,) * nd)
    if not no_bias and bias is not None:
        b = _bias_to_i32_pc(bias, r_d, r_w)
        out = out + b.reshape((1, -1) + (1,) * nd)
    return out, -r_o, r_o

register("_contrib_quantized_conv_pc", _k_quantized_conv_pc,
         arg_names=("data", "weight", "wrange", "bias", "min_data",
                    "max_data"),
         aliases=("quantized_conv_pc",), num_outputs=3, nondiff=True)


def _k_quantized_pooling(data, min_data, max_data, *, kernel=(), pool_type="max",
                         stride=(), pad=(), global_pool=False):
    nd = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        init = jnp.iinfo(jnp.int8).min if data.dtype == jnp.int8 else 0
        out = lax.reduce_window(data, jnp.array(init, data.dtype),
                                lax.max, window, strides, padding)
    else:  # avg pooling stays in int32 then rounds back to the same scale
        s = lax.reduce_window(data.astype(jnp.int32), jnp.int32(0), lax.add,
                              window, strides, padding)
        denom = 1
        for k in kernel:
            denom *= k
        out = jnp.round(s / denom).astype(data.dtype)
    return out, jnp.asarray(min_data, jnp.float32).reshape(()), \
        jnp.asarray(max_data, jnp.float32).reshape(())

register("_contrib_quantized_pooling", _k_quantized_pooling,
         arg_names=("data", "min_data", "max_data"),
         aliases=("quantized_pooling",), num_outputs=3, nondiff=True)


def _k_quantized_flatten(data, min_data, max_data):
    return (data.reshape(data.shape[0], -1),
            jnp.asarray(min_data, jnp.float32).reshape(()),
            jnp.asarray(max_data, jnp.float32).reshape(()))

register("_contrib_quantized_flatten", _k_quantized_flatten,
         arg_names=("data", "min_data", "max_data"),
         aliases=("quantized_flatten",), num_outputs=3, nondiff=True)
