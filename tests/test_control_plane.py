"""mxnet_tpu.serve.control_plane — the cross-process serving tier.

Covers ISSUE 19's contract: the MXRP frame codec round-trips tensors
and rejects newer-wire frames loudly; a RemoteReplica is
bit-identical to the in-process server it fronts; a mid-stream
connection kill (injected at the cataloged ``serve.rpc.send`` fault
point) fails over through the router's existing re-dispatch path with
the token stream intact; a slow stream consumer never head-of-line
blocks other requests on the shared connection; the autoscaler's
hysteresis, cooldown and bounds; spawn failures and wire errors land
in the retryable classification classes; stale registry leases are
rejected; and the router's ``requests_lost`` audit stays exactly 0
across a connection kill.

All tier-1 tests run in ONE process over real localhost sockets (the
actual 3-subprocess chaos gate lives in ``tools/ctrl_smoke.py``).
"""
import os
import struct
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import base, serve
from mxnet_tpu.parallel.dist import LeaseDir
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience.supervisor import classify
from mxnet_tpu.serve import control_plane as cp
from mxnet_tpu.serve.control_plane.rpc import (RPCConnectionError,
                                               WIRE_MAGIC, WIRE_VERSION)

VOCAB = 32


def _decode_server(seed=4):
    mx.random.seed(seed)
    model = serve.TinyDecoder(vocab=VOCAB, embed=8)
    model.initialize(mx.init.Xavier())
    spec = serve.BucketSpec(batch_sizes=(1, 2), example_shape=(None,),
                            lengths=(4, 8), dtype="int32")
    srv = serve.DecodeServer(model, spec, max_slots=2, max_len=16)
    srv.start()
    return srv


@pytest.fixture(scope="module")
def decode_pair():
    """Two warmed same-seed decode servers behind endpoints — the
    bit-identical replica pool every cross-process test rides.  Tests
    must NOT shut the routers down (that would shut down the shared
    servers through the wire); they drop their client connections
    instead."""
    pair = []
    for _ in range(2):
        srv = _decode_server(seed=4)
        pair.append((srv, cp.serve_replica(srv)))
    yield pair
    for srv, ep in pair:
        ep.stop()
        srv.shutdown(drain=False)


def _remotes(decode_pair):
    return [cp.RemoteReplica(ep.host, ep.port, rid=i)
            for i, (_, ep) in enumerate(decode_pair)]


def _drop(replicas):
    for rr in replicas:
        rr._teardown(RPCConnectionError("test teardown"))


# ---------------------------------------------------------------------------
# 1. wire codec


def test_wire_roundtrip_and_version_mismatch():
    import socket

    a, b = socket.socketpair()
    try:
        meta = {"op": "x", "rid": 3, "kwargs": {"k": 1}}
        arrays = {"t": np.arange(6, dtype=np.int32).reshape(2, 3),
                  "f": np.linspace(0, 1, 4, dtype=np.float32)}
        cp.send_frame(a, meta, arrays)
        got_meta, got = cp.recv_frame(b)
        assert got_meta == meta
        for k in arrays:
            assert got[k].dtype == arrays[k].dtype
            assert np.array_equal(got[k], arrays[k])

        # payload-less frame
        cp.send_frame(a, {"op": "ping"})
        assert cp.recv_frame(b) == ({"op": "ping"}, None)

        # a frame stamped by a NEWER build is rejected with a
        # diagnosis, never misparsed
        hdr = struct.Struct("<HIQ")
        a.sendall(WIRE_MAGIC + hdr.pack(WIRE_VERSION + 7, 2, 0) + b"{}")
        with pytest.raises(mx.MXNetError, match="newer mxnet_tpu"):
            cp.recv_frame(b)

        # bad magic: not our protocol at all
        a.sendall(b"HTTP" + b"\x00" * hdr.size)
        with pytest.raises(mx.MXNetError, match="bad magic"):
            cp.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_truncated_frame_classifies_network_not_corrupt():
    """A connection dying MID-FRAME is a transport failure the router
    retries — it must NOT classify like a corrupt checkpoint file even
    though both involve truncation."""
    import socket

    a, b = socket.socketpair()
    try:
        hdr = struct.Struct("<HIQ")
        a.sendall(WIRE_MAGIC + hdr.pack(WIRE_VERSION, 100, 0) + b"{par")
        a.close()
        with pytest.raises(RPCConnectionError, match="truncated frame"):
            cp.recv_frame(b)
        try:
            cp.recv_frame(b)
        except RPCConnectionError as e:
            assert classify(e) == "network"
    finally:
        b.close()
    assert classify(ConnectionResetError("peer reset")) == "network"
    assert classify(ConnectionRefusedError("nope")) == "network"
    assert classify(BrokenPipeError("gone")) == "network"
    # the fatal/corrupt passthrough matrix is untouched
    assert classify(mx.MXNetError(
        "corrupt or truncated NDArray file")) == "corrupt_checkpoint"
    assert classify(ValueError("boom")) == "fatal"


# ---------------------------------------------------------------------------
# 2. remote parity


def test_remote_replica_parity_bit_identical(decode_pair):
    """The SAME request through the wire and in-process returns the
    SAME bytes — RemoteReplica is a transport, not a reinterpretation."""
    srv, _ = decode_pair[0]
    (rr,) = _remotes(decode_pair)[:1]
    rr.start()
    try:
        rng = np.random.RandomState(7)
        for _ in range(3):
            prompt = rng.randint(
                0, VOCAB, size=int(rng.randint(2, 7))).astype(np.int32)
            handle = rr.submit(prompt, max_new_tokens=5)
            toks = list(handle)
            remote = handle.result(timeout=60)
            local = srv.generate(prompt, max_new_tokens=5, timeout=60)
            assert np.array_equal(remote, np.asarray(local))
            assert toks == [int(t) for t in local]
        assert rr.pending() == srv.pending()
        assert np.array_equal(rr.probe_example(), srv.probe_example())
        assert rr.health()["ok"] is True
        assert rr.stats()["admitted"] >= 3
    finally:
        _drop([rr])


def test_remote_model_server_parity():
    from mxnet_tpu.gluon import nn

    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False, in_units=6, activation="relu"),
            nn.Dense(5, flatten=False, in_units=8))
    net.initialize(mx.init.Xavier())
    spec = serve.BucketSpec(batch_sizes=(1, 2),
                            example_shape=(None, 6), lengths=(4, 8))
    srv = serve.ModelServer(net, spec, max_queue=16)
    srv.start()
    ep = cp.serve_replica(srv)
    rr = cp.RemoteReplica(ep.host, ep.port, rid=0)
    try:
        rr.start()
        x = np.random.RandomState(0).rand(4, 6).astype(np.float32)
        remote = rr.submit(x).result(timeout=60)
        local = srv.predict(x, timeout=60)
        assert np.array_equal(remote, np.asarray(local))
    finally:
        _drop([rr])
        ep.stop()
        srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# 3/4. pooled streaming: failover + no HOL blocking


def test_midstream_connection_kill_fails_over(decode_pair):
    """Kill the serving connection after 2 streamed tokens (injected at
    ``serve.rpc.send``): the router re-dispatches on the other replica
    and the CONSUMER sees one uninterrupted, duplicate-free stream —
    bit-identical to a single-server run."""
    srv0, _ = decode_pair[0]
    replicas = _remotes(decode_pair)
    router = serve.Router(servers=replicas, health_sec=0.0)
    router.start()
    try:
        prompt = np.array([1, 2, 3], np.int32)
        ref = [int(t) for t in srv0.generate(prompt, max_new_tokens=6,
                                             timeout=60)]
        # stall the (in-process) decode loop so the stream is still
        # LIVE when the wire is cut — without it a fast box finishes
        # all 6 tokens before the victim is even picked
        stall = faults.FaultPlan([{"site": "serve.decode",
                                   "action": "stall", "delay_s": 0.05,
                                   "times": None}])
        with faults.armed(stall):
            handle = router.submit_stream(prompt, max_new_tokens=6)
            got = [next(handle), next(handle)]
            # find who is serving the stream, then cut ITS connection
            victim = next(r for r in replicas if r._pending)
            plan = faults.FaultPlan([{"site": "serve.rpc.send",
                                      "action": "raise",
                                      "match": {"replica": victim.rid}}])
            with faults.armed(plan):
                with pytest.raises(mx.MXNetError):
                    victim.ping()   # the send that drops the wire
        assert [f["site"] for f in plan.fired()] == ["serve.rpc.send"]
        assert plan.fired()[0]["ctx"]["replica"] == victim.rid
        got += list(handle)
        assert got == ref                      # no gap, no duplicates
        assert np.array_equal(handle.result(timeout=60),
                              np.asarray(ref, np.int32))
        s = router.stats()
        assert s["retries"] >= 1
        assert s["requests_lost"] == 0
    finally:
        _drop(replicas)


def test_slow_consumer_does_not_block_others(decode_pair):
    """Two streams multiplexed on ONE replica connection: the consumer
    ignoring stream A must not stall stream B's tokens (the demux
    drains the socket unconditionally into per-request queues)."""
    replicas = _remotes(decode_pair)[:1]
    router = serve.Router(servers=replicas, health_sec=0.0)
    router.start()
    try:
        slow = router.submit_stream(np.array([1, 2, 3], np.int32),
                                    max_new_tokens=8)
        fast = router.submit_stream(np.array([4, 5], np.int32),
                                    max_new_tokens=4)
        # consume B to completion while A sits unread
        fast_toks = list(fast)
        assert len(fast_toks) == 4
        assert np.array_equal(fast.result(timeout=60),
                              np.asarray(fast_toks, np.int32))
        # A lost nothing while we ignored it
        slow_toks = list(slow)
        assert len(slow_toks) == 8
        s = router.stats()
        assert s["served"] == 2 and s["requests_lost"] == 0
    finally:
        _drop(replicas)


# ---------------------------------------------------------------------------
# 5. autoscaler


class _FakePool:
    def __init__(self, n=1):
        self.n = n
        self.actions = []

    def replica_count(self):
        return self.n

    def healthy_count(self):
        return self.n

    def load(self):
        return 0.0

    def scale_up(self):
        self.n += 1
        self.actions.append("up")
        return self.n

    def scale_down(self, timeout=60.0):
        self.n -= 1
        self.actions.append("down")
        return self.n


class _FakeMonitor:
    def __init__(self):
        self.state = "ok"

    def status(self):
        return (self.state, [] if self.state == "ok" else ["latency"])


@pytest.fixture
def _ctrl_env():
    """Pin the restart-free autoscaler knobs for the test, then
    restore."""
    names = ("CTRL_COOLDOWN_SEC", "CTRL_SCALE_UP_OCCUPANCY",
             "CTRL_SCALE_DOWN_OCCUPANCY")
    base.setenv("CTRL_COOLDOWN_SEC", 0)
    yield
    for n in names:
        base.setenv(n, None)


def test_autoscaler_hysteresis_cooldown_and_bounds(_ctrl_env):
    pool = _FakePool(n=1)
    loads = []
    scaler = cp.Autoscaler(pool, min_replicas=1, max_replicas=3,
                           up_ticks=2, down_ticks=2,
                           load_fn=lambda: loads.pop(0))
    # hysteresis: ONE hot tick is not a trend
    loads[:] = [0.9, 0.2, 0.9, 0.9]
    assert scaler.tick()["action"] == "hold"
    assert scaler.tick()["action"] == "hold"   # streak broken
    assert scaler.tick()["action"] == "hold"
    assert scaler.tick()["action"] == "up"     # 2 consecutive
    assert pool.n == 2

    # cooldown: a fresh breach inside the window is blocked
    base.setenv("CTRL_COOLDOWN_SEC", 3600)
    before = cp.ctrl_stats()["blocked_cooldown"]
    loads[:] = [0.9, 0.9]
    scaler.tick()
    assert scaler.tick()["action"] == "hold"
    assert cp.ctrl_stats()["blocked_cooldown"] == before + 1
    base.setenv("CTRL_COOLDOWN_SEC", 0)

    # bounds: at max_replicas the breach is tallied, not actuated
    # (the up-streak persisted across the cooldown block, so this
    # single hot tick reaches the actuation gate again)
    pool.n = 3
    before = cp.ctrl_stats()["blocked_bounds"]
    loads[:] = [0.9]
    assert scaler.tick()["action"] == "hold"
    assert cp.ctrl_stats()["blocked_bounds"] == before + 1
    assert pool.n == 3

    # scale down on sustained idle, but never below min_replicas
    loads[:] = [0.1, 0.1, 0.1, 0.1, 0.1, 0.1]
    acts = [scaler.tick()["action"] for _ in range(4)]
    assert acts.count("down") == 2 and pool.n == 1
    before = cp.ctrl_stats()["blocked_bounds"]
    assert scaler.tick()["action"] == "hold"   # streak rebuilding
    assert scaler.tick()["action"] == "hold"   # blocked at the floor
    assert pool.n == 1
    assert cp.ctrl_stats()["blocked_bounds"] == before + 1


def test_autoscaler_slo_pressure_scales_up(_ctrl_env):
    """A firing SLO counts as pressure even when queues look shallow —
    latency degrades before occupancy saturates."""
    pool = _FakePool(n=1)
    mon = _FakeMonitor()
    scaler = cp.Autoscaler(pool, monitor=mon, min_replicas=1,
                           max_replicas=3, up_ticks=2, down_ticks=2,
                           load_fn=lambda: 0.3)
    mon.state = "degraded"
    assert scaler.tick()["action"] == "hold"
    d = scaler.tick()
    assert d["action"] == "up" and "slo" in d["reason"]
    assert pool.n == 2


# ---------------------------------------------------------------------------
# 6. spawn failure classification


def test_spawn_failure_injected_and_classified(tmp_path):
    proc = cp.ReplicaProcess(["/definitely/not/a/binary"],
                             str(tmp_path), "7")
    plan = faults.FaultPlan([{"site": "serve.replica.spawn",
                              "action": "raise"}])
    with faults.armed(plan):
        with pytest.raises(mx.MXNetError) as ei:
            proc.spawn()
    assert classify(ei.value) == "transient"
    assert plan.fired()[0]["ctx"]["replica"] == "7"

    # a real exec failure is a ReplicaSpawnError, also retryable
    with pytest.raises(cp.ReplicaSpawnError) as ei:
        proc.spawn()
    assert classify(ei.value) == "transient"
    assert "spawn failed" in str(ei.value)


# ---------------------------------------------------------------------------
# 7. discovery leases


def test_discovery_rejects_stale_leases(tmp_path):
    d = str(tmp_path)
    live = LeaseDir(d, prefix="replica", lease_sec=5.0)
    live.publish("0", {"host": "h", "port": 1, "pid": 11,
                       "kind": "decode"})
    live.publish("1", {"host": "h", "port": 2, "pid": 22,
                       "kind": "decode"})
    # replica 1 was SIGKILLed long ago: its marker stopped refreshing
    old = time.time() - 3600
    os.utime(live.path_for("1"), (old, old))
    before = cp.ctrl_stats()["stale_leases_rejected"]
    found = cp.discover_replicas(d, lease_sec=5.0)
    assert set(found) == {"0"}
    assert found["0"]["port"] == 1
    assert cp.ctrl_stats()["stale_leases_rejected"] == before + 1
    # a retired lease disappears entirely
    live.retire("0")
    assert cp.discover_replicas(d, lease_sec=5.0) == {}


# ---------------------------------------------------------------------------
# 8. zero-loss audit across a kill


def test_requests_lost_zero_across_connection_kill(decode_pair):
    """A burst with a connection kill in the middle: every request is
    accounted for (served or failed), the audit identity holds at
    exactly zero, and survivors' results stay bit-identical."""
    srv0, _ = decode_pair[0]
    replicas = _remotes(decode_pair)
    router = serve.Router(servers=replicas, health_sec=0.0)
    router.start()
    try:
        rng = np.random.RandomState(11)
        prompts = [rng.randint(0, VOCAB, size=int(rng.randint(2, 7)))
                   .astype(np.int32) for _ in range(6)]
        refs = [[int(t) for t in srv0.generate(p, max_new_tokens=4,
                                               timeout=60)]
                for p in prompts]
        futs = [router.submit(p, max_new_tokens=4) for p in prompts[:3]]
        plan = faults.FaultPlan([{"site": "serve.rpc.send",
                                  "action": "raise",
                                  "match": {"replica": 0}}])
        with faults.armed(plan):
            try:
                replicas[0].ping()   # cut replica 0's wire mid-burst
            except mx.MXNetError:
                pass
        futs += [router.submit(p, max_new_tokens=4)
                 for p in prompts[3:]]
        outs = [f.result(timeout=120) for f in futs]
        for out, ref in zip(outs, refs):
            assert [int(t) for t in out] == ref
        s = router.stats()
        assert s["served"] == 6
        assert s["requests_lost"] == 0
        # the books balance by construction, not by luck:
        assert s["submitted"] == 6
        assert s["failed"] == 0
    finally:
        _drop(replicas)


# ---------------------------------------------------------------------------
# decode sinks (the multiplexing hook the endpoint rides)


def test_decode_handle_sink_replays_history(decode_pair):
    """add_sink() attached LATE still sees every token exactly once,
    then exactly one terminal — the endpoint can attach whenever the
    submit frame arrives."""
    srv, _ = decode_pair[0]
    handle = srv.submit(np.array([1, 2, 3], np.int32), max_new_tokens=5)
    expect = [int(t) for t in handle.result(timeout=60)]
    seen = []
    done = threading.Event()
    handle.add_sink(lambda item: (seen.append(item),
                                  done.set()
                                  if item is cp.rpc.STREAM_DONE
                                  or isinstance(item, BaseException)
                                  else None))
    assert done.wait(30)
    assert seen[:-1] == expect
    assert seen[-1] is cp.rpc.STREAM_DONE
