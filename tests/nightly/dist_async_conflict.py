"""dist_async at n=3: conflicting and out-of-order pushes.

VERDICT r2 weak #5: async semantics were only tested at n=2 with
commutative updates.  This script drives three workers through

1. a DETERMINISTIC out-of-order interleaving (w2 pushes first, then
   w0, then w1 — the reverse of rank order) asserting the exact
   partial merge each worker observes at its turn (per-push server
   merge, no barrier),
2. a CONCURRENT push storm (50 unsynchronized pushes per worker)
   asserting the final merged sum is exact — no lost or double-applied
   updates under real connection-level races,
3. a server-side optimizer round asserting every worker's push was
   applied EXACTLY once (distinct powers of ten make any loss or
   double-apply visible in the final value).

Ref: tests/nightly/dist_async_kvstore.py (upstream) scaled past its
2-worker commutative case.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import dist  # noqa: E402

dist.init()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import kvstore, nd  # noqa: E402

kv = kvstore.create("dist_async")
rank, size = kv.rank, kv.num_workers
assert size == 3, f"this test is written for 3 workers, got {size}"
tmpdir = os.environ.get("MXTPU_TEST_TMPDIR", "/tmp")
port = os.environ["DMLC_PS_ROOT_PORT"]


def marker(name):
    return os.path.join(tmpdir, f"conflict_{port}_{name}")


def wait_for(name, timeout=15.0):
    deadline = time.time() + timeout
    while not os.path.exists(marker(name)):
        if time.time() > deadline:
            raise AssertionError(f"timed out waiting for {name}")
        time.sleep(0.02)


def signal(name):
    with open(marker(name), "w") as f:
        f.write("go")


kv.init("w", nd.zeros((4,)))
kv.barrier()

# -- phase 1: reverse-rank-order pushes, exact partial merges ------------
push_val = {0: 1.0, 1: 2.0, 2: 4.0}[rank]
order = [2, 0, 1]                      # deliberately not rank order
seen_before_me = 0.0
for r in order:
    if r == rank:
        break
    seen_before_me += {0: 1.0, 1: 2.0, 2: 4.0}[r]

if order.index(rank) > 0:
    wait_for(f"phase1_{order[order.index(rank) - 1]}")
kv.push("w", [nd.ones((4,)) * push_val])
out = nd.zeros((4,))
kv.pull("w", out=out)
expect = seen_before_me + push_val
assert np.allclose(out.asnumpy(), expect), \
    f"rank {rank}: saw {out.asnumpy()[0]}, expected {expect}"
signal(f"phase1_{rank}")

kv.barrier()
base = 7.0  # 1 + 2 + 4

# -- phase 2: unsynchronized concurrent storm ----------------------------
N = 50
for _ in range(N):
    kv.push("w", [nd.ones((4,))])
kv.barrier()
out = nd.zeros((4,))
kv.pull("w", out=out)
expect = base + size * N
assert np.allclose(out.asnumpy(), expect), (out.asnumpy()[0], expect)

# -- phase 3: server-side optimizer, exactly-once application ------------
kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
kv.barrier()
kv.push("w", [nd.ones((4,)) * (10.0 ** rank)])
kv.barrier()
out = nd.zeros((4,))
kv.pull("w", out=out)
expect = base + size * N - (1.0 + 10.0 + 100.0)
assert np.allclose(out.asnumpy(), expect), (out.asnumpy()[0], expect)

print(f"worker {rank}/{size}: dist_async conflict OK "
      f"(out-of-order merge, {N}-push storm, exactly-once optimizer)")
