"""Large-tensor tier: >2^31-element NDArrays and int64 indexing.

Ref role: tests/nightly/test_large_array.py — the reference's nightly
large-tensor suite guards the int64 indexing build (USE_INT64_TENSOR_SIZE)
against 32-bit index truncation in kernels and the front end.  The XLA
analogue: index arithmetic must survive past 2^31 elements through
reshape/slice/take/reduce/argmax and the imperative front end.

Scaled to this box: one shared uint8 array of 2^31+16 elements (~2.1 GB)
exercised by every test; MXTPU_TEST_LARGE_DTYPE=float32 upgrades to the
8.6 GB variant on hosts with the RAM/HBM for it (the TPU-host run).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

LARGE = 2**31 + 16  # past the int32 index boundary
_DTYPE = os.environ.get("MXTPU_TEST_LARGE_DTYPE", "uint8")


@pytest.fixture(scope="module", autouse=True)
def large_tensor_mode():
    """The int64 tier runs under the USE_INT64_TENSOR_SIZE analogue
    (x64 indices); restored after the module so the rest of the suite
    keeps the default 32-bit index math."""
    from mxnet_tpu import util

    util.enable_large_tensor(True)
    assert mx.runtime.Features().is_enabled("INT64_TENSOR_SIZE")
    yield
    util.enable_large_tensor(False)


@pytest.fixture(scope="module")
def big():
    """One shared >2^31-element array: zeros with a sentinel planted
    past the 2^31 boundary."""
    x = nd.zeros((LARGE,), dtype=_DTYPE)
    x[2**31 + 7] = 3
    x.wait_to_read()
    return x


def test_creation_shape_size(big):
    assert big.shape == (LARGE,)
    assert big.size == LARGE
    assert big.size > np.iinfo(np.int32).max


def test_int64_scalar_index_read(big):
    # reads on both sides of the 2^31 boundary
    assert int(big[2**31 + 7].asscalar()) == 3
    assert int(big[2**31 + 6].asscalar()) == 0
    assert int(big[-1].asscalar()) == 0


def test_slice_across_boundary(big):
    s = big[2**31 - 4:2**31 + 12]
    out = s.asnumpy()
    assert out.shape == (16,)
    assert out[11] == 3  # sentinel at offset (2^31+7) - (2^31-4)
    assert out.sum() == 3


def test_reshape_keeps_elements(big):
    # LARGE = 16 * (2^27 + 1)
    r = big.reshape((16, 2**27 + 1))
    assert r.shape == (16, 2**27 + 1)
    # sentinel lands at flat index 2^31+7 = 16*(2^27+1) row-major:
    row, col = divmod(2**31 + 7, 2**27 + 1)
    assert int(r[row, col].asscalar()) == 3


def test_take_large_indices(big):
    idx = nd.array(np.array([0, 2**31 + 7, LARGE - 1], np.int64),
                   dtype="int64")
    out = nd.take(big, idx).asnumpy()
    assert list(out.astype(np.int64)) == [0, 3, 0]


def test_reduce_sum_int64(big):
    # accumulate in int64: a 32-bit accumulator cannot even hold the
    # element count, so any index/accumulator truncation shows up here
    total = nd.sum(big.astype("int64"))
    assert int(total.asscalar()) == 3


def test_argmax_past_boundary(big):
    pos = nd.argmax(big, axis=0)
    assert int(pos.asscalar()) == 2**31 + 7


def test_elementwise_and_copy(big):
    y = big + 1
    assert int(y[2**31 + 7].asscalar()) == 4
    assert int(y[0].asscalar()) == 1
    del y


def test_mean_large_float():
    # float path: mean over >2^31 elements must normalize by the true
    # int64 count (a f32 cast of the count would still pass; a i32
    # truncation would not)
    x = nd.ones((LARGE,), dtype=_DTYPE)
    m = nd.mean(x.astype("float64"))
    assert abs(float(m.asscalar()) - 1.0) < 1e-9
