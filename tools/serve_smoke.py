"""`make serve-smoke`: serving-tier CI gate.

Starts a ModelServer on a tiny model, pushes 100 mixed-length requests
through a deliberately small queue (so backpressure actually fires),
drains, and asserts the stats invariants from docs/serving.md:

    submitted == attempts - rejected_overload
    served + expired + failed + cancelled == submitted
    queue_depth == in_flight == 0            (after drain)
    graph.post_warmup_compiles == 0          (closed compile surface)

Exit code 0 = every invariant holds. Runs on the CPU backend so it is
chip-independent.
"""
import json
import sys

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import serve
    from mxnet_tpu.gluon import nn

    feat, attempts = 8, 100
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, flatten=False, in_units=feat, activation="relu"),
            nn.Dense(4, flatten=False, in_units=16))
    net.initialize(mx.init.Xavier())

    lengths = (4, 8, 16)
    spec = serve.BucketSpec(batch_sizes=(1, 2, 4),
                            example_shape=(None, feat), lengths=lengths)
    srv = serve.ModelServer(net, spec, max_queue=64, linger_ms=1.0)
    srv.start()

    rng = np.random.RandomState(0)
    futs, rejected = [], 0
    for _ in range(attempts):
        x = rng.rand(int(rng.choice(lengths)), feat).astype(np.float32)
        try:
            futs.append(srv.submit(x))
        except serve.ServerOverloadedError:
            rejected += 1
    for f in futs:
        f.result(timeout=300)
    srv.drain()
    s = srv.stats()
    print(json.dumps(s, default=str))

    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    check("submitted == attempts - rejected",
          s["submitted"] == attempts - rejected)
    check("rejected counter matches caller-side rejects",
          s["rejected_overload"] == rejected)
    check("served accounts for every admitted request",
          s["served"] + s["expired_deadline"] + s["failed"]
          + s["cancelled"] == s["submitted"])
    check("drain left zero queued work", s["queue_depth"] == 0)
    check("drain left zero in-flight work", s["in_flight"] == 0)
    check("zero post-warmup compiles",
          s["graph"]["post_warmup_compiles"] == 0)
    check("warmup covered the whole bucket grid",
          s["warmup_batches"] == len(spec.bucket_shapes()))
    check("every batch landed in a known bucket",
          set(s["bucket_hits"]) <= {spec.key(b, l)
                                    for b in spec.batch_sizes
                                    for l in spec.lengths})
    check("latency percentiles recorded",
          s["latency"]["count"] == s["served"]
          and s["latency"]["p99_ms"] is not None)

    if failures:
        print("serve-smoke FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"serve-smoke OK: {s['served']} served, {rejected} rejected "
          f"by backpressure, fill={s['batch_fill_ratio']}, "
          f"p99={s['latency']['p99_ms']}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
