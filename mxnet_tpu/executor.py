"""Executor namespace (ref: python/mxnet/executor.py).

The reference keeps `Executor` in its own module; here the class lives
with the symbolic graph (`symbol/symbol.py`) since bind-time planning
is XLA's job, but `mx.executor.Executor` remains importable for ported
scripts.
"""
from .symbol.symbol import Executor  # noqa: F401

__all__ = ["Executor"]
