"""Nightly checkpoint stress (slow-marked; deselected from tier-1).

Repeated save/restore churn under both engine modes, plus a real
kill -9 mid-training-loop with resume from latest() — the end-to-end
version of the fault-tolerance contract.
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.gluon import nn

pytestmark = pytest.mark.slow


@pytest.mark.parametrize("mode", ["ThreadedEngine", "NaiveEngine"])
def test_checkpoint_stress_repeated_save_restore(tmp_path, mode):
    """20 rounds of train/save/restore churn: every restore is
    bit-identical and retention holds the directory at keep_n."""
    prev = mx.engine.engine_type()
    mx.engine.set_engine_type(mode)
    try:
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.01})
        x = nd.array(np.random.RandomState(0).rand(8, 8)
                     .astype(np.float32))
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
        for step in range(1, 21):
            with autograd.record():
                loss = net(x).sum()
            loss.backward()
            trainer.step(1)
            mgr.save(step, params=net, trainer=trainer)
            if step % 5 == 0:
                mgr.wait_until_finished()
                w = {k: v.data().asnumpy().copy()
                     for k, v in
                     net._collect_params_with_prefix().items()}
                net2 = nn.HybridSequential()
                net2.add(nn.Dense(16, activation="relu"), nn.Dense(4))
                net2.initialize()
                net2(x)  # materialize deferred shapes
                trainer2 = gluon.Trainer(net2.collect_params(), "adam",
                                         {"learning_rate": 0.01})
                meta = mgr.restore(params=net2, trainer=trainer2)
                assert meta["step"] == step
                for k, v in net2._collect_params_with_prefix().items():
                    np.testing.assert_array_equal(v.data().asnumpy(),
                                                  w[k])
                assert trainer2._optimizer.num_update == step
        mgr.wait_until_finished()
        assert len(mgr.steps()) == 3
    finally:
        mx.engine.set_engine_type(prev)


_CHILD = r"""
import sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.gluon import nn

ckpt_dir = sys.argv[1]
mx.random.seed(3)
net = nn.Dense(8, in_units=8)
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9})
x = nd.array(np.random.RandomState(1).rand(4, 8).astype(np.float32))
mgr = checkpoint.CheckpointManager(ckpt_dir, keep_n=3)
step = 0
print("READY", flush=True)
while True:  # train until killed
    step += 1
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    mgr.save(step, params=net, trainer=trainer)
"""


def test_kill9_mid_run_then_resume(tmp_path):
    """SIGKILL a training loop that checkpoints every step; the parent
    resumes from latest() — which is always a complete snapshot."""
    ckpt_dir = str(tmp_path / "ckpts")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", _CHILD, ckpt_dir],
                            stdout=subprocess.PIPE, env=env,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    try:
        assert proc.stdout.readline().strip() == b"READY"
        deadline = time.time() + 60
        while checkpoint.latest(ckpt_dir) is None:
            assert time.time() < deadline, "child made no checkpoint"
            time.sleep(0.1)
        time.sleep(0.5)  # let a save be mid-flight with high odds
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    step = checkpoint.latest(ckpt_dir)
    assert step is not None
    mgr = checkpoint.CheckpointManager(ckpt_dir, keep_n=3)
    net = nn.Dense(8, in_units=8)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    meta = mgr.restore(params=net, trainer=trainer)
    assert meta["step"] == step
    assert trainer._optimizer.num_update == step
    assert np.all(np.isfinite(net.weight.data().asnumpy()))
    # resumed training keeps working
    x = nd.ones((4, 8))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    assert trainer._optimizer.num_update == step + 1
