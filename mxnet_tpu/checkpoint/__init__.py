"""mxnet_tpu.checkpoint — atomic, async, resumable training checkpoints.

See docs/checkpointing.md for the save/resume workflow, the sharded
multi-process layout, retention, and the SIGTERM preemption hook.
"""
from .atomic import atomic_file, fsync_dir, fsync_file, write_json  # noqa: F401
from .manager import MANIFEST, CheckpointManager, latest  # noqa: F401
from .reshard import (merge_pipeline_states,  # noqa: F401
                      reshard_zero_snapshot, source_rank)
