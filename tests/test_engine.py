"""Native dependency engine tests.

Ref test strategy: tests/cpp/engine/threaded_engine_test.cc — random
dependency DAGs executed on naive vs threaded engines must produce
identical results (the engine's race-freedom test), plus WaitForVar /
WaitForAll semantics from tests/python/unittest/test_engine.py.
"""
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import engine
from mxnet_tpu.utils import native_engine

pytestmark = pytest.mark.skipif(
    native_engine.load() is None, reason="native engine not built")


def test_cpp_selftest_random_dags():
    for seed in range(20):
        assert native_engine.self_test(seed, n_vars=12, n_ops=3000,
                                       num_workers=8) == 0, seed


def test_push_returns_future_result():
    eng = native_engine.NativeEngine(num_workers=2)
    fut = eng.push(lambda: 40 + 2)
    assert fut.result(timeout=10) == 42
    eng.close()


def test_exception_propagates_via_future():
    eng = native_engine.NativeEngine(num_workers=2)
    def boom():
        raise ValueError("boom")
    fut = eng.push(boom)
    with pytest.raises(ValueError, match="boom"):
        fut.result(timeout=10)
    eng.close()


def test_write_write_ordering():
    """WAW: writes to the same var run in push order."""
    eng = native_engine.NativeEngine(num_workers=8)
    v = eng.new_variable()
    out = []
    for i in range(200):
        def op(i=i):
            out.append(i)
        eng.push(op, mutable_vars=[v])
    eng.wait_for_var(v)
    assert out == list(range(200))
    eng.close()


def test_concurrent_readers_exclusive_writer():
    """RAR runs concurrently; a writer excludes all readers."""
    eng = native_engine.NativeEngine(num_workers=8)
    v = eng.new_variable()
    active = [0]
    peak = [0]
    lock = threading.Lock()
    writer_saw = []

    def reader():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.01)
        with lock:
            active[0] -= 1

    def writer():
        with lock:
            writer_saw.append(active[0])

    for _ in range(8):
        eng.push(reader, const_vars=[v])
    eng.push(writer, mutable_vars=[v])
    for _ in range(8):
        eng.push(reader, const_vars=[v])
    eng.wait_all()
    assert peak[0] > 1, "readers never overlapped"
    assert writer_saw == [0], "writer ran while readers active"
    eng.close()


def test_python_fuzz_threaded_matches_naive():
    """Random DAG over python cells: threaded result == sequential."""
    rng = np.random.RandomState(7)
    n_vars, n_ops = 10, 500
    steps = []
    for i in range(n_ops):
        w = int(rng.randint(n_vars))
        reads = sorted({int(r) for r in rng.randint(n_vars, size=3)} - {w})
        steps.append((reads, w))

    def run(threaded):
        cells = list(range(1, n_vars + 1))
        if threaded:
            eng = native_engine.NativeEngine(num_workers=8)
            vids = [eng.new_variable() for _ in range(n_vars)]
            for i, (reads, w) in enumerate(steps):
                def op(reads=reads, w=w, salt=i + 1):
                    acc = salt
                    for r in reads:
                        acc = acc * 1000003 + cells[r]
                    cells[w] = cells[w] * 31 + acc
                eng.push(op, const_vars=[vids[r] for r in reads],
                         mutable_vars=[vids[w]])
            eng.wait_all()
            eng.close()
        else:
            for i, (reads, w) in enumerate(steps):
                acc = i + 1
                for r in reads:
                    acc = acc * 1000003 + cells[r]
                cells[w] = cells[w] * 31 + acc
        return cells

    assert run(True) == run(False)


def test_wait_for_var_blocks_until_writes_done():
    eng = native_engine.NativeEngine(num_workers=4)
    v = eng.new_variable()
    done = []
    def slow():
        time.sleep(0.05)
        done.append(1)
    eng.push(slow, mutable_vars=[v])
    eng.wait_for_var(v)
    assert done == [1]
    eng.close()


def test_delete_variable_runs_after_pending_ops():
    eng = native_engine.NativeEngine(num_workers=4)
    v = eng.new_variable()
    out = []
    eng.push(lambda: out.append(1), mutable_vars=[v])
    eng.delete_variable(v)
    eng.wait_all()
    assert out == [1]
    eng.close()


def test_overlapping_const_and_mutable_vars_no_deadlock():
    """A var listed as both read and write must not self-deadlock: the
    engine normalizes it to mutable-only (ref: engine CHECKs disjoint)."""
    eng = native_engine.NativeEngine(num_workers=2)
    v = eng.new_variable()
    out = []
    fut = eng.push(lambda: out.append(1), const_vars=[v, v],
                   mutable_vars=[v, v])
    fut.result(timeout=10)
    assert out == [1]
    eng.wait_all()
    eng.close()


def test_engine_module_push_with_deps():
    if engine.native_engine() is None:
        pytest.skip("native engine unavailable")
    v = engine.new_variable()
    order = []
    f1 = engine.push(lambda: order.append("a"), mutable_vars=[v])
    f2 = engine.push(lambda: order.append("b"), mutable_vars=[v])
    f1.result(timeout=10), f2.result(timeout=10)
    assert order == ["a", "b"]


def test_stream_fifo_within_lane():
    """Ops on one stream run in push order (ref: stream_manager.h —
    per-stream FIFO), regardless of which backend realizes the lane."""
    s = engine.Stream("test-fifo")
    order = []
    futs = [s.push(lambda i=i: order.append(i)) for i in range(20)]
    for f in futs:
        f.result(timeout=10)
    assert order == list(range(20))


def test_streams_overlap_across_lanes():
    """Two lanes must make independent progress: a blocked 'h2d' lane
    cannot stall the 'd2h' lane (the reference's compute-vs-copy stream
    separation)."""
    import threading

    if engine.is_naive():
        pytest.skip("NaiveEngine runs pushes inline by design "
                    "(MXNET_ENGINE_TYPE=NaiveEngine semantics) — "
                    "a blocking task blocks the caller, so lane "
                    "overlap doesn't exist in this mode")
    gate = threading.Event()
    sm = engine.StreamManager()
    slow = sm.get("cpu(0)", "h2d")
    fast = sm.get("cpu(0)", "d2h")
    assert sm.get("cpu(0)", "h2d") is slow  # registry caches per key
    slow.push(gate.wait)                    # blocks its lane only
    out = fast.push(lambda: "ran").result(timeout=10)
    assert out == "ran"
    gate.set()
    slow.wait()


def test_stream_kind_validated():
    with pytest.raises(ValueError):
        engine.StreamManager().get("cpu(0)", "bogus")
