"""Async fault-tolerant checkpointing — the subsystem behind
``parallel/dist.py``'s restart advice ("restart the job from the last
checkpoint").

A checkpoint is a step-tagged directory::

    <dir>/ckpt-00000042/
        MANIFEST.json            format_version, step, epoch, files, extra
        params-shard0.params     utils.serialization container (per process)
        trainer-shard0.states    versioned Trainer states pickle
        rng-shard0.json          mx.random.get_state() snapshot

Commit protocol: every process writes its shard files into the shared
``ckpt-<step>.tmp`` directory and fsyncs them; after a ``parallel/dist``
barrier, process 0 writes the fsync'd manifest and renames the temp dir
onto the final name (the atomic commit point), then fsyncs the parent.
``latest()`` requires both the final name AND the manifest, so an
interrupted save — killed at ANY point — is never resumable state; its
``*.tmp`` leftovers are garbage-collected by the next successful commit.

Saves are asynchronous: ``save()`` snapshots device-buffer *references*
synchronously (XLA arrays are immutable — a later optimizer step rebinds
``NDArray._data``, it never overwrites the snapshot), pushes the
device→host readback onto the engine's ``d2h`` stream and the
serialization + commit onto ``host_pool()``, so training continues while
the previous checkpoint drains.  Errors surface at the
``wait_until_finished()`` barrier, which also runs before the next save.
"""
from __future__ import annotations

import concurrent.futures
import os
import pickle
import re
import shutil
import signal
import threading

from .. import engine, profiler
from .. import random as _random
from ..base import MXNetError
from . import atomic, reshard as _reshard

MANIFEST = "MANIFEST.json"


def _rank():
    from ..parallel import dist

    try:
        return dist.rank()
    except Exception:  # jax backend not initialized yet: single process
        return 0


def _barrier(name):
    from ..parallel import dist

    try:
        multi = dist.is_multiprocess()
    except Exception:
        multi = False
    if multi:
        dist.barrier(name)


def _num_processes():
    from ..parallel import dist

    try:
        return dist.num_workers()
    except Exception:
        return 1


def _get_logger():
    from ..log import get_logger

    return get_logger("mxnet_tpu.checkpoint")


def _is_corrupt_failure(e):
    """Does this restore failure mean the checkpoint PAYLOAD is damaged
    (fall back to an older step), as opposed to a caller error like a
    shape/topology mismatch (raise)?  Raw deserialization errors —
    pickle/EOF/json — are damage by definition; MXNetErrors count only
    when they carry the serialization tier's corrupt/truncated wording.
    OSError deliberately does NOT count: a transient I/O blip (NFS/
    object-store hiccup, EACCES misconfig) on an intact newest step
    must surface retriably, not silently forfeit its progress to an
    older step."""
    if isinstance(e, MXNetError):
        text = str(e).lower()
        return "corrupt" in text or "truncated" in text
    return isinstance(e, (pickle.UnpicklingError, EOFError, ValueError))


def _is_fallback_skippable(e):
    """During the auto-resume fallback scan, a step is also skippable
    when it simply lacks a component the caller asked for (saved
    without params=/trainer=/pipeline=) — a per-step property, not a
    caller error, so an older complete step may still satisfy the
    restore."""
    return _is_corrupt_failure(e) or (
        isinstance(e, MXNetError) and "saved without" in str(e))


def _first_line(e):
    """First line of an exception message, safe for empty messages
    (a bare OSError()/EOFError() strs to '')."""
    lines = str(e).splitlines()
    return lines[0][:200] if lines else type(e).__name__


def _resilience_fallback_restore():
    """Book a successful corrupt-latest fallback into the resilience
    telemetry (profiler 'resilience' section) when that tier is
    available; never a hard dependency."""
    try:
        from ..resilience import stats as _rstats

        _rstats.add("fallback_restores")
    except Exception:  # pragma: no cover - resilience tier absent
        pass


# -- snapshot trees ---------------------------------------------------------
# Two phases so the expensive part never runs on the training thread:
# _capture (sync, cheap) swaps NDArray leaves for their underlying
# device buffers; _fetch (on the d2h stream) turns device buffers into
# host numpy arrays.


def _capture(obj):
    from ..ndarray.ndarray import NDArray

    if isinstance(obj, NDArray):
        return obj._data
    if isinstance(obj, dict):
        return {k: _capture(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_capture(v) for v in obj)
    return obj


def _fetch(obj):
    import jax
    import numpy as np

    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _fetch(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_fetch(v) for v in obj)
    return obj


def _param_dict(params):
    """Normalize a params target into name -> NDArray/Parameter/array."""
    if params is None:
        return None
    if hasattr(params, "_collect_params_with_prefix"):  # gluon Block
        return {k: v.data()
                for k, v in params._collect_params_with_prefix().items()
                if v._data is not None}
    if isinstance(params, dict):
        out = {}
        for k, v in params.items():
            out[k] = v.data() if hasattr(v, "_finish_deferred_init") else v
        return out
    raise MXNetError(
        f"cannot checkpoint params of type {type(params).__name__}: "
        "expected a gluon Block or a name->NDArray dict")


class CheckpointManager:
    """Atomic, async, resumable checkpoints (see module docstring).

    Usage::

        mgr = checkpoint.CheckpointManager("/ckpts", keep_n=3)
        meta = mgr.restore(params=net, trainer=trainer) \
            if mgr.latest() is not None else None   # auto-resume
        for step in range(start, n_steps):
            ...train...
            if step % 100 == 0:
                mgr.save(step, params=net, trainer=trainer)
        mgr.wait_until_finished()
    """

    FORMAT_VERSION = 1

    def __init__(self, directory, keep_n=5, prefix="ckpt", ctx=None):
        self.directory = os.path.abspath(os.fspath(directory))
        self.keep_n = int(keep_n) if keep_n else 0
        self.prefix = prefix
        self._step_re = re.compile(rf"^{re.escape(prefix)}-(\d+)$")
        self._tmp_re = re.compile(rf"^{re.escape(prefix)}-(\d+)\.tmp$")
        os.makedirs(self.directory, exist_ok=True)
        if _rank() == 0:  # peers share the dir: exactly one healer
            self._recover()
        # peers must not scan (latest/restore) until the heal is done,
        # else a kill inside a re-save's two-rename window lets rank 0
        # resume the healed step N while others resume N-1 — silent
        # cross-rank divergence
        _barrier("checkpoint-init")
        self._stream = engine.d2h_stream(ctx)
        self._pending = None  # (step, future) of the in-flight save
        self._hook_signum = None
        self._prev_handler = None
        self._state_fn = None

    # -- discovery ----------------------------------------------------------

    def steps(self):
        """Committed checkpoint steps, ascending.  A directory without a
        manifest (interrupted between mkdir and commit on a filesystem
        with non-atomic dir rename) is NOT committed."""
        out = []
        for name in os.listdir(self.directory):
            m = self._step_re.match(name)
            if m and os.path.isfile(
                    os.path.join(self.directory, name, MANIFEST)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self):
        """Newest committed step, or None when no checkpoint exists."""
        s = self.steps()
        return s[-1] if s else None

    def _dir_for(self, step):
        return os.path.join(self.directory, f"{self.prefix}-{step:08d}")

    # -- save ---------------------------------------------------------------

    def save(self, step, params=None, trainer=None, pipeline=None,
             epoch=None, extra=None, sync=False):
        """Checkpoint `step` asynchronously; returns the commit future.

        params : gluon Block or name->NDArray dict (optional)
        trainer : gluon.Trainer (optional) — optimizer states + counters
        pipeline : pipeline.Pipeline (optional) — every stage's iterator
            state (source position, shuffle ring + RNG, in-flight
            batches), captured synchronously at call time so the resumed
            job replays the exact remaining batch sequence
        extra : JSON-serializable user metadata stored in the manifest
        sync : block until committed (always implied under NaiveEngine)

        Blocks first on any still-draining previous save (the error
        surfacing point) — at most one checkpoint is in flight.
        """
        # A SIGTERM landing between the wait_until_finished below and
        # the _pending registration would re-enter save() from the
        # preemption handler and start a second commit racing the
        # half-scheduled one (shared tmp dir, .old juggling, _gc) —
        # defer delivery across the critical section and hand the
        # signal to the real handler once _pending is consistent.
        deferred = []
        prev_sig = None
        if (self._hook_signum is not None
                and threading.current_thread() is threading.main_thread()):
            prev_sig = signal.getsignal(self._hook_signum)
            signal.signal(self._hook_signum,
                          lambda s, f: deferred.append(s))
        try:
            self.wait_until_finished()
            step = int(step)
            # the captured device-buffer references must survive until
            # the d2h readback drains: hold off buffer DONATION (the
            # fused optimizer step would otherwise delete them on the
            # very next Trainer.step) from capture to fetch-complete
            engine.acquire_donation_hold()
            try:
                with profiler.op_scope("checkpoint.save.capture",
                                       cat="checkpoint"):
                    state = {
                        "params": _capture(_param_dict(params)),
                        "trainer": (None if trainer is None
                                    else _capture(trainer.states_dict())),
                        # pipeline state is host trees by construction
                        # (in-flight device batches drain to numpy), so
                        # the d2h readback passes it through untouched
                        "pipeline": (None if pipeline is None
                                     else pipeline.state_dict()),
                        "rng": _random.get_state(),
                    }
                meta = {"format_version": self.FORMAT_VERSION,
                        "step": step, "epoch": epoch, "extra": extra,
                        "num_processes": _num_processes()}
                fetch_fut = self._stream.push(self._readback, state)
            except BaseException:
                engine.release_donation_hold()
                raise
            fetch_fut.add_done_callback(
                lambda _f: engine.release_donation_hold())
            # chain the commit off the readback instead of parking a
            # host_pool worker on fetch_fut.result() for the whole d2h
            # drain (with CPU_WORKER_NTHREADS=1 that would stall the IO
            # prefetcher behind every checkpoint)
            fut = concurrent.futures.Future()

            def _commit_when_read(ff):
                def _run():
                    try:
                        fut.set_result(self._write_commit(ff, step, meta))
                    except BaseException as e:  # noqa: BLE001 via future
                        fut.set_exception(e)

                engine.push_host(_run)

            fetch_fut.add_done_callback(_commit_when_read)
            self._pending = (step, fut)
            # Multi-process: the commit path runs dist barriers (device
            # collectives) — issuing those from a background thread
            # while the main thread keeps enqueueing training
            # collectives can interleave differently across processes
            # and deadlock, so saves block until committed there; async
            # overlap is a single-process (per-host-checkpoint)
            # optimization for now.
            if sync or engine.is_naive() or _num_processes() > 1:
                self.wait_until_finished()
        finally:
            if prev_sig is not None:
                signal.signal(self._hook_signum, prev_sig)
                if deferred and callable(prev_sig):
                    prev_sig(deferred[0], None)
        return fut

    def wait_until_finished(self):
        """Barrier for the in-flight save; re-raises its error if the
        async readback/serialization/commit failed.

        ``_pending`` stays set until the result is in: a SIGTERM final
        save arriving while the main thread is parked here re-enters
        via the handler, still sees the in-flight save, and waits for
        it — instead of starting a concurrent commit whose _gc could
        delete the draining save's temp dir mid-write."""
        pending = self._pending
        if pending is None:
            return
        try:
            pending[1].result()
        finally:
            if self._pending is pending:
                self._pending = None

    def _readback(self, state):
        with profiler.op_scope("checkpoint.save.readback", cat="checkpoint"):
            engine.fault_point("engine.d2h")
            return _fetch(state)

    def _write_commit(self, fetch_fut, step, meta):
        with profiler.op_scope("checkpoint.save.commit", cat="checkpoint"):
            state = fetch_fut.result()
            rank = _rank()
            tmp = self._dir_for(step) + ".tmp"
            final = self._dir_for(step)
            # a crashed earlier save at this step may have left stale
            # shard files in tmp — committing them would smuggle a dead
            # run's state into the manifest, so rank 0 clears first and
            # a barrier orders the clear before any peer writes
            if rank == 0 and os.path.isdir(tmp):
                shutil.rmtree(tmp)
            _barrier("checkpoint-clear")
            os.makedirs(tmp, exist_ok=True)
            if state["params"] is not None:
                from ..utils import serialization

                p = os.path.join(tmp, f"params-shard{rank}.params")
                serialization.save_ndarrays(p, state["params"])
                atomic.fsync_file(p)
            if state["trainer"] is not None:
                p = os.path.join(tmp, f"trainer-shard{rank}.states")
                with open(p, "wb") as f:
                    pickle.dump(state["trainer"], f)
                atomic.fsync_file(p)
            if state["pipeline"] is not None:
                p = os.path.join(tmp, f"pipeline-shard{rank}.state")
                with open(p, "wb") as f:
                    pickle.dump(state["pipeline"], f)
                atomic.fsync_file(p)
            atomic.write_json(os.path.join(tmp, f"rng-shard{rank}.json"),
                              state["rng"])
            # chaos site: a 'truncate' fault here corrupts a shard AFTER
            # the writes but BEFORE the manifest/rename, committing a
            # checkpoint whose payload is damaged — the injected failure
            # the restore() corrupt-latest fallback is tested against
            engine.fault_point("checkpoint.commit", dir=tmp, step=step)
            atomic.fsync_dir(tmp)
            _barrier("checkpoint-save")
            if rank == 0:
                meta["files"] = sorted(os.listdir(tmp))
                atomic.write_json(os.path.join(tmp, MANIFEST), meta)
                old = None
                if os.path.isdir(final):
                    # re-save of the same step: never rmtree the
                    # committed copy before the new one lands — park it
                    # aside so a kill in this window loses nothing
                    # (_recover renames it back if the commit never
                    # happened)
                    old = final + ".old"
                    if os.path.isdir(old):
                        shutil.rmtree(old)
                    os.rename(final, old)
                os.rename(tmp, final)  # the commit point
                atomic.fsync_dir(self.directory)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
            _barrier("checkpoint-commit")
            if rank == 0:
                self._gc(step)
            return final

    def _recover(self):
        """Heal a kill inside a re-save's two-rename commit window: a
        parked ``*.old`` whose final name is gone is the still-committed
        copy — rename it back; one whose final exists is garbage."""
        for name in os.listdir(self.directory):
            if not (name.endswith(".old")
                    and self._step_re.match(name[:-len(".old")])):
                continue
            src = os.path.join(self.directory, name)
            base = src[:-len(".old")]
            try:
                if os.path.isdir(base):
                    shutil.rmtree(src, ignore_errors=True)
                else:
                    os.rename(src, base)
            except OSError:
                pass  # a concurrent healer won the rename: fine

    def _gc(self, current_step):
        """Retention: drop committed checkpoints beyond keep_n and temp
        leftovers of older interrupted saves."""
        if self.keep_n:
            for s in self.steps()[:-self.keep_n]:
                shutil.rmtree(self._dir_for(s), ignore_errors=True)
        for name in os.listdir(self.directory):
            m = self._tmp_re.match(name)
            if m and int(m.group(1)) < current_step:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- restore ------------------------------------------------------------

    def restore(self, step=None, params=None, trainer=None, pipeline=None,
                restore_rng=True, strict_topology=False):
        """Load checkpoint `step` (default: ``latest()``) in place.

        params/trainer/pipeline mirror ``save()`` targets; parameters
        load into the Block/dict, optimizer states + update counters
        into the Trainer, iterator state into a freshly built
        identically-composed Pipeline (which then replays the exact
        remaining batch sequence), and the global RNG is rewound so the
        resumed run draws the same stream the killed run would have.
        The RNG is restored BEFORE the pipeline so replay-skip sources
        that draw from it replay the saved epoch's permutation.
        Returns the manifest metadata ``{"step", "epoch", "extra",
        "params"}`` — "params" is the loaded name->NDArray dict only
        when no target was given.

        A checkpoint saved by a DIFFERENT world size (a 16-rank job
        preempted down to 8, or scaled up) is RESHARDED onto this
        job's topology: rank-replicated param/RNG shards remap, ZeRO-1
        optimizer flat shards gather and re-slice onto the new layout,
        and per-rank pipeline cursors merge under the rank-symmetric
        ``shard()`` contract (see :mod:`.reshard` /
        docs/checkpointing.md "Elastic restore").  Jobs that must NOT
        silently reshard — model-parallel layouts with genuinely
        rank-distinct parameters — pass ``strict_topology=True`` to
        restore the loud world-size rejection.

        With ``step=None`` a corrupt or truncated newest step does NOT
        raise: it is logged loudly and the previous retained step is
        restored instead (checkpoints exist to survive exactly this),
        falling back step by step; only when *no* retained step loads
        does restore raise, listing every step's failure.  An explicit
        ``step=`` keeps strict semantics (corruption raises).
        """
        self.wait_until_finished()
        if step is not None:
            return self._restore_step(int(step), params, trainer,
                                      pipeline, restore_rng,
                                      strict_topology)
        steps = self.steps()
        if not steps:
            raise MXNetError(
                f"no committed checkpoint under {self.directory}: nothing "
                "to resume (an interrupted save's *.tmp directory does "
                "not count)")
        failures = []
        for s in reversed(steps):
            try:
                meta = self._restore_step(s, params, trainer, pipeline,
                                          restore_rng, strict_topology)
            except Exception as e:  # noqa: BLE001 — filtered below
                if not _is_fallback_skippable(e):
                    if failures:
                        # a failed earlier attempt may already have
                        # applied some components (e.g. params landed,
                        # then the trainer blob raised): never let the
                        # caller mistake this for an untouched target
                        raise MXNetError(
                            f"restore failed at step {s} while falling "
                            f"back past corrupt step(s) "
                            f"{[f[0] for f in failures]}: "
                            f"{_first_line(e)} — the restore target may "
                            "be PARTIALLY mutated by the failed "
                            "attempt(s); restore an explicit step= or "
                            "rebuild the targets before retrying") from e
                    raise
                failures.append((s, e))
                _get_logger().error(
                    "checkpoint step %d under %s is corrupt, truncated "
                    "or incomplete (%s); falling back to the previous "
                    "retained step",
                    s, self.directory, _first_line(e))
                continue
            if failures:
                _get_logger().error(
                    "restored step %d after %d newer corrupt step(s): %s "
                    "— training resumes from older state; investigate "
                    "the storage layer",
                    s, len(failures), [f[0] for f in failures])
                _resilience_fallback_restore()
            return meta
        raise MXNetError(
            f"no retained checkpoint under {self.directory} is loadable "
            "— every step failed: "
            + "; ".join(f"step {s}: {_first_line(e)[:150]}"
                        for s, e in failures))

    def _restore_step(self, step, params, trainer, pipeline, restore_rng,
                      strict_topology=False):
        d = self._dir_for(int(step))
        mpath = os.path.join(d, MANIFEST)
        if not os.path.isfile(mpath):
            raise MXNetError(
                f"checkpoint step {step} under {self.directory} is "
                "missing or uncommitted")
        import json

        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except ValueError as e:
            raise MXNetError(
                f"{mpath}: corrupt checkpoint manifest ({e}); this "
                "should be impossible for a committed checkpoint — "
                "restore an earlier step") from None
        ver = manifest.get("format_version", 0)
        if ver > self.FORMAT_VERSION:
            raise MXNetError(
                f"{mpath}: checkpoint format v{ver} was written by a "
                f"newer mxnet_tpu (this build reads <= "
                f"v{self.FORMAT_VERSION}); upgrade to restore it")
        saved_procs = int(manifest.get("num_processes", 1))
        procs = _num_processes()
        resharding = saved_procs != procs
        if resharding and strict_topology:
            raise MXNetError(
                f"{mpath}: world-size mismatch — checkpoint was saved "
                f"by a {saved_procs}-process job but this job runs "
                f"{procs} process(es), and strict_topology=True "
                "forbids elastic resharding. Drop strict_topology to "
                "repartition the checkpoint onto this topology "
                "(rank-replicated param/RNG shards remap, ZeRO-1 "
                "optimizer shards gather and re-slice, per-rank "
                "pipeline cursors merge under the rank-symmetric "
                "shard() contract), or restore with the original "
                "world size. See docs/checkpointing.md, 'Elastic "
                "restore'.")
        rank = _rank()
        src = _reshard.source_rank(rank, saved_procs) if resharding \
            else rank
        if resharding:
            # chaos site: a 'raise' fault here makes the RESHARD itself
            # fail transiently — the elastic supervisor must retry the
            # resize, not die (the resize-is-retried regression test)
            engine.fault_point("checkpoint.reshard", kind="topology",
                              saved_world=saved_procs, world=procs)
            _get_logger().warning(
                "elastic restore: repartitioning checkpoint step %s "
                "saved at world %d onto world %d (rank %d reads saved "
                "shard %d; pass strict_topology=True to forbid this)",
                step, saved_procs, procs, rank, src)
        with profiler.op_scope("checkpoint.restore", cat="checkpoint"):
            loaded = self._restore_params(d, src, params)
            self._restore_trainer(d, src, trainer)
            if restore_rng:
                rpath = os.path.join(d, f"rng-shard{src}.json")
                if os.path.isfile(rpath):
                    with open(rpath) as f:
                        _random.set_state(json.load(f))
            self._restore_pipeline(
                d, src, pipeline,
                saved_world=saved_procs if resharding else None)
        return {"step": int(manifest["step"]),
                "epoch": manifest.get("epoch"),
                "extra": manifest.get("extra"),
                "params": loaded}

    def _restore_params(self, d, rank, params):
        from ..utils import serialization

        pfile = os.path.join(d, f"params-shard{rank}.params")
        if not os.path.isfile(pfile):
            if params is not None:
                raise MXNetError(
                    f"{d}: no parameter shard for process {rank} "
                    f"(params-shard{rank}.params) — this step was "
                    "saved without params=; pass step= an entry of "
                    "steps() that has them")
            return None
        if params is not None and hasattr(params,
                                          "_collect_params_with_prefix"):
            # Block target: restore through the same validated dict
            # path (Block.load_parameters would silently adopt
            # mismatched shapes and can stop half-applied)
            params = params._collect_params_with_prefix()
        loaded = serialization.load_ndarrays(pfile)
        if params is None:
            return loaded
        # dict target: validate EVERYTHING first, then apply — a caller
        # catching a mismatch error must never be left half-restored
        extra = set(loaded) - set(params)
        if extra:
            raise MXNetError(
                f"{pfile}: checkpoint has parameters with no "
                f"counterpart in the restore target: {sorted(extra)}")
        missing = set(params) - set(loaded)
        if missing:
            raise MXNetError(
                f"{pfile}: restore target has parameters missing from "
                f"the checkpoint: {sorted(missing)}")
        for name, arr in loaded.items():
            tgt = params[name]
            # Parameter.set_data would silently ADOPT a wrong shape
            # (it re-assigns .shape), so pre-check it too; deferred
            # dims (0, or a still-None shape) accept anything
            shape = getattr(tgt, "shape", None)
            if shape is not None and (
                    len(shape) != len(arr.shape)
                    or any(s and s != a
                           for s, a in zip(shape, arr.shape))):
                raise MXNetError(
                    f"{pfile}: shape mismatch for {name!r}: checkpoint "
                    f"{tuple(arr.shape)} vs target {tuple(shape)}")
        for name, arr in loaded.items():
            tgt = params[name]
            if hasattr(tgt, "set_data"):  # Parameter
                tgt.set_data(arr)
            else:  # NDArray
                tgt._data = arr._data
        return None

    def _restore_pipeline(self, d, rank, pipeline, saved_world=None):
        if pipeline is None:
            return
        if saved_world is not None:
            # elastic reshard: read EVERY saved rank's cursor state and
            # merge under the rank-symmetric shard() contract (the
            # merge is agreement verification — see reshard.py); the
            # merged state loads into this rank's rebuilt shard(M, r)
            # pipeline
            import time as _time

            t0 = _time.perf_counter()
            blobs = []
            for r in range(saved_world):
                pfile = os.path.join(d, f"pipeline-shard{r}.state")
                if not os.path.isfile(pfile):
                    raise MXNetError(
                        f"{d}: cannot reshard the input pipeline — "
                        f"saved rank {r}'s pipeline-shard{r}.state is "
                        f"missing (saved world {saved_world}); was "
                        "this step saved without pipeline= on every "
                        "rank?")
                with open(pfile, "rb") as f:
                    blobs.append(pickle.load(f))
            pipeline.load_state_dict(
                _reshard.merge_pipeline_states(blobs, where=d))
            _reshard._book_reshard_ms(_time.perf_counter() - t0)
            return
        pfile = os.path.join(d, f"pipeline-shard{rank}.state")
        if not os.path.isfile(pfile):
            raise MXNetError(
                f"{d}: checkpoint has no input-pipeline state for "
                f"process {rank} (was it saved without pipeline=?)")
        with open(pfile, "rb") as f:
            blob = pickle.load(f)
        pipeline.load_state_dict(blob)

    def _restore_trainer(self, d, rank, trainer):
        tfile = os.path.join(d, f"trainer-shard{rank}.states")
        if trainer is None:
            return
        if not os.path.isfile(tfile):
            raise MXNetError(
                f"{d}: checkpoint has no trainer states for process "
                f"{rank} (was it saved without trainer=?)")
        with open(tfile, "rb") as f:
            blob = pickle.load(f)
        self._merge_zero_shards(d, blob, own=f"trainer-shard{rank}.states")
        self._reshard_zero_for(trainer, blob, tfile)
        saved_mesh = blob.get("mesh_shape") if isinstance(blob, dict) \
            else None
        cur_mesh = getattr(trainer, "_mesh_shape", None)
        if saved_mesh or cur_mesh:
            # elastic mesh leg: spmd snapshots hold full (gathered)
            # arrays, so a MESH-SHAPE change needs no data motion —
            # validate/log it (model-axis changes are loud) and mark
            # the event for chaos plans; load_states_dict re-checks
            engine.fault_point(
                "checkpoint.reshard", kind="mesh",
                saved_mesh=saved_mesh or "", world=0)
        trainer.load_states_dict(blob, source=tfile)

    @staticmethod
    def _reshard_zero_for(trainer, blob, tfile):
        """Elastic ZeRO leg: when the snapshot's shard world differs
        from the target trainer's replica world AND the trainer runs
        sharded, re-slice the flat shards onto the new layout on host
        (``reshard.reshard_zero_snapshot`` — gather, re-pad to the new
        ``zero_padded_size``, re-slice) so ``load_states_dict`` adopts
        them directly instead of materializing full per-param states.
        An unsharded target keeps the gather-on-load path unchanged."""
        zero = blob.get("zero") if isinstance(blob, dict) else None
        if not zero or not getattr(trainer, "_zero_shard", False):
            return
        try:
            world = len(trainer._params[0].list_ctx())
        except Exception:  # no params / uninitialized: gather path
            return
        if world <= 1 or int(zero["world"]) == world:
            return
        import time as _time

        t0 = _time.perf_counter()
        engine.fault_point("checkpoint.reshard", kind="zero",
                          saved_world=int(zero["world"]), world=world)
        _get_logger().warning(
            "elastic restore: re-slicing ZeRO-1 optimizer shards from "
            "world %d onto world %d (%s)",
            int(zero["world"]), world, tfile)
        blob["zero"] = _reshard.reshard_zero_snapshot(zero, world)
        _reshard._book_reshard_ms(_time.perf_counter() - t0)

    @staticmethod
    def _merge_zero_shards(d, blob, own=None):
        """Gather-on-restore for ZeRO-1 optimizer state: a multi-process
        sharded save leaves each rank's 1/world state shards in its own
        ``trainer-shard<r>.states``; when this rank's blob does not
        cover the full shard world, pull the missing ranks' shards from
        their sibling files so ``Trainer.load_states_dict`` can gather
        them into canonical per-param states (a sharded run restarts
        unsharded and vice versa).  Single-process saves already carry
        every rank's shards and skip this scan."""
        zero = blob.get("zero") if isinstance(blob, dict) else None
        if not zero:
            return
        world = int(zero["world"])
        have = {int(r) for r in zero["shards"]}
        if have == set(range(world)):
            return
        rx = re.compile(r"^trainer-shard(\d+)\.states$")
        for name in sorted(os.listdir(d)):
            if have == set(range(world)):
                break  # every rank gathered: skip the remaining blobs
            m = rx.match(name)
            if m is None or name == own:
                continue
            with open(os.path.join(d, name), "rb") as f:
                peer = pickle.load(f)
            pz = peer.get("zero") if isinstance(peer, dict) else None
            if not pz:
                continue
            for r, chunks in pz["shards"].items():
                if int(r) not in have:
                    zero["shards"][r] = chunks
                    have.add(int(r))
        missing = set(range(world)) - have
        if missing:
            raise MXNetError(
                f"{d}: ZeRO-1 optimizer-state shards for rank(s) "
                f"{sorted(missing)} of {world} are missing — the "
                "sharded save did not complete on every rank; restore "
                "an earlier step")

    # -- preemption ---------------------------------------------------------

    def install_sigterm_hook(self, state_fn, signum=signal.SIGTERM):
        """Final synchronous save on SIGTERM (preemption notice).

        ``state_fn()`` returns the kwargs for ``save()`` — include
        everything a resume needs, typically ``{"step": n, "params":
        net, "trainer": trainer}`` (a params-less final save would
        become ``latest()`` yet not be resumable into a net) — or None
        to skip.  After the save the previous
        handler is chained (or the default disposition re-raised), so
        the process still terminates.  Main-process/main-thread only,
        like any Python signal handler.
        """

        if self._hook_signum is not None:
            # re-install = swap the state provider; never re-chain (the
            # handler would chain to ITSELF and recurse on delivery)
            if signum != self._hook_signum:
                self.uninstall_sigterm_hook()
            else:
                self._state_fn = state_fn
                return

        self._state_fn = state_fn

        def _handler(sig, frame):
            try:
                kwargs = self._state_fn()
                if kwargs is not None:
                    kwargs.setdefault("sync", True)
                    self.save(**kwargs)
            finally:
                # post-mortem timeline next to the final checkpoint
                # (no-op unless the flight-recorder ring is armed).
                # The WHOLE block is guarded: a failure here — e.g. the
                # signal landing mid-way through the telemetry
                # package's own first import — must never skip the
                # handler chaining below (swallowing a termination
                # request is the one unacceptable outcome)
                try:
                    from ..telemetry import flight as _flight

                    _flight.dump_if_enabled("sigterm",
                                            directory=self.directory)
                except Exception:  # noqa: BLE001 — advisory only
                    pass
                prev = self._prev_handler
                if callable(prev):
                    prev(sig, frame)
                elif prev is None or prev == signal.SIG_DFL:
                    # None = installed from C: we cannot chain to it,
                    # but swallowing a termination request is worse —
                    # re-raise the default disposition so the process
                    # still dies (the supervisor would otherwise
                    # escalate to SIGKILL mid-something-worse)
                    signal.signal(sig, signal.SIG_DFL)
                    os.kill(os.getpid(), sig)

        self._prev_handler = signal.signal(signum, _handler)
        self._hook_signum = signum

    def uninstall_sigterm_hook(self):
        if self._hook_signum is None:
            return
        signal.signal(self._hook_signum,
                      self._prev_handler if self._prev_handler is not None
                      else signal.SIG_DFL)
        self._hook_signum = None
        self._prev_handler = None
        self._state_fn = None


def latest(directory, prefix="ckpt"):
    """Newest committed step under `directory`, or None — a pure
    read-only scan (unlike constructing a CheckpointManager, which
    heals interrupted re-saves), safe for monitors polling a live
    training job's checkpoint dir."""
    if not os.path.isdir(directory):
        return None
    rx = re.compile(rf"^{re.escape(prefix)}-(\d+)$")
    steps = [int(m.group(1)) for name in os.listdir(directory)
             if (m := rx.match(name))
             and os.path.isfile(os.path.join(directory, name, MANIFEST))]
    return max(steps) if steps else None
