"""mxnet_tpu.serve.Router — the fault-tolerant replica pool.

Covers ISSUE 14's contract: least-loaded dispatch off live queue/
compute attribution; per-request deadline BUDGET propagation (a
replica sees the remaining ms, not the original); transient dispatch
failures classified through resilience.classify and retried on a
different replica; overload spills then sheds (never burns retry
budget hammering a full pool); health-based eviction with a warm
spare admitted only after its full AOT warmup (zero in-traffic
compiles on survivors — the chaos gate); per-tenant quota admission;
tail-latency hedging; and zero-downtime rolling reload (every request
served entirely by pre- or post-reload weights).
"""
import json
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, serve
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import RetryPolicy, faults
from mxnet_tpu.resilience.supervisor import classify
from mxnet_tpu.serve.batcher import (DeadlineExceededError,
                                     ServerOverloadedError)

FEAT = 6


def _make_net(seed=3, out_units=5):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False, in_units=FEAT, activation="relu"),
            nn.Dense(out_units, flatten=False, in_units=8))
    net.initialize(mx.init.Xavier())
    return net


def _spec(batches=(1, 2, 4), lengths=(4, 8)):
    return serve.BucketSpec(batch_sizes=batches,
                            example_shape=(None, FEAT), lengths=lengths)


def _factory(seed=3, checkpoint=None, **server_kw):
    server_kw.setdefault("max_queue", 64)
    server_kw.setdefault("linger_ms", 0.5)

    def factory(rid):
        return serve.ModelServer(_make_net(seed=seed), _spec(),
                                 checkpoint=checkpoint, **server_kw)
    return factory


def _requests(n, rng, lengths=(2, 3, 4, 7, 8)):
    return [rng.rand(int(rng.choice(lengths)), FEAT).astype(np.float32)
            for _ in range(n)]


def _router(n=3, seed=3, health_sec=0.0, **kw):
    return serve.Router(_factory(seed=seed), n, health_sec=health_sec,
                        **kw)


def _ref(net, x):
    """Single-request reference forward (no server in the loop)."""
    return net(mx.nd.array(x[None])).asnumpy()[0]


# ---------------------------------------------------------------------------
# routing basics


def test_pool_serves_and_spreads_load():
    """A 3-replica pool serves a mixed burst with results identical to
    the single-net reference, spreads dispatches across replicas, and
    accounts for every admitted request (requests_lost == 0)."""
    ref_net = _make_net(seed=3)
    router = _router(3)
    router.start()
    try:
        rng = np.random.RandomState(0)
        reqs = _requests(30, rng)
        futs = [router.submit(x) for x in reqs]
        outs = [f.result(timeout=120) for f in futs]
        for x, out in zip(reqs, outs):
            np.testing.assert_allclose(out, _ref(ref_net, x),
                                       rtol=2e-5, atol=2e-5)
        s = router.stats()
        assert s["submitted"] == s["served"] == 30
        assert s["requests_lost"] == 0
        assert s["healthy"] == s["pool_size"] == 3
        assert sum(r["dispatched"] for r in s["replicas"].values()) \
            == s["dispatched"] >= 30
        # least-loaded + tie-break rotation puts work on >1 replica
        assert sum(1 for r in s["replicas"].values()
                   if r["dispatched"] > 0) >= 2
        assert s["latency"]["count"] == 30
    finally:
        router.shutdown()
    for rep in router.replicas:
        assert rep.server.stats()["graph"]["post_warmup_compiles"] == 0


def test_least_loaded_pick_prefers_idle_replica():
    router = _router(3)
    router.start()
    try:
        a, b, c = router.replicas
        a.ewma_ms = b.ewma_ms = c.ewma_ms = 10.0
        b.server.pending = lambda: 5
        c.server.pending = lambda: 2
        a.server.pending = lambda: 0
        assert router._pick(frozenset()) is a
        assert router._pick({a.id}) is c
        a.ewma_ms = 1000.0   # idle but very slow loses to short queue
        assert router._pick(frozenset()) is c
    finally:
        router.shutdown(drain=False)


def test_deadline_budget_propagation_on_retry():
    """The replica sees the REMAINING deadline budget: after a failed
    first dispatch and a backoff, the retry replica's deadline_ms is
    measurably smaller than the caller's original figure."""
    seen = []
    router = _router(2, retry=RetryPolicy(max_retries=2, base_delay=0.15,
                                          max_delay=0.15))
    router.start()
    try:
        for rep in router.replicas:
            orig = rep.server.submit

            def spy(example, _orig=orig, deadline_ms=None, **kw):
                seen.append(deadline_ms)
                return _orig(example, deadline_ms=deadline_ms, **kw)
            rep.server.submit = spy
        plan = faults.FaultPlan([{"site": "serve.replica.submit",
                                  "action": "raise", "on_hit": 1}])
        x = np.zeros((4, FEAT), np.float32)
        with faults.armed(plan):
            out = router.submit(x, deadline_ms=2000).result(timeout=60)
        assert out is not None
        # the faulted first dispatch raises BEFORE reaching submit, so
        # the spy sees exactly the retry — carrying the caller's budget
        # MINUS the 150 ms backoff, not the original 2000
        assert len(seen) == 1
        assert seen[0] is not None
        assert 0 < seen[0] <= 2000 - 140
        s = router.stats()
        assert s["retries"] == 1 and s["served"] == 1
        assert s["requests_lost"] == 0
    finally:
        router.shutdown()


def test_transient_dispatch_failure_retries_on_other_replica():
    """An injected serve.replica.submit fault is classified transient
    and re-dispatched on a DIFFERENT replica; the fault plan's fired()
    record makes the whole scenario bit-replayable."""
    router = _router(2)
    router.start()
    try:
        plan = faults.FaultPlan([{"site": "serve.replica.submit",
                                  "action": "raise", "on_hit": 1}],
                                seed=5)
        x = np.zeros((4, FEAT), np.float32)
        with faults.armed(plan):
            out = router.submit(x).result(timeout=60)
        assert out.shape == (4, 5)
        fired = plan.fired()
        assert [f["site"] for f in fired] == ["serve.replica.submit"]
        failed_replica = fired[0]["ctx"]["replica"]
        s = router.stats()
        assert s["retries"] == 1
        served_on = [i for i, r in s["replicas"].items()
                     if r["served"] > 0]
        assert served_on and failed_replica not in served_on
        assert s["requests_lost"] == 0
    finally:
        router.shutdown()


def test_retry_budget_exhaustion_fails_classified():
    """A replica failing persistently exhausts the seeded RetryPolicy;
    the caller gets a classified error naming the attempts, never a
    hang or a silent loss."""
    router = _router(2, retry=RetryPolicy(max_retries=1, base_delay=0.0))
    router.start()
    try:
        plan = faults.FaultPlan([{"site": "serve.replica.submit",
                                  "action": "raise", "times": None}])
        x = np.zeros((4, FEAT), np.float32)
        with faults.armed(plan):
            fut = router.submit(x)
            with pytest.raises(mx.MXNetError, match="retry budget"):
                fut.result(timeout=60)
        s = router.stats()
        assert s["failed"] == 1 and s["requests_lost"] == 0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# overload + deadline classification (ISSUE 14 satellite)


def test_classify_overload_and_deadline_are_not_transient():
    """ServerOverloadedError / DeadlineExceededError get their own
    NON-retryable classes — their 'try again'-shaped messages must not
    read as transient, or a retry loop hammers an overloaded pool."""
    assert classify(ServerOverloadedError(
        "request queue full (64); retry with backoff")) == "overloaded"
    assert classify(serve.TenantQuotaExceededError(
        "tenant quota exceeded")) == "overloaded"
    assert classify(DeadlineExceededError(
        "deadline passed while queued")) == "deadline"
    # message-shape fallback for foreign (e.g. RPC) errors
    assert classify(mx.MXNetError(
        "rpc error DEADLINE_EXCEEDED: deadline exceeded")) == "deadline"
    assert classify(mx.MXNetError(
        "backend queue full, try again")) == "overloaded"
    # genuinely transient shapes still retry
    assert classify(mx.MXNetError(
        "collective UNAVAILABLE: try again")) == "transient"


def test_overload_spills_then_sheds_without_retries():
    """Every replica full -> the router spills across the pool once,
    then rejects with a classified overload error; the retry budget is
    untouched (shed load, don't hammer)."""
    router = _router(2)
    router.start()
    try:
        for rep in router.replicas:
            def full(example, deadline_ms=None, **kw):
                raise ServerOverloadedError("request queue full (0)")
            rep.server.submit = full
        fut = router.submit(np.zeros((4, FEAT), np.float32))
        with pytest.raises(serve.NoHealthyReplicaError) as ei:
            fut.result(timeout=30)
        assert classify(ei.value) == "overloaded"
        s = router.stats()
        assert s["rejected_overload"] == 1
        assert s["retries"] == 0        # overload burned NO retries
        assert s["requests_lost"] == 0
    finally:
        router.shutdown(drain=False)


def test_supervisor_paces_overloaded_restarts():
    """A TRAINING job seeing overloaded/deadline-shaped failures must
    restart with backoff, not back-to-back — instant restarts would
    hammer the overloaded resource and burn the whole max_restarts
    budget inside one blip."""
    from mxnet_tpu.resilience import Supervisor

    calls = []

    def train(ctx):
        calls.append(time.monotonic())
        if len(calls) < 3:
            raise mx.MXNetError("collective DEADLINE_EXCEEDED: "
                                "deadline exceeded")
        return "done"

    sup = Supervisor(max_restarts=3, retry=RetryPolicy(
        max_retries=5, base_delay=0.1, max_delay=0.1))
    assert sup.run(train) == "done"
    assert len(calls) == 3
    # each re-invocation waited ~base_delay: paced, not instant
    assert calls[1] - calls[0] >= 0.09
    assert calls[2] - calls[1] >= 0.09


def test_ctor_rejects_unfillable_pool():
    srv = serve.ModelServer(_make_net(), _spec())
    with pytest.raises(mx.MXNetError, match="no factory"):
        serve.Router(servers=[srv], n_replicas=3)


def test_expired_budget_fails_without_dispatch():
    router = _router(2)
    router.start()
    try:
        fut = router.submit(np.zeros((4, FEAT), np.float32),
                            deadline_ms=-1.0)   # already exhausted
        with pytest.raises(DeadlineExceededError, match="budget"):
            fut.result(timeout=30)
        s = router.stats()
        assert s["expired_deadline"] == 1 and s["dispatched"] == 0
        assert s["requests_lost"] == 0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# tenant quota + hedging


def test_tenant_quota_admission_control():
    router = _router(2, tenant_quota=2)
    router.start()
    try:
        for rep in router.replicas:
            rep.server.submit = \
                lambda example, deadline_ms=None, **kw: Future()
        x = np.zeros((4, FEAT), np.float32)
        f1 = router.submit(x, tenant="a")
        f2 = router.submit(x, tenant="a")
        with pytest.raises(serve.TenantQuotaExceededError) as ei:
            router.submit(x, tenant="a")
        assert classify(ei.value) == "overloaded"
        f3 = router.submit(x, tenant="b")   # other tenants unaffected
        f4 = router.submit(x)               # untenanted: no quota
        f1.cancel()                          # resolution frees the slot
        f5 = router.submit(x, tenant="a")
        s = router.stats()
        assert s["rejected_quota"] == 1
        assert s["submitted"] == 5
    finally:
        router.shutdown(drain=False)
    for f in (f2, f3, f4, f5):
        assert f.done()
    assert router.stats()["requests_lost"] == 0


def test_hedge_near_deadline():
    """A request dispatched with less budget than hedge_ms runs on two
    replicas; the first result wins, exactly one is delivered."""
    ref_net = _make_net(seed=3)
    router = _router(2, hedge_ms=60_000)
    router.start()
    try:
        x = np.random.RandomState(1).rand(4, FEAT).astype(np.float32)
        out = router.submit(x, deadline_ms=30_000).result(timeout=60)
        np.testing.assert_allclose(out, _ref(ref_net, x),
                                   rtol=2e-5, atol=2e-5)
        s = router.stats()
        assert s["hedges"] == 1 and s["dispatched"] == 2
        assert s["served"] == 1
        assert s["requests_lost"] == 0
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# the chaos gate: eviction + warm replacement under a seeded fault plan


def test_chaos_replica_death_evicts_heals_and_loses_nothing():
    """ISSUE 14 acceptance: a seeded plan kills 1 of 3 replicas
    mid-burst (every dispatch to it fails) and stalls a health probe.
    Zero admitted requests are lost (each resolves via re-dispatch),
    the sick replica is evicted and its warm replacement rejoins after
    a full AOT warmup, and survivors serve the whole episode with zero
    in-traffic compiles."""
    ref_net = _make_net(seed=3)
    router = _router(3, health_sec=0.25, evict_after=3,
                     retry=RetryPolicy(max_retries=3, base_delay=0.01,
                                       max_delay=0.05))
    router.start()
    try:
        survivor_ids = {r.id for r in router.replicas if r.id != 1}
        plan = faults.FaultPlan([
            {"site": "serve.replica.submit", "action": "raise",
             "match": {"replica": 1}, "times": None},
            {"site": "serve.replica.health", "action": "stall",
             "on_hit": 2, "delay_s": 0.02, "times": 1},
        ], seed=7)
        rng = np.random.RandomState(0)
        reqs = _requests(40, rng)
        with faults.armed(plan):
            futs = [router.submit(x, deadline_ms=30_000) for x in reqs]
            outs = [f.result(timeout=120) for f in futs]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                s = router.stats()
                if s["healthy"] == 3 and s["replacements"] >= 1:
                    break
                time.sleep(0.02)
        for x, out in zip(reqs, outs):
            np.testing.assert_allclose(out, _ref(ref_net, x),
                                       rtol=2e-5, atol=2e-5)
        s = router.stats()
        assert s["served"] == 40
        assert s["requests_lost"] == 0
        assert s["evictions"] == 1 and s["replacements"] == 1
        assert s["healthy"] == s["pool_size"] == 3
        assert s["retries"] >= 1
        assert s["last_recovery_ms"] is not None
        assert 1 not in {r.id for r in router.replicas}
        # the replay record is deterministic and names the dead replica
        assert all(f["ctx"].get("replica") in (1, 0, 2)
                   for f in plan.fired())
        assert any(f["site"] == "serve.replica.submit"
                   and f["ctx"]["replica"] == 1 for f in plan.fired())
        # zero in-traffic compiles on survivors AND on the warm spare
        for rep in router.replicas:
            assert rep.server.stats()["graph"][
                "post_warmup_compiles"] == 0, rep.id
            assert rep.id in survivor_ids or rep.id >= 3
        router.drain(timeout=60)
    finally:
        router.shutdown(drain=False)


def test_probe_failures_alone_evict_a_wedged_replica():
    """Health probing catches a replica that accepts requests but
    never answers them (a wedged batcher): consecutive probe failures
    trip the circuit breaker without any caller traffic."""
    router = _router(2, health_sec=0.15, evict_after=2)
    router.start()
    try:
        victim = router.replicas[0]
        victim.server.submit = \
            lambda example, deadline_ms=None, **kw: Future()  # wedged
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            s = router.stats()
            if s["evictions"] >= 1 and s["healthy"] >= 2:
                break
            time.sleep(0.02)
        s = router.stats()
        assert s["probe_failures"] >= 2
        assert s["evictions"] == 1 and s["replacements"] == 1
        assert s["healthy"] == 2
        # the pool still serves
        out = router.submit(
            np.zeros((4, FEAT), np.float32)).result(timeout=60)
        assert out.shape == (4, 5)
    finally:
        router.shutdown(drain=False)


# ---------------------------------------------------------------------------
# rolling reload (ISSUE 14 satellite: under load, old XOR new weights)


def test_rolling_reload_under_load_serves_old_xor_new(tmp_path):
    """A mid-burst rolling_reload() across a 3-replica pool serves
    EVERY admitted request — each with pre-reload weights or
    post-reload weights, never a mix within one request — at zero
    post-warmup compiles and zero drops."""
    trained = _make_net(seed=11)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(7, params=trained, sync=True)
    mgr.wait_until_finished()

    serving_ref = _make_net(seed=99)
    router = serve.Router(_factory(seed=99, checkpoint=mgr), 3,
                          health_sec=0.0)
    router.start()
    try:
        rng = np.random.RandomState(2)
        reqs = _requests(60, rng)
        futs = [None] * len(reqs)

        def submitter():
            for i, x in enumerate(reqs):
                futs[i] = router.submit(x, deadline_ms=60_000)
                time.sleep(0.002)

        th = threading.Thread(target=submitter)
        th.start()
        time.sleep(0.04)                       # mid-burst
        metas = router.rolling_reload(timeout=60)
        th.join()
        # a few guaranteed-post-rollout requests (rolling_reload has
        # returned, so every replica now holds the new weights)
        extras = _requests(3, rng)
        reqs += extras
        futs += [router.submit(x, deadline_ms=60_000) for x in extras]
        outs = [f.result(timeout=120) for f in futs]

        assert [m["step"] for m in metas] == [7, 7, 7]
        n_old = n_new = 0
        for x, out in zip(reqs, outs):
            old = _ref(serving_ref, x)
            new = _ref(trained, x)
            is_old = np.allclose(out, old, rtol=2e-5, atol=2e-5)
            is_new = np.allclose(out, new, rtol=2e-5, atol=2e-5)
            assert is_old != is_new     # exactly one weight set, no mix
            n_old += is_old
            n_new += is_new
        assert n_new >= 3                # the rollout really landed
        s = router.stats()
        assert s["served"] == 63 and s["requests_lost"] == 0
        assert s["reloads"] == 3
        router.drain(timeout=60)
        for rep in router.replicas:
            st = rep.server.stats()
            assert st["graph"]["post_warmup_compiles"] == 0
            assert st["reloads"] == 1
    finally:
        router.shutdown(drain=False)


def test_rolling_reload_single_replica_reloads_in_place(tmp_path):
    trained = _make_net(seed=11)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(3, params=trained, sync=True)
    mgr.wait_until_finished()
    router = serve.Router(_factory(seed=99, checkpoint=mgr), 1,
                          health_sec=0.0)
    router.start()
    try:
        x = np.random.RandomState(5).rand(4, FEAT).astype(np.float32)
        metas = router.rolling_reload()
        out = router.submit(x).result(timeout=60)
        np.testing.assert_allclose(out, _ref(trained, x),
                                   rtol=2e-5, atol=2e-5)
        assert [m["step"] for m in metas] == [3]
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# observability


def test_router_section_window_scoped():
    from mxnet_tpu import profiler

    profiler.dumps(reset=True)
    router = _router(2)
    router.start()
    try:
        futs = [router.submit(np.zeros((4, FEAT), np.float32))
                for _ in range(4)]
        for f in futs:
            f.result(timeout=60)
    finally:
        router.shutdown()
    d = json.loads(profiler.dumps(reset=True))
    assert d["router"]["dispatched"] >= 4
    d2 = json.loads(profiler.dumps())
    assert d2["router"]["dispatched"] == 0      # window rewound


def test_router_metrics_export():
    from mxnet_tpu.telemetry import metrics

    reg = metrics.Registry()
    router = _router(2)
    router.start()
    try:
        collector = metrics.register_router(router, registry=reg)
        futs = [router.submit(np.zeros((4, FEAT), np.float32))
                for _ in range(3)]
        for f in futs:
            f.result(timeout=60)
        page = reg.render()
        assert 'mxtpu_router_served{router="' in page
        assert "mxtpu_router_requests_lost" in page
        assert "mxtpu_router_healthy" in page
        assert 'mxtpu_router_replica_healthy{replica="0",router="' \
            in page
        assert "mxtpu_router_latency_ms_bucket" in page
        reg.unregister_collector(collector)
        assert "mxtpu_router_served" not in reg.render()
    finally:
        router.shutdown()


def test_router_request_span_hop_attribution(tmp_path):
    """A traced pooled request leaves a balanced serve.router.request
    async span whose dispatch-hop instants attribute each attempt to a
    replica with the remaining budget at that hop."""
    from mxnet_tpu import telemetry

    router = _router(2)
    router.start()
    trace_path = str(tmp_path / "router.trace.json")
    try:
        plan = faults.FaultPlan([{"site": "serve.replica.submit",
                                  "action": "raise", "on_hit": 1}])
        with telemetry.trace(trace_path):
            with faults.armed(plan):
                router.submit(np.zeros((4, FEAT), np.float32),
                              deadline_ms=30_000).result(timeout=60)
    finally:
        router.shutdown()
    events = json.load(open(trace_path))["traceEvents"]
    begins = [e for e in events if e["ph"] == "b"
              and e["name"] == "serve.router.request"]
    ends = [e for e in events if e["ph"] == "e"
            and e["name"] == "serve.router.request"]
    hops = [e for e in events if e["ph"] == "n"
            and e["name"] == "serve.router.dispatch"]
    assert len(begins) == len(ends) == 1
    assert ends[0]["args"]["outcome"] == "served"
    assert ends[0]["args"]["attempts"] == 2
    assert len(hops) == 1    # the faulted attempt never reached submit
    assert hops[0]["args"]["replica"] in (0, 1)
    assert 0 < hops[0]["args"]["remaining_ms"] <= 30_000


# ---------------------------------------------------------------------------
# decode pool


def test_decode_pool_routes():
    """The router fronts DecodeServer replicas through the same edge:
    submit kwargs (max_new_tokens) pass through, results are the full
    token sequences, and probing auto-adapts (one-token probes)."""
    VOCAB = 32

    def make_model():
        mx.random.seed(4)
        m = serve.TinyDecoder(vocab=VOCAB, embed=8)
        m.initialize(mx.init.Xavier())
        return m

    dspec = serve.BucketSpec(batch_sizes=(1, 2), example_shape=(None,),
                             lengths=(4, 8), dtype="int32")

    def factory(rid):
        return serve.DecodeServer(make_model(), dspec, max_slots=2,
                                  max_len=16)

    ref_srv = serve.DecodeServer(make_model(), dspec, max_slots=2,
                                 max_len=16)
    ref_srv.start()
    router = serve.Router(factory, 2, health_sec=0.0)
    router.start()
    try:
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, VOCAB, size=int(rng.randint(2, 7)))
                   .astype(np.int32) for _ in range(6)]
        futs = [router.submit(p, max_new_tokens=4) for p in prompts]
        outs = [f.result(timeout=120) for f in futs]
        for p, out in zip(prompts, outs):
            ref = ref_srv.generate(p, max_new_tokens=4, timeout=120)
            np.testing.assert_array_equal(out, ref)
        s = router.stats()
        assert s["served"] == 6 and s["requests_lost"] == 0
    finally:
        router.shutdown()
        ref_srv.shutdown()
    for rep in router.replicas:
        assert rep.server.stats()["graph"]["post_warmup_compiles"] == 0


# ---------------------------------------------------------------------------
# lifecycle


@pytest.mark.slow
def test_router_concurrent_stress_under_lock_checker(tmp_path):
    """8 submitter threads, an injected replica death mid-stream, and a
    rolling reload — all under the runtime lock-order checker
    (raise-on-inversion): every request resolves or fails classified,
    the pool heals, zero requests lost, zero inversions observed."""
    from mxnet_tpu.analysis import runtime as lockrt
    from mxnet_tpu.resilience.supervisor import classify as _classify

    trained = _make_net(seed=11)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(1, params=trained, sync=True)
    mgr.wait_until_finished()

    lockrt.enable(raise_on_inversion=True)
    lockrt.wrap_existing()
    try:
        router = serve.Router(
            _factory(seed=3, checkpoint=mgr), 3, health_sec=0.2,
            evict_after=3,
            retry=RetryPolicy(max_retries=3, base_delay=0.01,
                              max_delay=0.05))
        router.start()
        plan = faults.FaultPlan([
            {"site": "serve.replica.submit", "action": "raise",
             "match": {"replica": 2}, "times": None}], seed=11)
        results, errors = [], []
        lock = threading.Lock()

        def submitter(seed):
            rng = np.random.RandomState(seed)
            for x in _requests(25, rng):
                try:
                    out = router.submit(
                        x, deadline_ms=60_000).result(timeout=120)
                    with lock:
                        results.append(out)
                except Exception as e:  # noqa: BLE001 — audited below
                    with lock:
                        errors.append(e)
        with faults.armed(plan):
            threads = [threading.Thread(target=submitter, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            router.rolling_reload(timeout=120)
            for t in threads:
                t.join()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                s = router.stats()
                if s["healthy"] == 3 and s["replacements"] >= 1:
                    break
                time.sleep(0.02)
        assert len(results) + len(errors) == 8 * 25
        for e in errors:     # every failure classified, none mysterious
            assert _classify(e) in ("transient", "overloaded",
                                    "deadline")
        s = router.stats()
        assert s["requests_lost"] == 0
        assert s["evictions"] == 1 and s["healthy"] == 3
        router.drain(timeout=120)
        for rep in router.replicas:
            assert rep.server.stats()["graph"][
                "post_warmup_compiles"] == 0
        assert lockrt.stats()["inversions"] == 0
    finally:
        lockrt.disable()


def test_shutdown_abrupt_resolves_everything():
    router = _router(2)
    router.start()
    for rep in router.replicas:
        rep.server.submit = \
            lambda example, deadline_ms=None, **kw: Future()
    futs = [router.submit(np.zeros((4, FEAT), np.float32))
            for _ in range(3)]
    router.shutdown(drain=False)
    for f in futs:
        assert f.done()
        with pytest.raises(serve.ServerClosedError):
            f.result(timeout=0)
    assert router.stats()["requests_lost"] == 0
    with pytest.raises(serve.ServerClosedError):
        router.submit(np.zeros((4, FEAT), np.float32))
