"""Shape buckets: the fixed compilation surface of a ModelServer.

XLA compiles one executable per input signature; serving arbitrary
request shapes therefore means either unbounded compilation (the TVM /
Julia-TPU papers' motivating failure, arxiv 1802.04799 / 1810.09868) or
padding every request into a small, closed set of shapes compiled ahead
of time.  A :class:`BucketSpec` names that closed set: a grid of batch
sizes x variable-axis lengths.  ``ModelServer`` warms every bucket at
startup, so steady-state traffic never compiles — the invariant
``tests/test_serve.py`` asserts with the CachedOp compile counters.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError


class BucketOverflowError(MXNetError):
    """A request is larger than every configured bucket."""


class BucketSpec:
    """The closed set of padded input shapes a server compiles.

    Parameters
    ----------
    batch_sizes : sequence of int
        Allowed batch dimensions, e.g. ``(1, 2, 4, 8)``.  A batch of n
        requests pads up to the smallest bucket >= n; the largest entry
        is also the coalescing cap.
    example_shape : tuple
        Per-request shape WITHOUT the batch dim.  At most one axis may
        be ``None`` — the variable (sequence/spatial) axis whose
        concrete sizes come from ``lengths``.
    lengths : sequence of int, optional
        Allowed sizes of the variable axis, e.g. ``(32, 64, 128)``.
        Required iff ``example_shape`` contains a ``None``.
    pad_value : float
        Fill for padded positions and dead batch rows.
    dtype : str
        Input dtype every bucket is compiled for.
    """

    def __init__(self, batch_sizes, example_shape, lengths=None,
                 pad_value=0.0, dtype="float32"):
        self.batch_sizes = tuple(sorted(set(int(b) for b in batch_sizes)))
        if not self.batch_sizes or self.batch_sizes[0] < 1:
            raise MXNetError("batch_sizes must be positive ints")
        self.example_shape = tuple(example_shape)
        var_axes = [i for i, s in enumerate(self.example_shape) if s is None]
        if len(var_axes) > 1:
            raise MXNetError(
                f"example_shape {self.example_shape} has more than one "
                "variable (None) axis; buckets support at most one")
        self.var_axis = var_axes[0] if var_axes else None
        if self.var_axis is not None:
            if not lengths:
                raise MXNetError(
                    "example_shape has a variable axis but no lengths= "
                    "bucket list was given")
            self.lengths = tuple(sorted(set(int(l) for l in lengths)))
        else:
            if lengths:
                raise MXNetError(
                    "lengths= given but example_shape has no variable "
                    "(None) axis to apply them to")
            self.lengths = None
        self.pad_value = pad_value
        self.dtype = np.dtype(dtype)

    # -- geometry -----------------------------------------------------------

    @property
    def max_batch(self):
        return self.batch_sizes[-1]

    def bucket_shapes(self):
        """Every (batch, *example) shape the server compiles — the AOT
        warmup schedule, smallest first so warmup fails fast on a bad
        model before burning time on the big shapes."""
        out = []
        for b in self.batch_sizes:
            for l in (self.lengths or (None,)):
                out.append((b,) + self._example_shape_for(l))
        return sorted(out, key=lambda s: int(np.prod(s)))

    def _example_shape_for(self, length):
        if self.var_axis is None:
            return self.example_shape
        shape = list(self.example_shape)
        shape[self.var_axis] = length
        return tuple(shape)

    def validate(self, example):
        """Check one request's array against the spec; returns its
        variable-axis length (or None for fixed-shape specs)."""
        shape = tuple(example.shape)
        if len(shape) != len(self.example_shape):
            raise MXNetError(
                f"request shape {shape} has rank {len(shape)}, spec "
                f"expects rank {len(self.example_shape)} "
                f"({self.example_shape}; no batch dim in requests)")
        for axis, (got, want) in enumerate(zip(shape, self.example_shape)):
            if want is None:
                continue
            if got != want:
                raise MXNetError(
                    f"request shape {shape} differs from spec "
                    f"{self.example_shape} at axis {axis}")
        if self.var_axis is None:
            return None
        length = shape[self.var_axis]
        if length > self.lengths[-1]:
            raise BucketOverflowError(
                f"request length {length} exceeds the largest bucket "
                f"{self.lengths[-1]}; add a bucket or truncate upstream")
        if length < 1:
            raise MXNetError(f"request shape {shape} has an empty "
                             "variable axis")
        return length

    def pick(self, n_requests, max_length=None):
        """Smallest (batch_bucket, length_bucket) covering a group."""
        n = min(int(n_requests), self.max_batch)
        batch = next(b for b in self.batch_sizes if b >= n)
        if self.var_axis is None:
            return batch, None
        length = next(l for l in self.lengths if l >= max_length)
        return batch, length

    # -- padding ------------------------------------------------------------

    def pad_batch(self, examples, batch, length):
        """Stack per-request host arrays into one padded bucket batch.

        Returns the (batch, *example_shape_for(length)) numpy array —
        dead rows beyond len(examples) and positions beyond each
        request's own length hold ``pad_value``.
        """
        shape = (batch,) + self._example_shape_for(length)
        out = np.full(shape, self.pad_value, dtype=self.dtype)
        for i, ex in enumerate(examples):
            idx = [i] + [slice(0, s) for s in ex.shape]
            out[tuple(idx)] = ex
        return out

    def key(self, batch, length):
        """Stable string id for a bucket, used in stats dicts."""
        return f"b{batch}" if length is None else f"b{batch}xl{length}"

    def __repr__(self):
        return (f"BucketSpec(batch_sizes={self.batch_sizes}, "
                f"example_shape={self.example_shape}, "
                f"lengths={self.lengths}, dtype={self.dtype.name})")
