"""Flight recorder: the last seconds of a crashing job, on disk.

A bounded ring (``collections.deque(maxlen=ring_size)``) of the most
recent telemetry events rides in :mod:`.tracer`; on a watchdog fire, a
fatal supervisor failure, or SIGTERM the ring plus a counters snapshot
is dumped to ``flight-<rank>-<ts>.json`` — a loadable Chrome-trace
timeline of what the process was doing when it died, with the
profiler's counter sections and currently-OPEN op scopes attached for
post-mortem context.

Arming:

- ``MXTPU_FLIGHT_RECORDER=<ring size>`` arms it process-wide at
  telemetry import (``0``/``off`` forces it off everywhere);
- ``resilience.Supervisor.run`` auto-arms it for the duration of the
  supervised job (default ring 512, dumps land next to the
  checkpoints) unless the env var said ``off``;
- ``enable(size, directory)`` / ``disable()`` for manual control.

Disarmed there is no ring and the tracer hooks stay bound to the
no-op — the same zero-cost contract as ``engine.fault_point``.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from ..base import getenv
from . import tracer

DEFAULT_RING = 512

_lock = threading.Lock()
_directory = "."
_auto_depth = 0          # nested Supervisor auto-enables


def _env_setting():
    """``MXTPU_FLIGHT_RECORDER``: None (unset), 0 (explicit off), or a
    ring size."""
    raw = getenv("FLIGHT_RECORDER")
    if raw is None:
        return None
    if str(raw).strip().lower() in ("0", "off", "false", "no"):
        return 0
    try:
        return max(1, int(raw))
    except ValueError:
        return DEFAULT_RING


def enabled():
    return tracer.flight_ring() is not None


def enable(size=None, directory=None):
    """Arm the ring (idempotent; a second call only resizes/re-aims).
    ``size`` defaults to ``MXTPU_FLIGHT_RECORDER`` or 512."""
    global _directory
    if size is None:
        size = _env_setting() or DEFAULT_RING
    size = max(1, int(size))
    with _lock:
        if directory is not None:
            _directory = str(directory)
        ring = tracer.flight_ring()
        if ring is not None and ring.maxlen == size:
            return
        old = list(ring) if ring is not None else []
        tracer.set_flight_ring(
            collections.deque(old[-size:], maxlen=size))


def disable():
    tracer.set_flight_ring(None)


def auto_enable(directory=None):
    """Supervisor entry hook: arm with defaults unless the env var
    explicitly said off.  A ring armed BEFORE the supervised run
    (manual ``enable()`` or the env var) is left exactly as configured
    — size, directory and post-run lifetime all belong to whoever
    armed it.  Nested/repeated supervised runs refcount, so the
    outermost exit disarms only what this hook armed."""
    global _auto_depth
    if _env_setting() == 0:
        return None
    if enabled():
        return "riding"        # pre-armed: don't resize, don't disarm
    enable(directory=directory)
    with _lock:
        _auto_depth += 1
    return "armed"


def auto_disable(token):
    """Supervisor exit hook; pass ``auto_enable``'s return value."""
    global _auto_depth
    if token != "armed":
        return
    with _lock:
        _auto_depth = max(0, _auto_depth - 1)
        keep = _auto_depth > 0
    if not keep:
        disable()


def dump(reason, directory=None, extra=None):
    """Write the ring + counters snapshot; returns the file path.

    The file is itself valid Chrome trace-event JSON (``traceEvents``
    at top level) so Perfetto loads the crash timeline directly; the
    ``counters`` (profiler sections), ``activeScopes`` (open op scopes,
    when the watchdog armed tracking), and ``extra`` keys carry the
    post-mortem context.
    """
    ring = tracer.flight_ring()
    events = list(ring) if ring is not None else []
    from .. import profiler

    data = {
        "reason": str(reason),
        "rank": _rank(),
        "time_unix": time.time(),
        "ring_size": ring.maxlen if ring is not None else 0,
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "counters": profiler.sections(),
        "activeScopes": {str(k): v for k, v in
                         profiler.active_scopes().items()},
    }
    if extra:
        data["extra"] = dict(extra)
    d = str(directory) if directory is not None else _directory
    os.makedirs(d, exist_ok=True)
    path = os.path.join(
        d, f"flight-{data['rank']}-{int(data['time_unix'] * 1e3)}.json")
    n = 0
    while os.path.exists(path):    # same-ms dumps: never overwrite
        n += 1
        path = path[:path.rindex(".json")].split("~")[0] + f"~{n}.json"
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)          # atomic: SIGKILL window safe
    tracer.bump("flight_dumps")
    return path


def dump_if_enabled(reason, directory=None, extra=None):
    """Best-effort dump for signal handlers / crash paths: no-op when
    the ring is disarmed, and never raises."""
    if not enabled():
        return None
    try:
        return dump(reason, directory=directory, extra=extra)
    except Exception:  # noqa: BLE001 — a dump must not mask the crash
        return None


def _rank():
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — pre-init / no backend: rank 0
        return 0
