"""Pass family 4: repo-invariant lints (MXA4xx).

These encode, mechanically, the invariants past PRs fixed by hand in
review passes — so the next violation is a CI failure, not a reviewer
catch.

MXA401  raw environment read — ``os.environ``/``os.getenv`` outside
        ``base.py``.  Every knob goes through ``base.getenv`` so both
        the ``MXTPU_``/``MXNET_`` spellings work; the documented
        exception is the raw launcher wire protocol (``DMLC_*``), which
        is allowed by prefix but still must be documented.
MXA402  undocumented env knob — a ``base.getenv("NAME")`` read whose
        ``MXTPU_NAME`` spelling (or a raw read whose literal name) does
        not appear in docs/ENV_VARS.md.
MXA403  profiler section registry violation — a ``_*_counters``
        provider in the profiler module that is not registered via
        ``register_section`` (the registry is what ``dumps()`` and
        ``_aggregate_table()`` iterate, so an unregistered section
        silently vanishes from both output paths), a registered
        provider that ignores its ``reset`` flag, or an output path
        calling a provider / the registry iterator without forwarding
        ``reset`` (the "reset dump must scope EVERY section" rule PRs
        2-5 each re-fixed by hand before the registry existed).
MXA404  uncataloged fault point — an ``engine.fault_point("site")``
        whose site name is missing from the docs/resilience.md catalog
        (chaos plans target sites by name; an uncataloged site is
        untestable by reading the docs).
MXA405  uncataloged telemetry name — a registered profiler section, a
        literal span site (``op_scope``/``span_begin``/``instant``/
        ``request_begin``), or a literal ``mxtpu_*`` metric name that
        does not appear in docs/observability.md (dashboards and trace
        queries target these names; an uncataloged one is invisible to
        anyone reading the docs — the fault-point rule, applied to
        observability).
"""
from __future__ import annotations

import ast
import re

from .core import Finding


# -- env reads --------------------------------------------------------------


def _literal(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _raw_env_reads(index, mod):
    """(node, name_or_None) for os.environ/os.getenv touches."""
    out = []
    for node in ast.walk(mod.tree):
        # os.environ.get("X") / os.environ["X"] / os.getenv("X")
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and mod.ext_aliases.get(f.value.value.id) == "os"
                    and f.value.attr == "environ"
                    and f.attr in ("get", "setdefault", "pop")):
                out.append((node, _literal(node.args[0])
                            if node.args else None))
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and mod.ext_aliases.get(f.value.id) == "os"
                  and f.attr == "getenv"):
                out.append((node, _literal(node.args[0])
                            if node.args else None))
        elif isinstance(node, ast.Subscript):
            v = node.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and mod.ext_aliases.get(v.value.id) == "os"
                    and v.attr == "environ"):
                out.append((node, _literal(node.slice)))
        elif isinstance(node, ast.Compare):
            # "X" in os.environ / "X" not in os.environ
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))):
                cmp = node.comparators[0]
                if (isinstance(cmp, ast.Attribute)
                        and isinstance(cmp.value, ast.Name)
                        and mod.ext_aliases.get(cmp.value.id) == "os"
                        and cmp.attr == "environ"):
                    out.append((node, _literal(node.left)))
    return out


def _env_findings(index, findings):
    cfg = index.cfg
    doc = index.doc_text(cfg.env_doc) or ""
    documented = set(re.findall(r"[A-Z][A-Z0-9_]{2,}", doc))
    exempt = set(cfg.env_exempt_modules)
    seen_doc_checks = set()

    for name, mod in sorted(index.modules.items()):
        raw = _raw_env_reads(index, mod)
        for node, env_name in raw:
            sym = index.enclosing(mod, node.lineno)
            allowed = (name in exempt
                       or (env_name is not None
                           and env_name.startswith(
                               tuple(cfg.raw_env_allowed_prefixes))))
            if not allowed:
                findings.append(Finding(
                    "MXA401", mod.relpath, node.lineno,
                    f"{sym}:{env_name or '<dynamic>'}",
                    f"raw environment read of "
                    f"{env_name or 'a computed name'} in {sym} — route "
                    f"through base.getenv so MXTPU_/MXNET_ spellings "
                    f"both work"))
            if (env_name is not None and name not in exempt
                    and env_name not in documented):
                k = (mod.relpath, env_name)
                if k not in seen_doc_checks:
                    seen_doc_checks.add(k)
                    findings.append(Finding(
                        "MXA402", mod.relpath, node.lineno,
                        f"{sym}:{env_name}",
                        f"env var {env_name} is read here but not "
                        f"documented in {cfg.env_doc}"))

        # base.getenv("NAME") reads: NAME must be documented as
        # MXTPU_NAME (the canonical spelling)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if fname not in cfg.getenv_fns or not node.args:
                continue
            env_name = _literal(node.args[0])
            if env_name is None:
                continue
            if "MXTPU_" + env_name not in documented:
                sym = index.enclosing(mod, node.lineno)
                k = (mod.relpath, env_name)
                if k in seen_doc_checks:
                    continue
                seen_doc_checks.add(k)
                findings.append(Finding(
                    "MXA402", mod.relpath, node.lineno,
                    f"{sym}:{env_name}",
                    f"env knob MXTPU_{env_name} (base.getenv "
                    f"{env_name!r}) is not documented in "
                    f"{cfg.env_doc}"))


# -- profiler window scoping ------------------------------------------------


def _fname(call_func):
    if isinstance(call_func, ast.Name):
        return call_func.id
    if isinstance(call_func, ast.Attribute):
        return call_func.attr
    return None


def _passes_reset(node):
    return any(isinstance(a, ast.Name) and a.id == "reset"
               for a in list(node.args)
               + [kw.value for kw in node.keywords])


def _profiler_findings(index, findings):
    cfg = index.cfg
    mod = index.modules.get(cfg.profiler_module)
    if mod is None:
        return
    # provider functions by the naming convention ...
    pattern_providers = {}
    for key, func in index.funcs.items():
        if func.module is mod and func.cls is None and \
                re.fullmatch(r"_[a-z0-9_]+_counters", func.name):
            pattern_providers[func.name] = func
    # ... and what the section registry actually holds:
    # register_section("name", provider_fn) calls in the module
    registered = {}   # local provider name -> (section name, call node)
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and _fname(node.func) in cfg.section_register_fns
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Name)):
            registered[node.args[1].id] = (_literal(node.args[0]), node)

    # membership: a conventionally-named provider that never reaches
    # the registry silently vanishes from BOTH output paths
    for name, func in sorted(pattern_providers.items()):
        if name not in registered:
            findings.append(Finding(
                "MXA403", mod.relpath, func.node.lineno, name,
                f"profiler section provider {name} is not registered "
                f"via register_section — dumps()/_aggregate_table() "
                f"iterate the registry, so this section would silently "
                f"vanish from both output paths"))

    # reset scoping: every provider (registered or convention-named)
    # must take reset and zero its counters under `if reset:`
    checkable = dict(pattern_providers)
    for name in registered:
        func = index.funcs.get((mod.modname, name))
        if func is not None:
            checkable.setdefault(name, func)
    for name, func in sorted(checkable.items()):
        argnames = [a.arg for a in func.node.args.args]
        if "reset" not in argnames:
            findings.append(Finding(
                "MXA403", mod.relpath, func.node.lineno, name,
                f"profiler section provider {name} takes no reset "
                f"parameter — sections must be window-scopable"))
            continue
        resets = False
        for node in ast.walk(func.node):
            if isinstance(node, ast.If):
                test_names = {n.id for n in ast.walk(node.test)
                              if isinstance(n, ast.Name)}
                if "reset" in test_names:
                    for sub in ast.walk(node):
                        if (isinstance(sub, ast.Call)
                                and "reset" in ast.dump(sub.func).lower()):
                            resets = True
        if not resets:
            findings.append(Finding(
                "MXA403", mod.relpath, func.node.lineno, name,
                f"profiler section provider {name} never resets its "
                f"counters under `if reset:` — dumps(reset=True) would "
                f"mix window events with forever-cumulative counts"))

    # both output paths must forward reset — whether they call a
    # provider directly (legacy style) or iterate the registry through
    # a section_iter_fns helper
    for caller_name in ("dumps", "_aggregate_table"):
        caller = index.funcs.get((mod.modname, caller_name))
        if caller is None:
            continue
        touched = False
        for node in ast.walk(caller.node):
            if not isinstance(node, ast.Call):
                continue
            fn = _fname(node.func)
            if fn in checkable:
                touched = True
                if not _passes_reset(node):
                    findings.append(Finding(
                        "MXA403", mod.relpath, node.lineno,
                        f"{caller_name}:{fn}",
                        f"{caller_name}() calls {fn} without forwarding "
                        f"reset — this output path would not "
                        f"window-scope the section"))
            elif fn in cfg.section_iter_fns:
                touched = True
                if not _passes_reset(node):
                    findings.append(Finding(
                        "MXA403", mod.relpath, node.lineno,
                        f"{caller_name}:{fn}",
                        f"{caller_name}() iterates the section "
                        f"registry via {fn} without forwarding reset — "
                        f"this output path would not window-scope ANY "
                        f"section"))
        if not touched and (registered or pattern_providers):
            findings.append(Finding(
                "MXA403", mod.relpath, caller.node.lineno,
                f"{caller_name}:<no-sections>",
                f"{caller_name}() neither iterates the section "
                f"registry nor calls a provider — counter sections "
                f"are missing from this output path"))


# -- fault-point catalog ----------------------------------------------------


def _fault_point_findings(index, findings):
    cfg = index.cfg
    doc = index.doc_text(cfg.resilience_doc) or ""
    for name, mod in sorted(index.modules.items()):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if fname not in cfg.fault_point_fns or not node.args:
                continue
            site = _literal(node.args[0])
            if site is None:
                continue   # dispatcher plumbing forwards a variable
            if f"`{site}`" not in doc and site not in doc:
                sym = index.enclosing(mod, node.lineno)
                findings.append(Finding(
                    "MXA404", mod.relpath, node.lineno,
                    f"{sym}:{site}",
                    f"fault point '{site}' is not cataloged in "
                    f"{cfg.resilience_doc} — chaos plans target sites "
                    f"by name"))


# -- telemetry catalog ------------------------------------------------------


def _telemetry_catalog_findings(index, findings):
    """MXA405: registered section names, literal span sites, and
    literal ``mxtpu_*`` metric names must appear in the observability
    doc — dashboards, scrape configs, and Perfetto queries target
    telemetry by name, so an undocumented name is unfindable."""
    cfg = index.cfg
    doc = index.doc_text(cfg.observability_doc) or ""

    def _check(mod, node, kind, name):
        if name in doc:
            return
        sym = index.enclosing(mod, node.lineno)
        findings.append(Finding(
            "MXA405", mod.relpath, node.lineno, f"{sym}:{name}",
            f"{kind} '{name}' is not cataloged in "
            f"{cfg.observability_doc} — telemetry consumers target "
            f"these names by reading the docs"))

    for _name, mod in sorted(index.modules.items()):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = _fname(node.func)
            lit = _literal(node.args[0])
            if lit is None:
                continue   # dynamic names (f-string buckets) are
                # documented as families, not checked per-site
            if fn in cfg.section_register_fns:
                _check(mod, node, "profiler section", lit)
            elif fn in cfg.span_site_fns:
                _check(mod, node, "span site", lit)
            elif fn in cfg.metric_def_fns and \
                    lit.startswith(cfg.metric_name_prefix):
                _check(mod, node, "metric", lit)


def run(index):
    findings = []
    _env_findings(index, findings)
    _profiler_findings(index, findings)
    _fault_point_findings(index, findings)
    _telemetry_catalog_findings(index, findings)
    return findings
