"""Attention ops.

Ref: src/operator/contrib/transformer.{cc,cu} (_contrib interleaved
matmul selfatt ops) — the Sockeye-era building blocks — upgraded to a
fused scaled-dot-product attention op (capability upgrade per SURVEY
§2.2 'Fused attention as Pallas flash-attention kernel, still
API-compatible').

Two paths: a Pallas flash-attention kernel on TPU (ops/pallas/
flash_attention.py) and this XLA fallback; the fallback is the oracle.
Selection is automatic by platform; MXTPU_DISABLE_PALLAS=1 forces the
fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import getenv
from .registry import register


def _use_pallas():
    if getenv("DISABLE_PALLAS", False, bool):
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def sdpa_reference(q, k, v, mask=None, *, scale=None, causal=False):
    """Scaled dot-product attention, XLA fallback / numeric oracle.

    q,k,v: (batch, heads, seq, head_dim). mask: additive (b,1,sq,sk) or
    bool; causal adds a lower-triangular mask.
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        logits = jnp.where(causal_mask, logits, jnp.asarray(-1e9, q.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.asarray(-1e9, q.dtype))
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _k_sdpa(q, k, v, mask=None, *, scale=None, causal=False,
            dropout_p=0.0):
    if _use_pallas():
        try:
            from .pallas.flash_attention import flash_attention

            return flash_attention(q, k, v, mask=mask, scale=scale,
                                   causal=causal)
        except Exception:  # pragma: no cover - pallas fallback safety
            pass
    return sdpa_reference(q, k, v, mask, scale=scale, causal=causal)


register("scaled_dot_product_attention", _k_sdpa,
         arg_names=("q", "k", "v", "mask"),
         aliases=("_contrib_sdpa",))


def _k_multihead_attention(query, key, value, in_weight, in_bias,
                           out_weight, out_bias, mask=None, *,
                           num_heads, causal=False):
    """Full fused MHA: qkv projection + sdpa + output projection.

    query/key/value: (batch, seq, model_dim); in_weight: (3*model, model)
    packed q,k,v projections; out_weight: (model, model).
    """
    b, sq, m = query.shape
    h = num_heads
    hd = m // h
    wq, wk, wv = jnp.split(in_weight, 3, axis=0)
    bq, bk, bv = jnp.split(in_bias, 3, axis=0)

    def proj(x, w, bias):
        return (x @ w.T + bias).reshape(x.shape[0], x.shape[1], h, hd) \
            .transpose(0, 2, 1, 3)

    qh = proj(query, wq, bq)
    kh = proj(key, wk, bk)
    vh = proj(value, wv, bv)
    out = _k_sdpa(qh, kh, vh, mask, scale=None, causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(b, sq, m)
    return out @ out_weight.T + out_bias


register("multihead_attention", _k_multihead_attention,
         arg_names=("query", "key", "value", "in_weight", "in_bias",
                    "out_weight", "out_bias", "mask"))


# Sockeye-era interleaved ops for parity with the reference's contrib
# (ref: src/operator/contrib/transformer.cc)

def _k_interleaved_matmul_selfatt_qk(qkv, *, heads):
    # qkv: (seq, batch, 3*model) interleaved per head
    s, b, m3 = qkv.shape
    m = m3 // 3
    hd = m // heads
    x = qkv.reshape(s, b, heads, 3, hd)
    q = x[:, :, :, 0]
    k = x[:, :, :, 1]
    q = q.transpose(1, 2, 0, 3) / jnp.sqrt(jnp.asarray(hd, qkv.dtype))
    k = k.transpose(1, 2, 0, 3)
    att = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    return att.reshape(b * heads, s, s)


register("_contrib_interleaved_matmul_selfatt_qk",
         _k_interleaved_matmul_selfatt_qk, arg_names=("queries_keys_values",))


def _k_interleaved_matmul_selfatt_valatt(qkv, att, *, heads):
    s, b, m3 = qkv.shape
    m = m3 // 3
    hd = m // heads
    v = qkv.reshape(s, b, heads, 3, hd)[:, :, :, 2]
    v = v.transpose(1, 2, 0, 3)
    att = att.reshape(b, heads, s, s)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
    return out.transpose(2, 0, 1, 3).reshape(s, b, m)


register("_contrib_interleaved_matmul_selfatt_valatt",
         _k_interleaved_matmul_selfatt_valatt,
         arg_names=("queries_keys_values", "attention"))
