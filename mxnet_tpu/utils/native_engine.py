"""ctypes binding for the native dependency engine (src/engine.cc →
lib/libmxtpu_engine.so).

Ref: include/mxnet/engine.h — Engine::PushAsync/NewVariable/WaitForVar/
WaitForAll, with the ThreadedVar RAW/WAR/WAW contract enforced in C++.
The TPU build uses it for host-side work (decode, checkpoint, staging);
device work is ordered by XLA/PjRt itself.  Falls back to None when the
.so is unavailable (MXTPU_NO_NATIVE=1 forces pure-Python paths).
"""
from __future__ import annotations

import concurrent.futures
import ctypes
import itertools
import os
import shutil
import subprocess
import threading

from ..base import getenv

_lib = None
_tried = False

_EngineFn = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load():
    """Return the native engine lib handle or None."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    from .libloader import load_native_lib

    # build just this target: the IO lib needs libjpeg and must not
    # block the engine (which has no external deps)
    lib = load_native_lib("libmxtpu_engine.so", "lib/libmxtpu_engine.so")
    if lib is None:
        return None
    lib.MXTPUEngineCreate.restype = ctypes.c_void_p
    lib.MXTPUEngineCreate.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.MXTPUEngineFree.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineNewVariable.restype = ctypes.c_uint64
    lib.MXTPUEngineNewVariable.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineDeleteVariable.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64]
    lib.MXTPUEnginePushAsync.argtypes = [
        ctypes.c_void_p, _EngineFn, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
    lib.MXTPUEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPUEngineWaitForAll.argtypes = [ctypes.c_void_p]
    lib.MXTPUEngineSelfTest.restype = ctypes.c_int
    lib.MXTPUEngineSelfTest.argtypes = [ctypes.c_uint64, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_int]
    _lib = lib
    return _lib


class NativeEngine:
    """Python handle on the C++ threaded engine.

    Ops are python callables; the C++ side enforces var dependencies and
    runs them on its worker pool.  Each push returns a Future whose
    result/exception comes from the callable.
    """

    def __init__(self, num_workers=None, naive=False):
        lib = load()
        assert lib is not None, "native engine library unavailable"
        self._lib = lib
        if num_workers is None:
            num_workers = getenv("CPU_WORKER_NTHREADS", 4, int)
        self._handle = ctypes.c_void_p(
            lib.MXTPUEngineCreate(num_workers, int(naive)))
        self._ops = {}
        self._ops_lock = threading.Lock()
        self._ids = itertools.count(1)
        # single static trampoline; ctx carries the op id so no per-op
        # CFUNCTYPE object lifetime to manage
        self._trampoline = _EngineFn(self._run_op)

    def _run_op(self, ctx):
        with self._ops_lock:
            fn, fut = self._ops.pop(int(ctx))
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001 - future semantics
            fut.set_exception(e)

    def new_variable(self):
        return self._lib.MXTPUEngineNewVariable(self._handle)

    def delete_variable(self, var):
        self._lib.MXTPUEngineDeleteVariable(self._handle, var)

    def push(self, fn, const_vars=(), mutable_vars=()):
        fut = concurrent.futures.Future()
        op_id = next(self._ids)
        with self._ops_lock:
            self._ops[op_id] = (fn, fut)
        cv = (ctypes.c_uint64 * len(const_vars))(*const_vars)
        mv = (ctypes.c_uint64 * len(mutable_vars))(*mutable_vars)
        self._lib.MXTPUEnginePushAsync(
            self._handle, self._trampoline, ctypes.c_void_p(op_id),
            cv, len(const_vars), mv, len(mutable_vars))
        return fut

    def wait_for_var(self, var):
        self._lib.MXTPUEngineWaitForVar(self._handle, var)

    def wait_all(self):
        self._lib.MXTPUEngineWaitForAll(self._handle)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.MXTPUEngineWaitForAll(self._handle)
            self._lib.MXTPUEngineFree(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def self_test(seed=0, n_vars=16, n_ops=2000, num_workers=8):
    """Random-DAG naive-vs-threaded equivalence check run inside the C++
    lib (ref: tests/cpp/engine/threaded_engine_test.cc)."""
    lib = load()
    assert lib is not None, "native engine library unavailable"
    return lib.MXTPUEngineSelfTest(seed, n_vars, n_ops, num_workers)
