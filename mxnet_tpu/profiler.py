"""Profiler (ref: src/profiler/profiler.{h,cc} + python/mxnet/profiler.py).

Two tiers, per SURVEY §5:
1. Op-level chrome://tracing JSON — every imperative invoke is bracketed
   (dispatch + optional sync timing), dumped via ``dumps()``/``dump()``
   exactly like the reference's MXDumpProfile.
2. XLA-level — ``start()`` can also open a jax.profiler trace
   (tensorboard-plugin-profile readable) capturing device timelines.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .base import getenv
from .telemetry import health as _health
from .telemetry import tracer as _tracer

_state = threading.local()
_config = {
    "profile_all": False,
    "profile_imperative": True,
    "profile_memory": False,  # per-op HBM/pool counter events
    "filename": "profile.json",
    "aggregate_stats": False,
    "xla_trace_dir": None,
    "sync": False,  # block per op for accurate durations
}
_events = []
_events_lock = threading.Lock()
_running = False
_xla_running = False
# running peaks across the profiled window (ref: the reference's
# profiler records memory-pool events per device — profiler.cc
# DeviceStats); sampled from PjRt memory_stats + the native staging pool
_mem_peak = {"device_bytes_in_use": 0, "pool_used_bytes": 0}


def set_config(**kwargs):
    """Ref: mx.profiler.set_config(profile_all=True, filename=...)."""
    for k, v in kwargs.items():
        if k in ("profile_symbolic", "profile_api", "continuous_dump"):
            continue  # accepted for parity
        _config[k] = v


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    else:
        stop()


def start():
    global _running, _xla_running
    _running = True
    if _config.get("xla_trace_dir"):
        import jax

        jax.profiler.start_trace(_config["xla_trace_dir"])
        _xla_running = True


def stop():
    global _running, _xla_running
    _running = False
    if _xla_running:
        import jax

        jax.profiler.stop_trace()
        _xla_running = False


def is_running():
    return _running


def _memory_sample():
    """Current device HBM + host staging-pool occupancy, in bytes.

    Device side: PjRt per-device allocator stats (bytes_in_use /
    peak_bytes_in_use — present on TPU, absent on some CPU builds).
    Host side: the native storage pool's counters (src/storage.cc).
    """
    sample = {}
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if k in stats:
                    sample[f"device_{k}"] = int(stats[k])
    except Exception:
        pass
    try:
        from .storage import Storage

        st = Storage.get().stats()
        sample["pool_used_bytes"] = int(st.get("used_bytes", 0))
        if "pool_bytes" in st:
            sample["pool_reserved_bytes"] = int(st["pool_bytes"])
    except Exception:
        pass
    for k in _mem_peak:
        if sample.get(k, 0) > _mem_peak[k]:
            _mem_peak[k] = sample[k]
    return sample


def record_op(name, begin_us, end_us, shapes=None, cat="operator"):
    if not _running:
        return
    mem = _memory_sample() if _config.get("profile_memory") else None
    with _events_lock:
        _events.append({
            "name": name, "ph": "X", "ts": begin_us,
            "dur": max(end_us - begin_us, 0.01),
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
            "cat": cat,
            "args": {"shapes": str(shapes)} if shapes else {},
        })
        if mem:
            # chrome counter track: stacked view of HBM + staging pool
            _events.append({
                "name": "memory", "ph": "C", "ts": end_us,
                "pid": os.getpid(), "cat": "memory", "args": mem,
            })


# Open-scope registry: while armed (the supervisor's watchdog turns it
# on via track_scopes), every entered-but-not-exited op scope is
# visible per thread — how a stalled job names its stuck PHASE (a
# completed-events trace can only name what finished).  One global
# boolean check per scope when disarmed.
_scope_track = False
_scope_lock = threading.Lock()
_open_scopes = {}  # thread ident -> [scope names, innermost last]


def track_scopes(on=True):
    """Arm/disarm open-scope tracking (watchdog diagnostics)."""
    global _scope_track
    _scope_track = bool(on)
    if not on:
        with _scope_lock:
            _open_scopes.clear()


def active_scopes():
    """Snapshot of currently OPEN op scopes per thread; populated only
    while ``track_scopes(True)``."""
    with _scope_lock:
        return {tid: list(stack) for tid, stack in _open_scopes.items()
                if stack}


class _OpScope:
    __slots__ = ("name", "cat", "t0")

    def __init__(self, name, cat="operator"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        if _scope_track:
            with _scope_lock:
                _open_scopes.setdefault(threading.get_ident(),
                                        []).append(self.name)
        # telemetry span hook: the disarmed binding is a ~ns no-op
        # (engine.fault_point pattern); armed, every op scope is a
        # span in the exported trace / flight-recorder ring
        _tracer.span_begin(self.name, self.cat)
        self.t0 = time.perf_counter() * 1e6
        return self

    def __exit__(self, exc_type, *a):
        t1 = time.perf_counter() * 1e6
        record_op(self.name, self.t0, t1, cat=self.cat)
        _tracer.span_end(self.name, self.cat)
        if exc_type is None:
            # health-monitor phase sink (telemetry.health): disarmed
            # it IS the module no-op, same ~ns contract as the tracer
            # hook above; a scope aborted by an exception books no
            # phase time (a failed step is not a completed step)
            _health.scope_end(self.name, self.cat, self.t0, t1)
        if _scope_track:
            with _scope_lock:
                stack = _open_scopes.get(threading.get_ident())
                # entered before arming: nothing of ours to pop
                if stack and stack[-1] == self.name:
                    stack.pop()


def op_scope(name, cat="operator"):
    """Trace bracket; `cat` groups rows in chrome://tracing (checkpoint
    save/restore phases are tagged cat="checkpoint")."""
    return _OpScope(name, cat)


def _graph_cache_counters(reset=False):
    """Compiled-graph cache compile/reuse split (gluon CachedOp) — only
    when the gluon tier is actually loaded; importing it from here would
    drag the whole frontend in for a profiler dump."""
    import sys

    block = sys.modules.get(__package__ + ".gluon.block")
    if block is None:
        return None
    stats = block.cached_graph_stats()
    if reset:
        # a reset dump must scope EVERY section to the window, not mix
        # per-window events with forever-cumulative compile counts
        block.reset_cached_graph_stats()
    return stats


def _trainer_step_counters(reset=False):
    """Step-fusion counters from gluon.Trainer (params_fused,
    buckets_built, dispatches_per_step) — window-scoped under reset=True
    exactly like cachedGraph; only present when the gluon tier is
    loaded."""
    import sys

    trainer = sys.modules.get(__package__ + ".gluon.trainer")
    if trainer is None:
        return None
    stats = trainer.trainer_step_stats()
    if reset:
        trainer.reset_trainer_step_stats()
    return stats


def _data_pipeline_counters(reset=False):
    """Input-pipeline counters (batches, host-build/h2d/wait ms,
    prefetch hit/miss) — window-scoped under reset=True exactly like
    cachedGraph/trainerStep; only present when the pipeline tier is
    loaded."""
    import sys

    pstats = sys.modules.get(__package__ + ".pipeline.stats")
    if pstats is None:
        return None
    stats = pstats.pipeline_stats()
    if reset:
        pstats.reset_pipeline_stats()
    return stats


def _resilience_counters(reset=False):
    """Supervisor/fault-recovery counters (restarts, retries by fault
    class, fallback_restores, watchdog_fires, time_lost_ms, and the
    elastic-resize trio resizes/ranks_lost/reshard_ms) — window-scoped
    under reset=True exactly like cachedGraph/trainerStep/
    dataPipeline; only present when the resilience tier is loaded."""
    import sys

    rstats = sys.modules.get(__package__ + ".resilience.stats")
    if rstats is None:
        return None
    stats = rstats.resilience_stats()
    if reset:
        rstats.reset_resilience_stats()
    return stats


def _decode_serve_counters(reset=False):
    """Continuous-batching decode counters (token steps, tokens,
    prefill batches, admissions, finishes, deadline expiries, slot
    occupancy) — window-scoped under reset=True exactly like every
    other section; only present when the decode serving tier is
    loaded."""
    import sys

    dec = sys.modules.get(__package__ + ".serve.decode")
    if dec is None:
        return None
    stats = dec.decode_serve_stats()
    if reset:
        dec.reset_decode_serve_stats()
    return stats


def _router_counters(reset=False):
    """Serve-router replica-pool counters (dispatches, retries, hedges,
    evictions/replacements, health probes, rolling reloads) —
    window-scoped under reset=True exactly like every other section;
    only present when the routing tier is loaded."""
    import sys

    rt = sys.modules.get(__package__ + ".serve.router")
    if rt is None:
        return None
    stats = rt.router_stats()
    if reset:
        rt.reset_router_stats()
    return stats


def _ctrl_counters(reset=False):
    """Serving control-plane counters (RPC traffic, replica spawn and
    retire churn, autoscaler decisions and the blocked-action tallies)
    — window-scoped under reset=True like every other section; only
    present when the control plane is loaded."""
    import sys

    cp = sys.modules.get(__package__ + ".serve.control_plane")
    if cp is None:
        return None
    stats = cp.ctrl_stats()
    if reset:
        cp.reset_ctrl_stats()
    return stats


def _quantize_counters(reset=False):
    """INT8 quantization counters (layers quantized, calibration
    batches + wall time, requantize folds, compiled int8 serve
    batches) — window-scoped under reset=True exactly like every other
    section; only present when the quantization tier is loaded."""
    import sys

    qz = sys.modules.get(__package__ + ".contrib.quantization")
    if qz is None:
        return None
    stats = qz.quantize_stats()
    if reset:
        qz.reset_quantize_stats()
    return stats


def _health_counters(reset=False):
    """Health-monitor counters (per-step phase breakdown ms, goodput/
    MFU gauges, SLO alerts, straggler flags) — window-scoped under
    reset=True exactly like every other section; only present once a
    HealthMonitor has been armed (telemetry.health)."""
    stats = _health.health_stats()
    if stats is None:
        return None
    if reset:
        _health.reset_health_stats()
    return stats


def _tune_counters(reset=False):
    """Autotuner counters (trials run, recompiles spent, blocked
    restart-class moves, best/baseline ratio) — window-scoped under
    reset=True exactly like every other section; only present when the
    tune subsystem is loaded."""
    import sys

    tune = sys.modules.get(__package__ + ".tune")
    if tune is None:
        return None
    stats = tune.tune_stats()
    if reset:
        tune.reset_tune_stats()
    return stats


def _telemetry_counters(reset=False):
    """Telemetry-subsystem counters (spans/instants/requests recorded,
    drops, flight dumps, scrapes, aggregations) — window-scoped under
    reset=True exactly like every other section."""
    stats = _tracer.telemetry_stats()
    if reset:
        _tracer.reset_telemetry_stats()
    return stats


# ---------------------------------------------------------------------------
# Section registry: every counter section a subsystem contributes to
# dumps()/the aggregate table is one (provider, table renderer) entry
# here.  PRs 2-5 each hand-wired a provider call into BOTH output
# paths and re-fixed the reset forwarding by hand; now both paths
# iterate this registry and the MXA403 invariant pass checks
# membership + reset scoping mechanically.


_sections = []   # [(name, provider, table_fn)] in registration order


def register_section(name, provider, table=None):
    """Register a counter section.

    ``provider(reset=False)`` returns the section's stats dict (or
    None while its subsystem is not loaded) and MUST zero its counters
    under ``reset=True`` — every section is window-scoped, so a reset
    dump never mixes per-window events with forever-cumulative counts.
    ``table(stats)`` (optional) returns the section's lines for
    ``dumps(format="table")``.  Re-registering a name replaces it.
    """
    for i, (n, _p, _t) in enumerate(_sections):
        if n == name:
            _sections[i] = (name, provider, table)
            return
    _sections.append((name, provider, table))


def unregister_section(name):
    """Drop a registered section (tests / unloading subsystems)."""
    _sections[:] = [s for s in _sections if s[0] != name]


def section_names():
    return [n for n, _p, _t in _sections]


def sections(reset=False):
    """Public snapshot of every loaded section: ``{name: stats}`` —
    the dict ``dumps()`` embeds and the /metrics collector exports."""
    return _section_data(reset)


def _section_data(reset=False):
    out = {}
    for name, provider, _table in list(_sections):
        stats = provider(reset)
        if stats is not None:
            out[name] = stats
    return out


def _section_tables(reset=False):
    lines = []
    for _name, provider, table in list(_sections):
        stats = provider(reset)
        if stats is None or table is None:
            continue
        lines.append("")
        lines.extend(table(stats))
    return lines


def _rows_table(title, rows):
    """Standard section renderer: a title plus label/value rows."""
    def render(stats):
        out = [title + ":"]
        for label, key in rows:
            out.append(f"{label:<40}{stats[key]:>12}")
        return out
    return render


def _resilience_table(stats):
    out = ["Resilience (supervisor):"]
    for label, key in (("restarts", "restarts"),
                       ("fallback restores", "fallback_restores"),
                       ("watchdog fires", "watchdog_fires"),
                       ("time lost (ms)", "time_lost_ms"),
                       ("elastic resizes", "resizes"),
                       ("ranks lost", "ranks_lost"),
                       ("reshard (ms)", "reshard_ms")):
        out.append(f"{label:<40}{stats[key]:>12}")
    for cls in sorted(stats["retries"]):
        out.append(f"{'retries[' + cls + ']':<40}"
                   f"{stats['retries'][cls]:>12}")
    return out


register_section("cachedGraph", _graph_cache_counters, _rows_table(
    "Compiled-Graph Cache (CachedOp)",
    (("graph compiles (new signature)", "compiles"),
     ("graph reuses (cache hit)", "reuses"))))
register_section("trainerStep", _trainer_step_counters, _rows_table(
    "Trainer Step Fusion",
    (("steps", "steps"),
     ("params fused", "params_fused"),
     ("allreduce buckets built", "buckets_built"),
     ("dispatches per step", "dispatches_per_step"),
     ("whole-step compiled steps", "whole_step_steps"),
     ("whole-step compiles", "whole_step_compiles"),
     ("whole-step fallbacks", "whole_step_fallbacks"),
     ("zero-sharded steps", "zero_steps"),
     ("zero-shard fallbacks", "zero_fallbacks"),
     ("spmd mesh steps", "spmd_steps"))))
register_section("dataPipeline", _data_pipeline_counters, _rows_table(
    "Data Pipeline",
    (("batches delivered", "batches"),
     ("host build (ms)", "host_build_ms"),
     ("h2d staging (ms)", "h2d_ms"),
     ("step wait-on-input (ms)", "wait_ms"),
     ("prefetch hits", "prefetch_hits"),
     ("prefetch misses", "prefetch_misses"))))
register_section("resilience", _resilience_counters, _resilience_table)
register_section("decodeServe", _decode_serve_counters, _rows_table(
    "Decode Serving (continuous batching)",
    (("decode steps", "steps"),
     ("tokens generated", "tokens"),
     ("prefill batches", "prefill_batches"),
     ("requests admitted", "admitted"),
     ("requests finished", "finished"),
     ("deadline expiries", "expired_deadlines"),
     ("slot occupancy (mean live/max)", "slot_occupancy"),
     ("pages in flight", "pages_in_flight"),
     ("copy-on-write page copies", "cow_copies"),
     ("prefix pages shared (hits)", "prefix_hit_pages"),
     ("draft proposal steps", "draft_steps"),
     ("draft tokens proposed", "spec_proposed"),
     ("draft tokens accepted", "spec_accepted"))))
register_section("router", _router_counters, _rows_table(
    "Serve Router (replica pool)",
    (("requests dispatched", "dispatched"),
     ("re-dispatches (retries)", "retries"),
     ("hedged dispatches", "hedges"),
     ("hedge wins", "hedge_wins"),
     ("replica evictions", "evictions"),
     ("warm replacements admitted", "replacements"),
     ("health probes", "probes"),
     ("health probe failures", "probe_failures"),
     ("rolling-reload legs", "reloads"))))
register_section("ctrl", _ctrl_counters, _rows_table(
    "Serving Control Plane",
    (("autoscaler ticks", "ticks"),
     ("scale-ups", "scale_ups"),
     ("scale-downs", "scale_downs"),
     ("actions blocked by cooldown", "blocked_cooldown"),
     ("actions blocked by bounds", "blocked_bounds"),
     ("replica processes spawned", "spawns"),
     ("replica spawn failures", "spawn_failures"),
     ("replicas drained and retired", "retired"),
     ("rpc requests served", "rpc_requests"),
     ("rpc streams opened", "rpc_streams"),
     ("rpc errors", "rpc_errors"),
     ("stale leases rejected", "stale_leases_rejected"),
     ("pool size (last tick)", "replicas"),
     ("mean occupancy (last tick)", "load"))))
register_section("quantize", _quantize_counters, _rows_table(
    "INT8 Quantization",
    (("layers quantized", "layers_quantized"),
     ("calibration batches", "calib_batches"),
     ("calibration time (ms)", "calib_ms"),
     ("requantize folds", "requant_folds"),
     ("int8 serve batches", "int8_serve_batches"))))
register_section("health", _health_counters, _rows_table(
    "Health Monitor",
    (("steps observed", "steps"),
     ("step time (ms)", "step_ms"),
     ("input wait (ms)", "input_wait_ms"),
     ("h2d staging (ms)", "h2d_ms"),
     ("compute (ms)", "compute_ms"),
     ("collective (ms)", "collective_ms"),
     ("optimizer (ms)", "optimizer_ms"),
     ("checkpoint stall (ms)", "checkpoint_ms"),
     ("compile (ms)", "compile_ms"),
     ("lost to recovery (ms)", "lost_ms"),
     ("monitor ticks", "ticks"),
     ("SLO alerts fired", "alerts"),
     ("stragglers flagged", "stragglers"),
     ("rules firing now", "rules_firing"),
     ("goodput (last window)", "goodput"),
     ("MFU (last window)", "mfu"),
     ("FLOPs per step", "flops_per_step"),
     ("step p95 (ms)", "step_p95_ms"))))
register_section("tune", _tune_counters, _rows_table(
    "Autotuner",
    (("trials run", "trials"),
     ("measurement windows", "measurements"),
     ("recompiles spent", "recompiles_spent"),
     ("candidates cost-model ranked", "candidates_ranked"),
     ("restart-class moves blocked", "blocked_moves"),
     ("knobs moved", "knobs_moved"),
     ("baseline score", "baseline_score"),
     ("best score", "best_score"),
     ("best/baseline ratio", "best_over_baseline"))))
register_section("telemetry", _telemetry_counters, _rows_table(
    "Telemetry (tracer / flight recorder / metrics)",
    (("spans recorded", "spans"),
     ("instant events", "instants"),
     ("request spans opened", "requests"),
     ("events dropped (lane cap)", "dropped"),
     ("flight-recorder dumps", "flight_dumps"),
     ("/metrics scrapes", "scrapes"),
     ("aggregate() calls", "aggregations"))))


def dumps(reset=False, format="json"):
    """Return the trace (ref: mx.profiler.dumps).

    format="json": chrome://tracing event JSON (the default).
    format="table": per-op aggregate summary — name, count, total/min/
    max/avg ms — requires set_config(aggregate_stats=True) like the
    reference's MXAggregateProfileStatsPrint (ref:
    src/profiler/aggregate_stats.cc)."""
    if format == "table":
        if not _config.get("aggregate_stats"):
            raise RuntimeError(
                "aggregate stats not enabled: call "
                "profiler.set_config(aggregate_stats=True) before "
                "profiling (ref: MXAggregateProfileStatsPrint)")
        return _aggregate_table(reset)
    with _events_lock:
        data = {"traceEvents": list(_events),
                "displayTimeUnit": "ms"}
        if _config.get("profile_memory"):
            data["memoryPeaks"] = dict(_mem_peak)
        if reset:
            _events.clear()
    # every registered counter section, reset forwarded so a reset
    # dump window-scopes ALL of them (MXA403 checks this mechanically)
    data.update(_section_data(reset))
    return json.dumps(data)


def _aggregate_table(reset=False):
    """Per-op totals across recorded events, formatted like the
    reference's aggregate stats table (ref: aggregate_stats.cc
    DumpTable: Name / Total Count / Time columns, sorted by total)."""
    with _events_lock:
        events = list(_events)
        if reset:
            _events.clear()
    stats = {}
    for ev in events:
        if "dur" not in ev:  # counter (memory) events have no duration
            continue
        s = stats.setdefault(ev["name"], [0, 0.0, float("inf"), 0.0])
        dur_ms = ev["dur"] / 1000.0
        s[0] += 1
        s[1] += dur_ms
        s[2] = min(s[2], dur_ms)
        s[3] = max(s[3], dur_ms)
    header = (f"{'Name':<40}{'Total Count':>12}{'Total (ms)':>14}"
              f"{'Min (ms)':>12}{'Max (ms)':>12}{'Avg (ms)':>12}")
    lines = ["Profile Statistics:", header, "-" * len(header)]
    for name, (cnt, tot, mn, mx) in sorted(
            stats.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<40}{cnt:>12}{tot:>14.4f}"
                     f"{mn:>12.4f}{mx:>12.4f}{tot / cnt:>12.4f}")
    if _config.get("profile_memory"):
        # memory-pool section (ref: profiler.cc DeviceStats / the
        # reference table's Memory: Device columns)
        lines.append("")
        lines.append("Memory Statistics (peak over profiled window):")
        for key, val in _mem_peak.items():
            lines.append(f"{key:<40}{val / 1e6:>14.3f} MB")
    # counter sections are window-scoped under reset=True exactly like
    # the event table above (and like the JSON format path)
    lines.extend(_section_tables(reset))
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write the trace file (ref: mx.profiler.dump)."""
    with open(_config["filename"], "w") as f:
        f.write(dumps())


def reset():
    with _events_lock:
        _events.clear()
    for k in _mem_peak:
        _mem_peak[k] = 0


def pause(profile_process="worker"):
    global _running
    _running = False


def resume(profile_process="worker"):
    global _running
    _running = True


# env autostart (ref: MXNET_PROFILER_AUTOSTART)
if getenv("PROFILER_AUTOSTART", False, bool):
    _config["profile_all"] = True
    start()
