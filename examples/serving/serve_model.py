"""End-to-end mxnet_tpu.serve demo: dynamic-batching inference.

Builds a small per-position MLP, saves a "trained" checkpoint, starts a
ModelServer on a bucket grid, pushes a mixed-length request stream from
concurrent client threads, hot-reloads weights mid-stream, and prints
the stats snapshot — the compile counters demonstrate the closed
compile surface (zero post-warmup compilations).

    python serve_model.py --cpu --requests 200

See docs/serving.md for the semantics each phase demonstrates.
"""
import argparse
import json
import sys
import threading
import time

import numpy as np


def parse_args():
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--requests", type=int, default=200,
                        help="total requests across all client threads")
    parser.add_argument("--clients", type=int, default=4,
                        help="concurrent submitter threads")
    parser.add_argument("--feat", type=int, default=32,
                        help="fixed feature axis of each request")
    parser.add_argument("--linger-ms", type=float, default=2.0,
                        help="batcher coalescing window")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="optional per-request deadline")
    parser.add_argument("--ckpt-dir", default=None,
                        help="checkpoint dir for the hot-reload phase "
                             "(default: a temp dir)")
    from _common import add_cpu_flag

    add_cpu_flag(parser)
    return parser.parse_args()


def main():
    args = parse_args()
    from _common import apply_backend

    apply_backend(args)

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint, serve
    from mxnet_tpu.gluon import nn

    def make_net(seed):
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(64, flatten=False, in_units=args.feat,
                         activation="relu"),
                nn.Dense(16, flatten=False, in_units=64))
        net.initialize(mx.init.Xavier())
        return net

    # a "trained" model checkpointed by some training job...
    ckpt_dir = args.ckpt_dir
    if ckpt_dir is None:
        import tempfile

        ckpt_dir = tempfile.mkdtemp(prefix="serve_demo_ckpt_")
    mgr = checkpoint.CheckpointManager(ckpt_dir)
    mgr.save(100, params=make_net(seed=7), sync=True)
    mgr.wait_until_finished()

    # ...served by a fresh process that will reload_weights() from it
    net = make_net(seed=1)
    lengths = (8, 16, 32)
    spec = serve.BucketSpec(batch_sizes=(1, 2, 4, 8),
                            example_shape=(None, args.feat),
                            lengths=lengths)
    srv = serve.ModelServer(net, spec, max_queue=args.requests + 8,
                            linger_ms=args.linger_ms, checkpoint=ckpt_dir)
    t0 = time.perf_counter()
    srv.start()  # hybridize + AOT warmup of all 12 buckets
    print(f"warmup: {len(spec.bucket_shapes())} buckets compiled in "
          f"{time.perf_counter() - t0:.2f}s", flush=True)

    # mixed-length traffic from concurrent clients
    per_client = args.requests // args.clients
    outcomes = {"ok": 0, "expired": 0, "rejected": 0}
    lock = threading.Lock()

    def client(seed):
        rng = np.random.RandomState(seed)
        futs = []
        for _ in range(per_client):
            x = rng.rand(int(rng.choice(lengths)),
                         args.feat).astype(np.float32)
            try:
                futs.append(srv.submit(x, deadline_ms=args.deadline_ms))
            except serve.ServerOverloadedError:
                with lock:
                    outcomes["rejected"] += 1
        for f in futs:
            try:
                f.result(timeout=300)
                with lock:
                    outcomes["ok"] += 1
            except serve.DeadlineExceededError:
                with lock:
                    outcomes["expired"] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    for t in threads:
        t.start()
    # hot reload mid-stream: traffic keeps flowing on the old weights
    # until the swap, nothing is dropped, nothing recompiles
    meta = srv.reload_weights()
    print(f"hot-reloaded checkpoint step {meta['step']} mid-stream",
          flush=True)
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0

    srv.drain()
    stats = srv.stats()
    print(json.dumps(stats, indent=2, default=str))
    served = stats["served"]
    print(f"served {served}/{args.requests} requests in {dt:.2f}s "
          f"({served / dt:.0f} req/s), outcomes {outcomes}")
    print(f"p50/p99 latency: {stats['latency']['p50_ms']}/"
          f"{stats['latency']['p99_ms']} ms, batch fill "
          f"{stats['batch_fill_ratio']}")
    compiles = stats["graph"]["post_warmup_compiles"]
    print(f"post-warmup compiles: {compiles}")
    if compiles != 0:
        print("ERROR: the bucket grid did not close the compile surface",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
