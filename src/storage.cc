// Pooled host-staging storage manager.
//
// Ref: src/storage/storage.cc + pooled_storage_manager.h — the
// reference pools GPU/pinned-host memory to avoid cudaMalloc/cudaFree
// on the hot path.  The TPU runtime owns HBM through PjRt, so what the
// framework still allocates at high frequency is HOST staging memory:
// decode buffers, batch assembly, checkpoint scatter/gather.  This
// manager provides the same pooling policies for those buffers:
//
//   * kPooled (default, ref: GPUPooledStorageManager): size-class
//     free-lists, sizes rounded up to the next power of two; freed
//     blocks are recycled, released only on ReleaseAll.
//   * kRoundedMany (ref: GPUPooledRoundedStorageManager): same but
//     keeps at most kMaxPerClass blocks per class to bound waste.
//   * kUnpooled (ref: NaiveStorageManager): malloc/free passthrough,
//     selected with MXTPU_MEM_POOL_TYPE=Unpooled for debugging.
//
// Exposed through a flat C ABI (ref: the MX* C API convention) and
// bound via ctypes in python/mxnet_tpu/storage.py.  Buffers are
// 64-byte aligned so numpy views vectorize and DMA into PjRt
// host-to-device transfers stays aligned.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kAlign = 64;
constexpr size_t kMaxPerClass = 32;

enum PoolType { kPooled = 0, kRoundedMany = 1, kUnpooled = 2 };

size_t RoundPow2(size_t n) {
  if (n < kAlign) return kAlign;
  size_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

struct Pool {
  explicit Pool(int type) : type_(static_cast<PoolType>(type)) {}

  ~Pool() { ReleaseAll(); }

  void* Alloc(size_t nbytes) {
    if (nbytes == 0) return nullptr;
    const size_t rounded =
        type_ == kUnpooled ? nbytes : RoundPow2(nbytes);
    if (type_ != kUnpooled) {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(rounded);
      if (it != free_.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pool_bytes_ -= rounded;
        used_bytes_ += rounded;
        hits_++;
        sizes_[p] = rounded;
        return p;
      }
    }
    void* p = nullptr;
    if (posix_memalign(&p, kAlign, rounded) != 0) {
      // one reclaim attempt before giving up (ref: DirectFreeAll on OOM)
      ReleaseAll();
      if (posix_memalign(&p, kAlign, rounded) != 0) return nullptr;
    }
    std::lock_guard<std::mutex> lk(mu_);
    misses_++;
    used_bytes_ += rounded;
    sizes_[p] = rounded;
    return p;
  }

  void Free(void* p) {
    if (p == nullptr) return;
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it == sizes_.end()) return;  // not ours; ignore
    const size_t rounded = it->second;
    sizes_.erase(it);
    used_bytes_ -= rounded;
    if (type_ == kUnpooled) {
      free(p);
      return;
    }
    auto& bucket = free_[rounded];
    if (type_ == kRoundedMany && bucket.size() >= kMaxPerClass) {
      free(p);
      return;
    }
    bucket.push_back(p);
    pool_bytes_ += rounded;
  }

  void DirectFree(void* p) {
    if (p == nullptr) return;
    std::lock_guard<std::mutex> lk(mu_);
    auto it = sizes_.find(p);
    if (it != sizes_.end()) {
      used_bytes_ -= it->second;
      sizes_.erase(it);
    }
    free(p);
  }

  void ReleaseAll() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : free_) {
      for (void* p : kv.second) free(p);
    }
    free_.clear();
    pool_bytes_ = 0;
  }

  uint64_t used_bytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return used_bytes_;
  }
  uint64_t pool_bytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return pool_bytes_;
  }
  uint64_t hits() {
    std::lock_guard<std::mutex> lk(mu_);
    return hits_;
  }
  uint64_t misses() {
    std::lock_guard<std::mutex> lk(mu_);
    return misses_;
  }

 private:
  PoolType type_;
  std::mutex mu_;
  std::map<size_t, std::vector<void*>> free_;   // size class -> blocks
  std::unordered_map<void*, size_t> sizes_;     // live ptr -> rounded size
  uint64_t used_bytes_ = 0;
  uint64_t pool_bytes_ = 0;
  uint64_t hits_ = 0;
  uint64_t misses_ = 0;
};

}  // namespace

extern "C" {

void* MXTPUStorageCreate(int pool_type) {
  return new (std::nothrow) Pool(pool_type);
}

void MXTPUStorageDestroy(void* h) { delete static_cast<Pool*>(h); }

void* MXTPUStorageAlloc(void* h, uint64_t nbytes) {
  return static_cast<Pool*>(h)->Alloc(nbytes);
}

void MXTPUStorageFree(void* h, void* p) { static_cast<Pool*>(h)->Free(p); }

void MXTPUStorageDirectFree(void* h, void* p) {
  static_cast<Pool*>(h)->DirectFree(p);
}

void MXTPUStorageReleaseAll(void* h) {
  static_cast<Pool*>(h)->ReleaseAll();
}

uint64_t MXTPUStorageUsedBytes(void* h) {
  return static_cast<Pool*>(h)->used_bytes();
}

uint64_t MXTPUStoragePoolBytes(void* h) {
  return static_cast<Pool*>(h)->pool_bytes();
}

uint64_t MXTPUStorageHits(void* h) { return static_cast<Pool*>(h)->hits(); }

uint64_t MXTPUStorageMisses(void* h) {
  return static_cast<Pool*>(h)->misses();
}

}  // extern "C"
