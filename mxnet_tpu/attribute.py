"""Attribute scoping (ref: python/mxnet/attribute.py — AttrScope's
canonical home; also exported as mx.AttrScope)."""
from .symbol.symbol import AttrScope  # noqa: F401
