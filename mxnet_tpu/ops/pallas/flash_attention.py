"""Flash attention Pallas kernel for TPU.

Ref capability: the reference has NO fused attention op (SURVEY §2.2
"no fused attention op in this era") — transformers are composed from
batch_dot + softmax, materializing the (S,S) score matrix in HBM.  This
kernel is the capability upgrade the survey prescribes: online-softmax
blockwise attention that keeps scores in VMEM, MXU-aligned 128-tiles.

Forward = Pallas kernel; backward = recompute via the XLA reference
(jax.custom_vjp) — the standard memory/flops trade (flash bwd kernel is
a later optimization; the VJP recompute is already O(S) memory because
XLA fuses the recomputation blockwise under remat).

Falls back transparently when seq/head dims don't tile (caller guards).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e9


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                      scale, seq_k):
    # refs carry a leading block dim of 1: (1, block_q, d) / (1, seq_k, d)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)  # q-block index

    q = q_ref[0] * scale
    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only k-blocks at or before this q-block contribute
        max_kb = jnp.minimum(((qi + 1) * block_q + block_k - 1) // block_k,
                             num_kb)
        m, l, acc = jax.lax.fori_loop(0, max_kb, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal, scale, block_q=128, block_k=128):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, sq, d)
    k3 = k.reshape(bh, sk, d)
    v3 = v.reshape(bh, sk, d)

    grid = (bh, sq // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k,
                          causal=causal, scale=scale, seq_k=sk),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
    )(q3, k3, v3)
    return out.reshape(b, h, sq, d)


def _tiles_ok(q, k, block_q=128, block_k=128):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    return (sq % block_q == 0 and sk % block_k == 0 and d % 128 == 0
            and sq >= block_q and sk >= block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_sdpa(q, k, v, causal, scale):
    return _flash_forward(q, k, v, causal=causal, scale=scale)


def _flash_sdpa_fwd(q, k, v, causal, scale):
    return _flash_forward(q, k, v, causal=causal, scale=scale), (q, k, v)


def _flash_sdpa_bwd(causal, scale, res, g):
    from ..attention import sdpa_reference

    q, k, v = res
    # recompute-based VJP through the XLA reference (numerically matches
    # the kernel; scores never fully materialized thanks to XLA blocking
    # under remat)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: sdpa_reference(q_, k_, v_, None, scale=scale,
                                          causal=causal), q, k, v)
    return vjp(g)


_flash_sdpa.defvjp(_flash_sdpa_fwd, _flash_sdpa_bwd)


def flash_attention(q, k, v, mask=None, scale=None, causal=False):
    """Fused attention; q,k,v: (batch, heads, seq, head_dim).

    Additive/bool masks and unaligned shapes fall back to the XLA
    reference (the caller treats this function as best-effort)."""
    from ..attention import sdpa_reference

    if mask is not None or not _tiles_ok(q, k):
        return sdpa_reference(q, k, v, mask, scale=scale, causal=causal)
    s = float(scale) if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_sdpa(q, k, v, bool(causal), s)
