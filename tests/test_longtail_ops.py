"""Long-tail operator tests: linalg family, vision ops (ROI/sampler/
transformer/correlation), multi-tensor ops, control flow, and the
self-documenting parameter descriptors (ref: tests/python/unittest/
test_operator.py sections + dmlc parameter.h doc behavior)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import check_numeric_gradient


# -- linalg -----------------------------------------------------------------

def test_linalg_gemm_family():
    rng = np.random.RandomState(0)
    A = rng.rand(2, 3, 4).astype(np.float32)
    B = rng.rand(2, 4, 5).astype(np.float32)
    C = rng.rand(2, 3, 5).astype(np.float32)
    out = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5)
    np.testing.assert_allclose(out.asnumpy(), 2 * (A @ B) + 0.5 * C,
                               rtol=1e-5)
    out2 = nd.linalg_gemm2(nd.array(A), nd.array(B))
    np.testing.assert_allclose(out2.asnumpy(), A @ B, rtol=1e-5)
    # transpose flags
    out3 = nd.linalg_gemm2(nd.array(A), nd.array(A), transpose_b=True)
    np.testing.assert_allclose(out3.asnumpy(),
                               A @ A.transpose(0, 2, 1), rtol=1e-5)


def test_linalg_potrf_potri_trsm():
    rng = np.random.RandomState(1)
    M = rng.rand(3, 3).astype(np.float32)
    A = M @ M.T + 3 * np.eye(3, dtype=np.float32)  # SPD
    L = nd.linalg_potrf(nd.array(A))
    np.testing.assert_allclose((L.asnumpy() @ L.asnumpy().T), A,
                               rtol=1e-4, atol=1e-4)
    Ainv = nd.linalg_potri(L)
    np.testing.assert_allclose(Ainv.asnumpy(), np.linalg.inv(A),
                               rtol=1e-3, atol=1e-4)
    B = rng.rand(3, 2).astype(np.float32)
    X = nd.linalg_trsm(L, nd.array(B))
    np.testing.assert_allclose(L.asnumpy() @ X.asnumpy(), B,
                               rtol=1e-4, atol=1e-5)
    # triangular matmul inverts trsm
    back = nd.linalg_trmm(L, X)
    np.testing.assert_allclose(back.asnumpy(), B, rtol=1e-4, atol=1e-5)


def test_linalg_syrk_diag_det():
    rng = np.random.RandomState(2)
    A = rng.rand(4, 3).astype(np.float32)
    np.testing.assert_allclose(nd.linalg_syrk(nd.array(A)).asnumpy(),
                               A @ A.T, rtol=1e-5)
    M = rng.rand(3, 3).astype(np.float32) + 2 * np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(
        nd.linalg_sumlogdiag(nd.array(M)).asnumpy(),
        np.log(np.diag(M)).sum(), rtol=1e-5)
    v = rng.rand(4).astype(np.float32)
    np.testing.assert_allclose(nd.linalg_makediag(nd.array(v)).asnumpy(),
                               np.diag(v))
    np.testing.assert_allclose(
        nd.linalg_extractdiag(nd.array(np.diag(v))).asnumpy(), v)
    np.testing.assert_allclose(nd.linalg_det(nd.array(M)).asnumpy(),
                               np.linalg.det(M), rtol=1e-4)
    sign, logdet = nd.linalg_slogdet(nd.array(M))
    s_ref, l_ref = np.linalg.slogdet(M)
    np.testing.assert_allclose(sign.asnumpy(), s_ref)
    np.testing.assert_allclose(logdet.asnumpy(), l_ref, rtol=1e-4)


def test_linalg_syevd_and_trian_pack():
    rng = np.random.RandomState(3)
    M = rng.rand(4, 4).astype(np.float32)
    S = (M + M.T) / 2
    U, lam = nd.linalg_syevd(nd.array(S))
    # A = U^T diag(lam) U (row-eigenvector convention)
    recon = U.asnumpy().T @ np.diag(lam.asnumpy()) @ U.asnumpy()
    np.testing.assert_allclose(recon, S, rtol=1e-3, atol=1e-4)
    packed = nd.linalg_extracttrian(nd.array(S))
    back = nd.linalg_maketrian(packed)
    np.testing.assert_allclose(np.tril(back.asnumpy()), np.tril(S),
                               rtol=1e-5)


def test_linalg_grad_flows():
    """linalg ops differentiate via jax autodiff (ref hand-writes these
    backwards in la_op-inl.h)."""
    rng = np.random.RandomState(4)
    A = nd.array(rng.rand(3, 3).astype(np.float32)
                 + 2 * np.eye(3, dtype=np.float32))
    A.attach_grad()
    with autograd.record():
        L = nd.linalg_potrf(A)
        loss = nd.linalg_sumlogdiag(L)  # = 0.5 * logdet(A)
    loss.backward()
    # d(0.5 logdet A)/dA = 0.5 A^-T
    ref = 0.5 * np.linalg.inv(A.asnumpy()).T
    got = A.grad.asnumpy()
    # cholesky VJP yields the symmetrized gradient (same as reference's
    # copy-lower convention differences): compare symmetrized forms
    np.testing.assert_allclose(got + got.T, ref + ref.T,
                               rtol=1e-2, atol=2e-3)


# -- vision -----------------------------------------------------------------

def test_bilinear_sampler_identity_and_shift():
    rng = np.random.RandomState(5)
    img = rng.rand(1, 1, 4, 4).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].astype(np.float32)  # identity
    out = nd.BilinearSampler(nd.array(img), nd.array(grid))
    np.testing.assert_allclose(out.asnumpy(), img, rtol=1e-5, atol=1e-6)
    # shift one pixel right: out[..., :-1] == img[..., 1:]
    grid_sh = grid.copy()
    grid_sh[:, 0] += 2.0 / 3.0  # one pixel in x (W-1=3)
    out2 = nd.BilinearSampler(nd.array(img), nd.array(grid_sh))
    np.testing.assert_allclose(out2.asnumpy()[..., :-1],
                               img[..., 1:], rtol=1e-4, atol=1e-5)
    # out-of-range samples are zero
    assert np.allclose(out2.asnumpy()[..., -1], 0, atol=1e-6)


def test_spatial_transformer_identity():
    rng = np.random.RandomState(6)
    img = rng.rand(2, 3, 5, 5).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    out = nd.SpatialTransformer(nd.array(img), nd.array(theta),
                                target_shape=(5, 5))
    np.testing.assert_allclose(out.asnumpy(), img, rtol=1e-4, atol=1e-5)
    # grid generator affine identity == base grid
    g = nd.GridGenerator(nd.array(theta), transform_type="affine",
                         target_shape=(3, 3))
    assert g.shape == (2, 2, 3, 3)
    np.testing.assert_allclose(g.asnumpy()[0, 0, 0],
                               [-1, 0, 1], atol=1e-6)


def test_roi_pooling():
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)  # whole image
    out = nd.ROIPooling(nd.array(data), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=1.0)
    np.testing.assert_allclose(out.asnumpy()[0, 0],
                               [[5, 7], [13, 15]])
    # scaled roi: top-left quadrant only
    rois2 = np.array([[0, 0, 0, 2, 2]], np.float32)
    out2 = nd.ROIPooling(nd.array(data), nd.array(rois2),
                         pooled_size=(1, 1), spatial_scale=0.5)
    # coords round to [0, 1]: max over rows 0-1 x cols 0-1 = 5
    np.testing.assert_allclose(out2.asnumpy()[0, 0], [[5]])


def test_correlation_self_peak():
    """Correlating a map with itself peaks at zero displacement."""
    rng = np.random.RandomState(7)
    x = rng.rand(1, 4, 6, 6).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1, is_multiply=True)
    o = out.asnumpy()[0]          # (9, Ho, Wo)
    # autocorrelation: the SPATIAL MEAN is maximized at zero displacement
    # (pointwise it need not be, by Cauchy-Schwarz)
    means = o.mean(axis=(1, 2))
    assert means.argmax() == 4, means


def test_vision_ops_grad_flow():
    rng = np.random.RandomState(8)
    img = nd.array(rng.rand(1, 2, 4, 4).astype(np.float32))
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = nd.array(np.stack([xs, ys])[None].astype(np.float32))
    img.attach_grad()
    grid.attach_grad()
    with autograd.record():
        out = nd.BilinearSampler(img, grid)
        loss = (out * out).sum()
    loss.backward()
    assert np.abs(img.grad.asnumpy()).sum() > 0
    assert img.grad.shape == img.shape


# -- multi-tensor -----------------------------------------------------------

def test_multi_sum_sq_and_sgd():
    rng = np.random.RandomState(9)
    ws = [rng.rand(3, 2).astype(np.float32) for _ in range(3)]
    gs = [rng.rand(3, 2).astype(np.float32) for _ in range(3)]
    ss = nd.multi_sum_sq(*[nd.array(w) for w in ws], num_arrays=3)
    np.testing.assert_allclose(ss.asnumpy(),
                               [np.sum(w * w) for w in ws], rtol=1e-5)
    flat = []
    for w, g in zip(ws, gs):
        flat += [nd.array(w), nd.array(g)]
    outs = nd.multi_sgd_update(*flat, lrs=(0.1, 0.2, 0.3),
                               wds=(0.0, 0.0, 0.1), num_weights=3)
    for i, (w, g) in enumerate(zip(ws, gs)):
        lr = (0.1, 0.2, 0.3)[i]
        wd = (0.0, 0.0, 0.1)[i]
        np.testing.assert_allclose(outs[i].asnumpy(),
                                   w - lr * (g + wd * w), rtol=1e-5)
    # momentum variant returns updated weights then momenta
    flat3 = []
    for w, g in zip(ws, gs):
        flat3 += [nd.array(w), nd.array(g), nd.zeros(w.shape)]
    outs3 = nd.multi_sgd_mom_update(*flat3, lrs=(0.1,) * 3,
                                    wds=(0.0,) * 3, momentum=0.9,
                                    num_weights=3)
    np.testing.assert_allclose(outs3[0].asnumpy(), ws[0] - 0.1 * gs[0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs3[3].asnumpy(), -0.1 * gs[0],
                               rtol=1e-5)


# -- control flow -----------------------------------------------------------

def test_foreach_cumsum():
    data = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    init = nd.zeros((2,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.contrib.foreach(body, data, init)
    np.testing.assert_allclose(final.asnumpy(), [6, 9])
    np.testing.assert_allclose(outs.asnumpy(),
                               [[0, 1], [2, 4], [6, 9]])


def test_while_loop_counts():
    def cond(i, s):
        return i < 3

    def func(i, s):
        return s + i, (i + 1, s + i)

    outs, (i_fin, s_fin) = nd.contrib.while_loop(
        cond, func, [nd.array([0.0]), nd.array([0.0])],
        max_iterations=5)
    assert float(i_fin.asscalar()) == 3
    assert float(s_fin.asscalar()) == 3  # 0+1+2
    np.testing.assert_allclose(outs.asnumpy().ravel(),
                               [0, 1, 3, 0, 0])


def test_cond_selects_branch():
    x = nd.array([2.0])
    out_t = nd.contrib.cond(nd.array([1.0]),
                            lambda: x * 10, lambda: x - 1)
    np.testing.assert_allclose(out_t.asnumpy(), [20.0])
    out_f = nd.contrib.cond(nd.array([0.0]),
                            lambda: x * 10, lambda: x - 1)
    np.testing.assert_allclose(out_f.asnumpy(), [1.0])


# -- parameter descriptors --------------------------------------------------

def test_op_docstrings_self_document():
    """help(mx.nd.Convolution) shows typed params with defaults/docs
    (the dmlc parameter.h auto-doc feature, VERDICT missing #6)."""
    doc = nd.Convolution.__doc__
    assert "Parameters" in doc
    assert "kernel : tuple" in doc and "required" in doc
    assert "num_group : int" in doc and "default=1" in doc
    # introspection fallback covers ops without explicit descriptors
    doc2 = nd.linalg_gemm2.__doc__
    assert "transpose_a" in doc2 and "default=False" in doc2


def test_op_param_validation():
    x = nd.ones((1, 1, 4, 4))
    with pytest.raises(mx.MXNetError):
        nd.Activation(x, act_type="bogus")
    with pytest.raises(mx.MXNetError):
        nd.Pooling(x, kernel=(2, 2), pool_type="median")
    with pytest.raises(mx.MXNetError):
        nd.Dropout(x, p=1.5)
    # valid calls still work
    assert nd.Activation(x, act_type="relu").shape == x.shape


def test_check_numeric_gradient_linalg():
    rng = np.random.RandomState(11)
    A = rng.rand(3, 3).astype(np.float64) + 2 * np.eye(3)

    def f(a):
        return nd.linalg_syrk(a)

    check_numeric_gradient(f, [nd.array(A.astype(np.float32))],
                           rtol=1e-2, atol=1e-3)


# ---------------------------------------------------------------------------
# round-2 audit additions: cumsum/fix/batch_take/ravel/unravel/Crop/SVMOutput


def test_cumsum():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_allclose(nd.cumsum(nd.array(x), axis=1).asnumpy(),
                               np.cumsum(x, axis=1))
    np.testing.assert_allclose(nd.cumsum(nd.array(x)).asnumpy(),
                               np.cumsum(x))


def test_fix_rounds_toward_zero():
    x = np.array([-1.7, -0.5, 0.5, 1.7], np.float32)
    np.testing.assert_array_equal(nd.fix(nd.array(x)).asnumpy(),
                                  np.fix(x))


def test_batch_take():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    idx = np.array([1, 3, 0], np.float32)
    out = nd.batch_take(nd.array(a), nd.array(idx)).asnumpy()
    np.testing.assert_array_equal(out, a[np.arange(3), idx.astype(int)])


def test_ravel_unravel_roundtrip():
    shape = (4, 5, 6)
    coords = np.array([[1, 3, 0], [4, 0, 2], [5, 1, 3]], np.int64)
    flat = nd.ravel_multi_index(nd.array(coords.astype(np.float32)),
                                shape=shape).asnumpy()
    expect = np.ravel_multi_index(tuple(coords), shape)
    np.testing.assert_array_equal(flat.astype(np.int64), expect)
    back = nd.unravel_index(nd.array(flat), shape=shape).asnumpy()
    np.testing.assert_array_equal(back.astype(np.int64), coords)


def test_crop_legacy():
    x = np.arange(2 * 3 * 6 * 8, dtype=np.float32).reshape(2, 3, 6, 8)
    out = nd.Crop(nd.array(x), offset=(1, 2), h_w=(4, 5)).asnumpy()
    np.testing.assert_array_equal(out, x[:, :, 1:5, 2:7])
    cc = nd.Crop(nd.array(x), h_w=(4, 4), center_crop=True).asnumpy()
    np.testing.assert_array_equal(cc, x[:, :, 1:5, 2:6])


def test_svm_output_forward_and_grad():
    from mxnet_tpu import autograd

    scores = np.array([[2.0, 1.0, 0.5], [0.0, 3.0, 2.9]], np.float32)
    label = np.array([0, 1], np.float32)
    s = nd.array(scores)
    s.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(s, nd.array(label), margin=1.0)
    # forward is identity on the scores
    np.testing.assert_array_equal(out.asnumpy(), scores)
    out.backward()
    g = s.grad.asnumpy()
    # row 0: class1 violates (1 - (2-1) = 0, not > 0) -> no violation;
    # class2: 1 - (2-0.5) = -0.5 -> none; grad row 0 all zero
    assert np.allclose(g[0], 0.0), g
    # row 1: class2 violates (1 - (3-2.9) = 0.9 > 0); class0: 1-3 < 0
    assert g[1, 2] > 0 and g[1, 1] < 0 and g[1, 0] == 0, g
    assert np.isclose(g[1].sum(), 0.0), g  # hinge grads balance


def test_ravel_large_indices_no_float_corruption():
    """flat indices past float32's 2^24 mantissa must stay exact
    (regression: float-dtype stride math corrupted them)."""
    shape = (3000, 3000, 3)
    coords = np.array([[2999], [2999], [2]], np.int32)
    flat = nd.ravel_multi_index(nd.array(coords, dtype=np.int32),
                                shape=shape).asnumpy()
    assert int(flat[0]) == 26999999, flat
    back = nd.unravel_index(nd.array([26999999], dtype=np.int32),
                            shape=shape).asnumpy()
    np.testing.assert_array_equal(back.astype(np.int64).reshape(-1),
                                  [2999, 2999, 2])


def test_unravel_index_nd_input():
    flat = np.array([[5, 23], [11, 0]], np.float32)
    out = nd.unravel_index(nd.array(flat), shape=(4, 6)).asnumpy()
    expect = np.stack(np.unravel_index(flat.astype(np.int64), (4, 6)))
    assert out.shape == (2, 2, 2)
    np.testing.assert_array_equal(out.astype(np.int64), expect)


def test_crop_out_of_bounds_raises():
    import pytest

    x = nd.zeros((1, 1, 6, 8))
    with pytest.raises(Exception, match="out of bounds"):
        nd.Crop(x, offset=(4, 0), h_w=(4, 8))
    with pytest.raises(Exception, match="out of bounds"):
        nd.Crop(x, offset=(-1, 0), h_w=(2, 2))


def test_special_gamma_family():
    """gamma/gammaln/digamma against scipy (ref: unary special ops)."""
    from scipy import special as sp

    x = np.array([0.5, 1.0, 2.5, 4.0], np.float32)
    np.testing.assert_allclose(nd.gamma(nd.array(x)).asnumpy(),
                               sp.gamma(x), rtol=1e-5)
    np.testing.assert_allclose(nd.gammaln(nd.array(x)).asnumpy(),
                               sp.gammaln(x), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nd.digamma(nd.array(x)).asnumpy(),
                               sp.digamma(x), atol=1e-5)


def test_choose_element_0d_alias():
    """Legacy alias of pick (ref: choose_element_0d, mshadow-era)."""
    x = nd.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    idx = nd.array([2.0, 0.0])
    np.testing.assert_allclose(
        nd.choose_element_0d(x, idx).asnumpy(), [3.0, 4.0])


def test_pick_mode_clip_and_wrap():
    x = nd.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    oob = nd.array([5.0, -7.0])
    np.testing.assert_allclose(  # clip (default): [2->2, -7->0]
        nd.pick(x, oob).asnumpy(), [3.0, 4.0])
    np.testing.assert_allclose(  # wrap: 5%3=2, -7%3=2
        nd.pick(x, oob, mode="wrap").asnumpy(), [3.0, 6.0])


def test_pick_method_and_bad_mode():
    x = nd.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    np.testing.assert_allclose(  # method API forwards mode
        x.pick(nd.array([5.0, -7.0]), mode="wrap").asnumpy(), [3.0, 6.0])
    with pytest.raises(mx.MXNetError, match="clip"):
        nd.pick(x, nd.array([0.0, 1.0]), mode="warp")
