"""Optimizer classes added for 1.x parity: Adamax / Nadam / SGLD /
DCASGD / Ftml (ref: python/mxnet/optimizer/optimizer.py) — 3-step
numpy-oracle trajectories through the real Optimizer.update path."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt


def _run(o, grads, w0):
    w = nd.array(w0.copy())
    state = o.create_state(0, w)
    for g in grads:
        o.update(0, w, nd.array(g), state)
    return w.asnumpy()


def _data(steps=3, n=5, seed=0):
    rng = np.random.RandomState(seed)
    w0 = rng.randn(n).astype(np.float32)
    grads = [rng.randn(n).astype(np.float32) for _ in range(steps)]
    return w0, grads


def test_adamax_oracle():
    w0, grads = _data()
    lr, b1, b2, eps = 0.002, 0.9, 0.999, 1e-8
    got = _run(opt.create("adamax", learning_rate=lr, wd=0.0), grads, w0)
    w, m, u = w0.copy(), 0.0, 0.0
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        u = np.maximum(b2 * u, np.abs(g))
        w = w - (lr / (1 - b1 ** t)) * m / (u + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_nadam_oracle():
    w0, grads = _data()
    lr, b1, b2, eps, sd = 0.001, 0.9, 0.999, 1e-8, 0.004
    got = _run(opt.create("nadam", learning_rate=lr, wd=0.0), grads, w0)
    w, m, v, msched = w0.copy(), 0.0, 0.0, 1.0
    for t, g in enumerate(grads, 1):
        mom_t = b1 * (1 - 0.5 * 0.96 ** (t * sd))
        mom_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * sd))
        msched = msched * mom_t
        msched_next = msched * mom_t1
        gp = g / (1 - msched)
        m = b1 * m + (1 - b1) * g
        mp = m / (1 - msched_next)
        v = b2 * v + (1 - b2) * g * g
        vp = v / (1 - b2 ** t)
        mbar = (1 - mom_t) * gp + mom_t1 * mp
        w = w - lr * mbar / (np.sqrt(vp) + eps)
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_sgld_noise_and_determinism():
    w0, grads = _data()
    mx.random.seed(7)
    got1 = _run(opt.create("sgld", learning_rate=0.01, wd=0.0), grads, w0)
    mx.random.seed(7)
    got2 = _run(opt.create("sgld", learning_rate=0.01, wd=0.0), grads, w0)
    np.testing.assert_allclose(got1, got2)  # seeded → reproducible
    assert np.isfinite(got1).all()
    # with lr→0 the update vanishes (both grad and noise terms scale)
    mx.random.seed(7)
    tiny = _run(opt.create("sgld", learning_rate=1e-12, wd=0.0), grads, w0)
    np.testing.assert_allclose(tiny, w0, atol=1e-4)


def test_dcasgd_oracle():
    w0, grads = _data()
    lr, mom_c, lam = 0.01, 0.9, 0.04
    got = _run(opt.create("dcasgd", learning_rate=lr, momentum=mom_c,
                          lamda=lam, wd=0.0), grads, w0)
    w, mom, prev = w0.copy(), 0.0, w0.copy()
    for g in grads:
        mom = mom_c * mom - lr * (g + lam * g * g * (w - prev))
        w = w + mom
        prev = w.copy()
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_ftml_oracle():
    w0, grads = _data()
    lr, b1, b2, eps = 0.0025, 0.6, 0.999, 1e-8
    got = _run(opt.create("ftml", learning_rate=lr, wd=0.0), grads, w0)
    w, d, v, z = w0.copy(), 0.0, 0.0, 0.0
    for t, g in enumerate(grads, 1):
        v = b2 * v + (1 - b2) * g * g
        d_new = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
        sigma = d_new - b1 * d
        z = b1 * z + (1 - b1) * g - sigma * w
        w = -z / d_new
        d = d_new
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_adamax_wd_clip_order():
    """Reference python tier folds wd in BEFORE clipping."""
    w0, grads = _data()
    lr, b1, b2, eps, wd, clip = 0.002, 0.9, 0.999, 1e-8, 0.5, 0.3
    got = _run(opt.create("adamax", learning_rate=lr, wd=wd,
                          clip_gradient=clip), grads, w0)
    w, m, u = w0.copy(), 0.0, 0.0
    for t, g in enumerate(grads, 1):
        gp = np.clip(g + wd * w, -clip, clip)
        m = b1 * m + (1 - b1) * gp
        u = np.maximum(b2 * u, np.abs(gp))
        w = w - (lr / (1 - b1 ** t)) * m / (u + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_dcasgd_wd_outside_square():
    """wd*w enters the update; the g^2 compensation uses bare grad."""
    w0, grads = _data()
    lr, mom_c, lam, wd, clip = 0.01, 0.9, 0.04, 0.5, 0.3
    got = _run(opt.create("dcasgd", learning_rate=lr, momentum=mom_c,
                          lamda=lam, wd=wd, clip_gradient=clip),
               grads, w0)
    w, mom, prev = w0.copy(), 0.0, w0.copy()
    for g in grads:
        gp = np.clip(g, -clip, clip)
        mom = mom_c * mom - lr * (gp + wd * w
                                  + lam * gp * gp * (w - prev))
        w = w + mom
        prev = w.copy()
    np.testing.assert_allclose(got, w, rtol=1e-5)


def test_ftml_wd_clip_order():
    w0, grads = _data()
    lr, b1, b2, eps, wd, clip = 0.0025, 0.6, 0.999, 1e-8, 0.5, 0.3
    got = _run(opt.create("ftml", learning_rate=lr, wd=wd,
                          clip_gradient=clip), grads, w0)
    w, d, v, z = w0.copy(), 0.0, 0.0, 0.0
    for t, g in enumerate(grads, 1):
        gp = np.clip(g + wd * w, -clip, clip)
        v = b2 * v + (1 - b2) * gp * gp
        d_new = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
        sigma = d_new - b1 * d
        z = b1 * z + (1 - b1) * gp - sigma * w
        w = -z / d_new
        d = d_new
    np.testing.assert_allclose(got, w, rtol=1e-4)


def test_new_optimizers_drive_training():
    """Each new optimizer reduces loss on a tiny least-squares task."""
    rng = np.random.RandomState(3)
    X = rng.randn(64, 6).astype(np.float32)
    true_w = rng.randn(6).astype(np.float32)
    y = X @ true_w

    for name, kw in (("adamax", {"learning_rate": 0.05}),
                     ("nadam", {"learning_rate": 0.05}),
                     ("dcasgd", {"learning_rate": 0.01}),
                     ("ftml", {"learning_rate": 0.05})):
        o = opt.create(name, wd=0.0, **kw)
        w = nd.zeros((6,))
        state = o.create_state(0, w)

        def loss_grad(wv):
            r = X @ wv - y
            return float((r * r).mean()), (2 / len(y)) * (X.T @ r)

        l0, _ = loss_grad(w.asnumpy())
        for _ in range(60):
            _, g = loss_grad(w.asnumpy())
            o.update(0, w, nd.array(g.astype(np.float32)), state)
        l1, _ = loss_grad(w.asnumpy())
        assert l1 < l0 * 0.5, f"{name}: {l0} -> {l1}"


def test_group_adagrad_oracle():
    """Row-wise AdaGrad (ref: mx.optimizer.contrib.GroupAdaGrad)."""
    rng = np.random.RandomState(1)
    w0 = rng.randn(4, 3).astype(np.float32)
    grads = [rng.randn(4, 3).astype(np.float32) for _ in range(3)]
    lr, eps = 0.01, 1e-5
    o = opt.create("groupadagrad", learning_rate=lr, epsilon=eps)
    w = nd.array(w0.copy())
    state = o.create_state(0, w)
    assert state.shape == (4, 1)  # one accumulator per row
    for g in grads:
        o.update(0, w, nd.array(g), state)
    wr, h = w0.copy(), np.zeros((4, 1), np.float32)
    for g in grads:
        h = h + np.mean(g * g, axis=1, keepdims=True)
        wr = wr - lr * g / (np.sqrt(h) + eps)
    np.testing.assert_allclose(w.asnumpy(), wr, rtol=1e-5)
