"""Detection image iterator + box-aware augmenters.

Ref: python/mxnet/image/detection.py — `ImageDetIter`,
`DetHorizontalFlipAug`, `DetRandomCropAug`, `DetBorrowAug`,
`CreateDetAugmenter`. Labels are per-image 2-D float arrays
`(num_obj, obj_width)` with `[cls, xmin, ymin, xmax, ymax, ...]` in
normalized [0,1] coordinates; the packed on-disk layout (lst and
recordio) is `[header_width, obj_width, <header...>, obj0..., ...]`
exactly as `tools/im2rec.py --pack-label` writes it.
"""
from __future__ import annotations

import os

import numpy as np

from ..base import MXNetError
from ..ndarray import ndarray as _nd
from .image import (Augmenter, CastAug, ColorJitterAug, HueJitterAug,
                    LightingAug, RandomGrayAug, imread, imresize)


class DetAugmenter:
    """Augmenter operating on (image, label) pairs."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the det pipeline
    (ref: mx.image.DetBorrowAug)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug needs an image Augmenter")
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter (or skip) — how the reference turns
    rand_crop/rand_pad fractions into probabilities
    (ref: mx.image.DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or np.random.random() < self.skip_prob:
            return src, label
        return self.aug_list[np.random.randint(len(self.aug_list))](
            src, label)


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding; boxes shrink into the new canvas
    (ref: mx.image.DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = (max(area_range[0], 1.0), max(area_range[1], 1.0))
        self.max_attempts = max_attempts
        self.pad_val = np.asarray(pad_val, np.float32)

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        for _ in range(self.max_attempts):
            area = np.random.uniform(*self.area_range)
            ar = np.random.uniform(*self.aspect_ratio_range)
            nw, nh = int(w * np.sqrt(area * ar)), int(h * np.sqrt(area / ar))
            if nw >= w and nh >= h:
                break
        else:
            return src, label
        x0 = np.random.randint(0, nw - w + 1)
        y0 = np.random.randint(0, nh - h + 1)
        arr = src.asnumpy()
        canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
        canvas[:] = self.pad_val.astype(arr.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = arr
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * w + x0) / nw
        out[valid, 3] = (out[valid, 3] * w + x0) / nw
        out[valid, 2] = (out[valid, 2] * h + y0) / nh
        out[valid, 4] = (out[valid, 4] * h + y0) / nh
        return _nd.array(canvas), out


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates (ref: mx.image.DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if np.random.random() < self.p:
            src = src.flip(axis=1)
            label = label.copy()
            valid = label[:, 0] >= 0
            xmin = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - xmin
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping objects whose center survives, with IoU-style
    coverage constraint (ref: mx.image.DetRandomCropAug)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _coverage(self, boxes, crop):
        x0, y0, x1, y1 = crop
        ix0 = np.maximum(boxes[:, 0], x0)
        iy0 = np.maximum(boxes[:, 1], y0)
        ix1 = np.minimum(boxes[:, 2], x1)
        iy1 = np.minimum(boxes[:, 3], y1)
        inter = np.clip(ix1 - ix0, 0, None) * np.clip(iy1 - iy0, 0, None)
        area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        return inter / np.maximum(area, 1e-12)

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        valid = label[:, 0] >= 0
        boxes = label[valid, 1:5]
        for _ in range(self.max_attempts):
            area = np.random.uniform(*self.area_range)
            ar = np.random.uniform(*self.aspect_ratio_range)
            cw = min(np.sqrt(area * ar), 1.0)
            ch = min(np.sqrt(area / ar), 1.0)
            cx = np.random.uniform(0, 1.0 - cw)
            cy = np.random.uniform(0, 1.0 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if boxes.size == 0:
                break
            cov = self._coverage(boxes, crop)
            centers_x = (boxes[:, 0] + boxes[:, 2]) / 2
            centers_y = (boxes[:, 1] + boxes[:, 3]) / 2
            keep = ((centers_x > crop[0]) & (centers_x < crop[2])
                    & (centers_y > crop[1]) & (centers_y < crop[3]))
            if keep.any() and cov[keep].min() >= self.min_object_covered:
                break
        else:
            return src, label  # no acceptable crop found
        x0, y0 = int(crop[0] * w), int(crop[1] * h)
        cw_px = max(int((crop[2] - crop[0]) * w), 1)
        ch_px = max(int((crop[3] - crop[1]) * h), 1)
        from .image import fixed_crop

        src = fixed_crop(src, x0, y0, cw_px, ch_px)
        out = label.copy()
        if boxes.size:
            nb = boxes.copy()
            # re-express in crop coordinates, clip, drop centers outside
            nb[:, [0, 2]] = (nb[:, [0, 2]] - crop[0]) / (crop[2] - crop[0])
            nb[:, [1, 3]] = (nb[:, [1, 3]] - crop[1]) / (crop[3] - crop[1])
            nb = np.clip(nb, 0.0, 1.0)
            cxs = (nb[:, 0] + nb[:, 2]) / 2
            cys = (nb[:, 1] + nb[:, 3]) / 2
            dead = ~((cxs > 0) & (cxs < 1) & (cys > 0) & (cys < 1))
            vi = np.where(valid)[0]
            out[vi, 1:5] = nb
            out[vi[dead], 0] = -1.0
        return src, out


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50, **kwargs):
    """Ref: mx.image.CreateDetAugmenter. rand_crop / rand_pad are
    PROBABILITIES (fraction of images augmented), realized through
    DetRandomSelectAug exactly like the reference."""
    auglist = []
    if rand_crop > 0:
        crop = DetRandomCropAug(
            min_object_covered, aspect_ratio_range,
            (min(area_range[0], 1.0), min(area_range[1], 1.0)),
            max_attempts)
        auglist.append(DetRandomSelectAug([crop],
                                          skip_prob=1.0 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0),
                               max(area_range[1], 1.0)), max_attempts)
        auglist.append(DetRandomSelectAug([pad],
                                          skip_prob=1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # geometric augs done: force to the final shape (boxes are
    # normalized, so a pure resize leaves labels untouched)
    from .image import ForceResizeAug

    auglist.append(DetBorrowAug(ForceResizeAug((data_shape[2],
                                                data_shape[1]),
                                               inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        from .image import IMAGENET_PCA_EIGVAL, IMAGENET_PCA_EIGVEC

        auglist.append(DetBorrowAug(LightingAug(
            pca_noise, IMAGENET_PCA_EIGVAL, IMAGENET_PCA_EIGVEC)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is not None or std is not None:
        from .image import ColorNormalizeAug, _resolve_mean_std

        mean, std = _resolve_mean_std(mean, std)
        auglist.append(DetBorrowAug(ColorNormalizeAug(_nd.array(mean),
                                                      _nd.array(std))))
    return auglist


class _LazyRecKey:
    """Marker for an on-demand indexed-recordio payload."""

    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key


def _parse_det_label(raw):
    """[header_w, obj_w, <header...>, objs...] → (num_obj, obj_w) array."""
    raw = np.asarray(raw, np.float32).ravel()
    if raw.size < 2:
        raise MXNetError(f"malformed det label (size {raw.size})")
    header_w, obj_w = int(raw[0]), int(raw[1])
    if header_w < 2 or obj_w < 5:
        raise MXNetError(
            f"det label header_width={header_w} object_width={obj_w}; "
            "need >=2 and >=5 ([cls, xmin, ymin, xmax, ymax, ...])")
    body = raw[header_w:]
    if body.size % obj_w:
        raise MXNetError("det label body not a multiple of object width")
    return body.reshape(-1, obj_w)


class ImageDetIter:
    """Detection iterator over .lst/.rec with box labels
    (ref: mx.image.ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, label_pad_width=None,
                 label_pad_value=-1.0, data_name="data",
                 label_name="label", last_batch_handle="pad",
                 num_parts=1, part_index=0):
        from ..io.io import DataDesc

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_pad_value = float(label_pad_value)
        from ..io.io import _check_partition

        _check_partition(num_parts, part_index)  # before any dataset scan
        self._shuffle = shuffle
        # each item: (label 2-D array, source) where source is a str
        # path, raw encoded bytes, or a lazy-read key into self._rec
        self._items = []
        self._rec = None
        if path_imgrec:
            from .. import recordio as _recordio

            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                # indexed: scan labels once (headers only), keep the
                # reader open and fetch payloads on demand — a COCO-size
                # .rec must not be held in RAM (ref: streaming iter)
                self._rec = _recordio.MXIndexedRecordIO(idx_path,
                                                        path_imgrec, "r")
                for key in self._rec.keys:
                    header, _ = _recordio.unpack(self._rec.read_idx(key))
                    self._items.append((_parse_det_label(header.label),
                                        _LazyRecKey(key)))
            else:
                rec = _recordio.MXRecordIO(path_imgrec, "r")
                while True:
                    s = rec.read()
                    if s is None:
                        break
                    header, img = _recordio.unpack(s)
                    self._items.append((_parse_det_label(header.label),
                                        img))
                rec.close()
        elif path_imglist:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = _parse_det_label([float(v) for v in
                                              parts[1:-1]])
                    self._items.append(
                        (label, os.path.join(path_root, parts[-1])))
        else:
            raise MXNetError("need path_imgrec or path_imglist")
        if num_parts > 1:  # dist-worker shard (ref: num_parts/part_index)
            self._items = self._items[part_index::num_parts]
        if not self._items:
            raise MXNetError("empty detection dataset")

        obj_w = self._items[0][0].shape[1]
        for lab, _ in self._items:
            if lab.shape[1] != obj_w:
                raise MXNetError("inconsistent object widths across images")
        max_obj = max(lab.shape[0] for lab, _ in self._items)
        if label_pad_width and label_pad_width < max_obj:
            raise MXNetError(
                f"label_pad_width={label_pad_width} is smaller than the "
                f"dataset's max object count {max_obj}; raise it or drop "
                "the argument")
        self.max_objects = label_pad_width or max_obj
        self.obj_width = obj_w
        self._aug = (aug_list if aug_list is not None
                     else CreateDetAugmenter((data_shape[0], data_shape[1],
                                              data_shape[2])))
        self.provide_data = [DataDesc(data_name, (batch_size,)
                                      + self.data_shape)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, self.max_objects,
                                        obj_w))]
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(
                f"unknown last_batch_handle {last_batch_handle!r}")
        self._order = list(range(len(self._items)))
        self._pos = 0
        self._last_batch_handle = last_batch_handle
        self._rollover = []  # leftover indices carried to the next epoch
        self.reset()

    def __iter__(self):
        return self

    def reset(self):
        self._pos = 0
        if self._shuffle:
            np.random.shuffle(self._order)
        if self._rollover:
            # roll_over: leftover samples lead the new epoch
            rest = [i for i in self._order if i not in set(self._rollover)]
            self._order = self._rollover + rest
            self._rollover = []

    def _load_image(self, src):
        if isinstance(src, _LazyRecKey):
            from .. import recordio as _recordio

            _, payload = _recordio.unpack(self._rec.read_idx(src.key))
            src = payload
        if isinstance(src, (bytes, bytearray)):
            from .image import imdecode

            return imdecode(src)
        return imread(src)

    def next(self):
        from ..io.io import DataBatch

        n = len(self._items)
        if self._pos >= n:
            raise StopIteration
        remaining = n - self._pos
        if remaining < self.batch_size:
            if self._last_batch_handle == "discard":
                self._pos = n
                raise StopIteration
            if self._last_batch_handle == "roll_over":
                # keep the leftovers for the start of the next epoch
                self._rollover = self._order[self._pos:]
                self._pos = n
                raise StopIteration
        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, self.max_objects,
                          self.obj_width), self.label_pad_value,
                         np.float32)
        pad = 0
        for i in range(self.batch_size):
            if self._pos >= n:
                pad += 1  # "pad": wrap around, report pad count
                self._pos += 1
                idx = self._order[(self._pos - 1) % n]
            else:
                idx = self._order[self._pos]
                self._pos += 1
            lab, src = self._items[idx]
            img = self._load_image(src)
            lab = lab.copy()
            for aug in self._aug:
                img, lab = aug(img, lab)
            arr = img.asnumpy().astype(np.float32)
            if arr.shape[0] != h or arr.shape[1] != w:
                # aug chain without a resize step: fix up float-safely
                # (imresize would cast normalized data through uint8)
                from .image import _resize_float

                arr = _resize_float(arr, w, h)
            data[i] = arr.transpose(2, 0, 1)
            labels[i, :lab.shape[0]] = lab
        return DataBatch(data=[_nd.array(data)],
                         label=[_nd.array(labels)], pad=pad)

    def __next__(self):
        return self.next()

    def draw_next(self, color=255, thickness=2):
        """Yield images with boxes burned in (debug aid; ref: draw_next)."""
        for lab, src in self._items:
            img = imresize(self._load_image(src), self.data_shape[2],
                           self.data_shape[1]).asnumpy().copy()
            h, w = img.shape[0], img.shape[1]
            for obj in lab:
                if obj[0] < 0:
                    continue
                x0, y0, x1, y1 = (int(obj[1] * w), int(obj[2] * h),
                                  int(obj[3] * w), int(obj[4] * h))
                img[y0:y1, x0:x0 + thickness] = color
                img[y0:y1, max(x1 - thickness, 0):x1] = color
                img[y0:y0 + thickness, x0:x1] = color
                img[max(y1 - thickness, 0):y1, x0:x1] = color
            yield img
