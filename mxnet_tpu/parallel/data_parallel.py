"""SPMD data-parallel training: the whole-step compiled path.

Ref: §3.3 of SURVEY.md — Trainer.step's kvstore push/pull pair becomes a
psum INSIDE the compiled step ("TPU translation: push+pull → psum over
ICI mesh axis inside the step computation; update_on_kvstore → sharded
optimizer state").  This module is that north-star path: ONE jitted XLA
computation per training step containing forward, backward, gradient
all-reduce (inserted by GSPMD from shardings) and the optimizer update,
with parameter donation for in-place update.

Works with any HybridBlock + gluon Loss + optimizer name.  The eager
Trainer (gluon/trainer.py) stays for MXNet-parity semantics; this class
is the performance path the bench uses.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import random as _random
from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _wrap
from . import mesh as mesh_mod


class DataParallelTrainer:
    """Compiled SPMD train step over a device mesh.

    batch axis sharded on 'dp'; params replicated (or tp-sharded via
    shard_params=True); grads psum'ed by GSPMD; optimizer fused in-step.
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, shard_params=False, donate=True,
                 shard_opt_states=False, compute_dtype=None, remat=False,
                 param_spec_fn=None, accum_steps=1):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        # gradient accumulation (ref: grad_req='add' + Trainer.step on
        # the accumulated batch): the global batch is split into
        # `accum_steps` micro-batches scanned INSIDE the compiled step —
        # activation memory scales with batch/accum_steps while the
        # optimizer sees the exact full-batch mean gradient.  TPU-first
        # form of the reference's python-loop accumulation: one XLA
        # computation, no per-micro-batch dispatch.
        self._accum = int(accum_steps)
        if self._accum < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        # multi-precision training (ref: MXNet fp16 + fp32 master weights,
        # optimizer_op multi_mp_sgd; TPU-first: bf16 feeds the MXU at full
        # rate, fp32 feeds it at ~1/4): master params + optimizer states
        # stay fp32, forward/backward run in `compute_dtype`
        self._compute_dtype = jnp.dtype(compute_dtype) \
            if compute_dtype is not None else None
        opt_params = dict(optimizer_params or {})
        self._lr = float(opt_params.pop("learning_rate", 0.01))
        self._opt_name = optimizer
        self._opt_params = opt_params
        self._shard_params = shard_params
        # optional (name, shape) -> PartitionSpec-or-None override: the
        # hook for non-tp layouts (e.g. expert parallelism: shard
        # MoEFFN's expert-stacked params over an 'ep' axis — see
        # parallel/moe.gluon_moe_param_spec_fn); None falls through to
        # the default rule
        self._param_spec_fn = param_spec_fn
        self._donate = donate
        # ZeRO-style: optimizer state sharded over 'dp'; XLA inserts the
        # gather/scatter collectives (ref: kvstore_dist_server.h
        # server-side sharded update, SURVEY §3.3 "update_on_kvstore →
        # sharded optimizer state")
        self._shard_opt_states = shard_opt_states
        # rematerialization (jax.checkpoint): don't store forward
        # activations across checkpoint boundaries — recompute them
        # during backward.  Applied PER DIRECT CHILD BLOCK of the model
        # (a single outer checkpoint would recompute everything and
        # still materialize every residual at once — no peak-HBM win);
        # children holding aux-mutating params (BatchNorm moving stats)
        # stay exact.  Trades ~1/3 more FLOPs for ~O(depth) less HBM
        # (the reference's closest analogue is mirror/memonger).
        self._remat = bool(remat)
        self._step_fn = None
        self._many_fns = {}
        self._n_inputs = 1
        self._named = None      # [(name, Parameter)]
        self._params = None     # list of raw jax arrays (device, sharded)
        self._states = None     # optimizer state pytree per param
        self._t = 0

    # -- param plumbing ------------------------------------------------------

    def _gather_params(self, sample_x):
        if self.block._active is False:
            self.block.hybridize()
        # one eager probe to finish deferred init
        if isinstance(sample_x, tuple):
            probe = self.block(*sample_x)
        else:
            probe = self.block(sample_x)
        if isinstance(probe, (list, tuple)):
            for p in probe:
                p.wait_to_read()
        self._named = self.block._ordered_params()
        from jax.sharding import NamedSharding

        params = []
        self._param_shardings = []
        self._custom_spec = []  # which params param_spec_fn placed
        for name, p in self._named:
            raw = p.data()._data
            from jax.sharding import PartitionSpec

            spec = None
            custom = False
            if self._param_spec_fn is not None:
                spec = self._param_spec_fn(name, raw.shape)
                custom = spec is not None
            if spec is None:
                if self._shard_params:
                    spec = mesh_mod.shard_param_spec(raw.shape, self.mesh)
                else:
                    spec = PartitionSpec()
            self._custom_spec.append(custom)
            sh = NamedSharding(self.mesh, spec)
            # explicit copy: device_put may alias `raw` (same device), and
            # the step donates its param inputs — donating an aliased
            # buffer would delete the block's own weights out from under
            # eager use (`Buffer has been deleted or donated`)
            params.append(mesh_mod.global_put(jnp.array(raw, copy=True),
                                              sh))
            self._param_shardings.append(sh)
        self._params = tuple(params)
        if self._param_spec_fn is not None and not any(self._custom_spec):
            # an explicitly-passed spec fn that placed NOTHING is a
            # misconfiguration (e.g. a custom block prefix the matcher
            # doesn't see) — training would silently replicate what the
            # user asked to shard
            raise MXNetError(
                "param_spec_fn matched no parameters; check the "
                "parameter names it filters on (e.g. "
                "gluon_moe_param_spec_fn expects the default 'moeffn' "
                "prefix)")
        self._trainable = [p.grad_req != "null" for _, p in self._named]

    def _opt_state_sharding(self, shape):
        """dp-sharded NamedSharding for one optimizer-state tensor:
        shard the largest dp-divisible axis; replicate if none."""
        from jax.sharding import NamedSharding, PartitionSpec

        dp = self.mesh.shape.get("dp", 1)
        dims = [None] * len(shape)
        if self._shard_opt_states and dp > 1:
            for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
                if shape[i] % dp == 0 and shape[i] >= dp:
                    dims[i] = "dp"
                    break
        return NamedSharding(self.mesh, PartitionSpec(*dims))

    def _place_state(self, raw, param_sharding=None, custom=False):
        z = jnp.zeros_like(raw)
        # a param placed by param_spec_fn (e.g. experts over 'ep')
        # keeps its optimizer state under the SAME sharding — a
        # replicated Adam state for an ep-sharded weight would cost
        # ep x the memory the sharding saved.  Default tp-sharded
        # params (shard_params=True) keep the ZeRO dp placement.
        if custom:
            spec = getattr(param_sharding, "spec", None)
            if spec is not None and any(s is not None for s in spec):
                return mesh_mod.global_put(z, param_sharding)
        return mesh_mod.global_put(z, self._opt_state_sharding(z.shape))

    def _init_opt_states(self):
        name = self._opt_name
        states = []
        # built below; stored as a tuple to keep jit pytree structure stable
        for raw, sh, custom, trainable in zip(self._params,
                                              self._param_shardings,
                                              self._custom_spec,
                                              self._trainable):
            if not trainable:
                states.append(None)
            elif name == "sgd" and self._opt_params.get("momentum", 0):
                states.append(self._place_state(raw, sh, custom))
            elif name in ("adam", "adamw", "lamb"):
                states.append((self._place_state(raw, sh, custom),
                               self._place_state(raw, sh, custom)))
            elif name == "sgd":
                states.append(None)
            else:
                raise MXNetError(
                    f"DataParallelTrainer supports sgd/adam/adamw/lamb, "
                    f"got {name!r}")
        self._states = tuple(states)

    # -- the compiled step --------------------------------------------------

    def _build_step(self):
        from jax.sharding import NamedSharding, PartitionSpec

        block, loss_block = self.block, self.loss_fn
        named = self._named
        trainable = self._trainable
        opt_name = self._opt_name
        op = dict(self._opt_params)
        momentum = float(op.get("momentum", 0.0))
        wd = float(op.get("wd", 0.0))
        beta1 = float(op.get("beta1", 0.9))
        beta2 = float(op.get("beta2", 0.999))
        eps = float(op.get("epsilon", 1e-8))
        clip = op.get("clip_gradient")

        from ..gluon.block import _tracing

        cdt = self._compute_dtype

        def _to_compute(r):
            if cdt is not None and jnp.issubdtype(r.dtype, jnp.floating):
                return r.astype(cdt)
            return r

        def forward_loss(param_raws, x_raw, y_raw, key):
            orig_dtypes = [r.dtype for r in param_raws]
            if cdt is not None:
                # trainable params only: non-trainables (BN moving
                # stats) must stay fp32 so their EMA isn't quantized to
                # bf16 every step — the BN kernel does its stats math
                # in fp32 regardless
                param_raws = tuple(
                    _to_compute(r) if tr else r
                    for r, tr in zip(param_raws, trainable))
                if isinstance(x_raw, tuple):
                    x_raw = tuple(_to_compute(r) for r in x_raw)
                else:
                    x_raw = _to_compute(x_raw)
            params = [p for _, p in named]
            old = [p._traced_value for p in params]
            prev = getattr(_tracing, "active", False)
            _tracing.active = True
            tok = _random.push_trace_key(key)
            wrappers = [_wrap(r) for r in param_raws]
            try:
                for p, w in zip(params, wrappers):
                    p._traced_value = w
                with autograd.pause(train_mode=True):
                    if isinstance(x_raw, tuple):
                        out = block.forward(*(_wrap(r) for r in x_raw))
                    else:
                        out = block.forward(_wrap(x_raw))
                    loss = loss_block(out, _wrap(y_raw))
            finally:
                _random.pop_trace_key(tok)
                _tracing.active = prev
                for p, o in zip(params, old):
                    p._traced_value = o
            # aux side effects (BatchNorm moving stats): wrappers mutated
            # in place during forward; surface as aux outputs (cast back
            # to the master dtype so bf16 never leaks into master params)
            aux = tuple(w._data.astype(d) for w, d in
                        zip(wrappers, orig_dtypes))
            return jnp.mean(loss._data.astype(jnp.float32)), aux

        def apply_opt(raw, g, state, lr, t):
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            if opt_name == "sgd":
                g = g + wd * raw
                if momentum:
                    new_m = momentum * state - lr * g
                    return raw + new_m, new_m
                return raw - lr * g, None
            m, v = state
            if opt_name != "adamw":
                g = g + wd * raw
            nm = beta1 * m + (1 - beta1) * g
            nv = beta2 * v + (1 - beta2) * jnp.square(g)
            mhat = nm / (1 - beta1 ** t)
            vhat = nv / (1 - beta2 ** t)
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if opt_name == "adamw":
                upd = upd + wd * raw
            if opt_name == "lamb":
                wn = jnp.linalg.norm(raw)
                un = jnp.linalg.norm(upd)
                ratio = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
                upd = ratio * upd
            return raw - lr * upd, (nm, nv)

        loss_fn_for_grad = forward_loss
        if self._remat and not self._apply_child_remat():
            # no wrappable children (flat model): checkpoint the whole
            # forward — full recompute, saves only the head residuals
            loss_fn_for_grad = jax.checkpoint(forward_loss)

        accum = self._accum

        def _grads_once(params, x, y, key):
            return jax.value_and_grad(
                loss_fn_for_grad, has_aux=True)(params, x, y, key)

        def _grads_accum(params, x, y, key):
            """Micro-batch scan: split the leading batch axis into
            (accum, B/accum), accumulate f32 grads, average.  Equal
            micro sizes make mean-of-means == full-batch mean, so the
            result is bitwise the same contract as _grads_once."""
            def split(a):
                b = a.shape[0]
                if b % accum:
                    raise ValueError(
                        f"batch {b} not divisible by accum_steps {accum}")
                return a.reshape((accum, b // accum) + a.shape[1:])

            xs = tuple(split(v) for v in x) if isinstance(x, tuple) \
                else split(x)
            ys = split(y)
            keys = jax.random.split(key, accum)

            def body(carry, inp):
                gsum, loss_sum = carry
                xi, yi, ki = inp
                (loss, aux), g = _grads_once(params, xi, yi, ki)
                gsum = jax.tree.map(
                    lambda s, gi: s + gi.astype(jnp.float32), gsum, g)
                return (gsum, loss_sum + loss), aux

            g0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (gsum, loss_sum), auxs = jax.lax.scan(
                body, (g0, jnp.float32(0)), (xs, ys, keys))
            grads = jax.tree.map(
                lambda s, p: (s / accum).astype(p.dtype), gsum, params)
            # aux (BN moving stats): the last micro-batch's update —
            # the same value a sequential grad_req='add' loop leaves
            aux = jax.tree.map(lambda a: a[-1], auxs)
            return (loss_sum / accum, aux), grads

        def step(params, states, x, y, key, lr, t):
            (loss, aux), grads = (
                _grads_accum if accum > 1 else _grads_once)(
                    params, x, y, key)
            new_params, new_states = [], []
            for raw, g, st, tr, new_raw in zip(params, grads, states,
                                               trainable, aux):
                if not tr:
                    # non-trainable: take the aux-updated value (BN stats)
                    new_params.append(new_raw)
                    new_states.append(st)
                else:
                    nw, ns = apply_opt(raw, g, st, lr, t)
                    new_params.append(nw)
                    new_states.append(ns)
            return loss, tuple(new_params), tuple(new_states)

        data_sh = mesh_mod.batch_sharding(self.mesh)
        repl = NamedSharding(self.mesh, PartitionSpec())
        x_sh = tuple(data_sh for _ in range(self._n_inputs)) \
            if self._n_inputs > 1 else data_sh
        # optimizer states keep their (possibly dp-sharded / ZeRO)
        # placement in and out of the step
        state_sh = jax.tree.map(lambda s: s.sharding, self._states)
        in_shardings = (tuple(self._param_shardings),
                        state_sh, x_sh, data_sh, repl, repl, repl)
        # pin param output shardings to the input layout, else GSPMD may
        # pick a different layout for returned params and the next call's
        # in_shardings check rejects them
        out_shardings = (repl, tuple(self._param_shardings), state_sh)
        donate = (0, 1) if self._donate else ()
        self._step_core = step
        self._in_shardings = in_shardings
        self._out_shardings = out_shardings
        self._step_fn = jax.jit(step, in_shardings=in_shardings,
                                out_shardings=out_shardings,
                                donate_argnums=donate)
        self._many_fns = {}

    def _build_step_many(self, n_steps, stacked):
        """Jit a lax.scan over `n_steps` applications of the step body —
        the bulk-execution path (ref: MXNET_EXEC_BULK_EXEC_TRAIN pushes
        whole graph segments to the engine in one go; here the whole
        K-step TRAINING RUN is one XLA computation, so per-dispatch
        latency — dominant through the remote device tunnel — is paid
        once per K steps instead of every step).

        `stacked`: True → x/y carry a leading (K,) axis with one
        minibatch per step; False → the same device-resident batch is
        reused every step (synthetic benchmark semantics).
        """
        step = self._step_core
        (param_sh, state_sh, x_sh, y_sh, repl, _, _) = self._in_shardings

        def many(params, states, x, y, keys, lr, t0):
            def body(carry, inp):
                params, states, t = carry
                if stacked:
                    key, xi, yi = inp
                else:
                    key = inp
                    xi, yi = x, y
                loss, params, states = step(params, states, xi, yi,
                                            key, lr, t)
                return (params, states, t + 1.0), loss
            xs = (keys, x, y) if stacked else keys
            (params, states, _), losses = jax.lax.scan(
                body, (params, states, t0), xs)
            return losses, params, states

        if stacked:
            from jax.sharding import NamedSharding, PartitionSpec

            def _stack_sh(sh):
                return NamedSharding(self.mesh,
                                     PartitionSpec(None, *sh.spec))
            x_in = jax.tree.map(_stack_sh, x_sh)
            y_in = _stack_sh(y_sh)
        else:
            x_in, y_in = x_sh, y_sh
        fn = jax.jit(
            many,
            in_shardings=(param_sh, state_sh, x_in, y_in, repl, repl, repl),
            out_shardings=(repl, param_sh, state_sh),
            donate_argnums=(0, 1) if self._donate else ())
        self._many_fns[(n_steps, stacked)] = fn
        return fn

    def _apply_child_remat(self):
        """Wrap each eligible direct child block's forward in
        jax.checkpoint so backward recomputes that child instead of
        storing its activations.  Returns the number of children
        wrapped.  Eligible: HybridBlock children whose params all carry
        gradients (aux-mutating children — BatchNorm moving stats —
        must stay exact: their in-place wrapper updates would leak
        checkpointed tracers).  Idempotent per trainer."""
        if getattr(self, "_remat_applied", False):
            return self._remat_count
        self._remat_applied = True
        self._remat_count = 0
        children = getattr(self.block, "_children", None) or {}
        for name, child in list(children.items()):
            params = child.collect_params()
            if any(p.grad_req == "null" for p in params.values()):
                continue
            child.forward = self._make_remat_forward(child.forward)
            self._remat_count += 1
        return self._remat_count

    @staticmethod
    def _make_remat_forward(orig):
        def fwd(*args):
            if not args or not all(isinstance(a, NDArray) for a in args):
                return orig(*args)  # non-array calling pattern: exact

            def pure(*raws):
                outs = orig(*[_wrap(r) for r in raws])
                if isinstance(outs, (tuple, list)):
                    return tuple(o._data for o in outs)
                return (outs._data,)

            outs = jax.checkpoint(pure)(*[a._data for a in args])
            wrapped = [_wrap(o) for o in outs]
            return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)

        return fwd

    # -- public api ---------------------------------------------------------

    def build(self, x):
        """Trace + compile the step for example input(s) `x` without
        running a step (needed before `load_states` on a fresh
        trainer). Idempotent."""
        if self._step_fn is not None:
            return
        multi = isinstance(x, (tuple, list))
        if multi:
            x = tuple(v._data if isinstance(v, NDArray) else v for v in x)
            self._n_inputs = len(x)
            probe = tuple(_wrap(jnp.asarray(v[:2])) for v in x)
        else:
            if isinstance(x, NDArray):
                x = x._data
            self._n_inputs = 1
            probe = _wrap(jnp.asarray(x[:2]))
        self._gather_params(probe)
        self._init_opt_states()
        self._build_step()

    def step(self, x, y):
        """One compiled SPMD step; returns scalar loss NDArray.

        `x` may be a single array or a tuple/list of arrays for
        multi-input blocks (BERT: tokens/types/targets/...); every
        input is batch-sharded on the 'dp' mesh axis.
        """
        multi = isinstance(x, (tuple, list))
        if multi:
            x = tuple(v._data if isinstance(v, NDArray) else v for v in x)
        elif isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        self.build(x)
        data_sh = mesh_mod.batch_sharding(self.mesh)
        if multi:
            x = tuple(mesh_mod.global_put(jnp.asarray(v), data_sh)
                      for v in x)
        else:
            x = mesh_mod.global_put(jnp.asarray(x), data_sh)
        y = mesh_mod.global_put(jnp.asarray(y), data_sh)
        self._t += 1
        key = _random.next_key()
        loss, self._params, self._states = self._step_fn(
            self._params, self._states, x, y, key,
            jnp.asarray(self._lr, jnp.float32),
            jnp.asarray(float(self._t), jnp.float32))
        return _wrap(loss)

    def step_many(self, x, y, n_steps=None):
        """Run K training steps as ONE compiled XLA computation
        (lax.scan over the step body); returns the per-step losses as a
        (K,) NDArray.

        Two calling modes:
        - ``step_many(xs, ys, n_steps=None)`` where ``xs``/``ys`` carry
          a leading (K,) axis: one minibatch per scanned step (bulk
          training over K pre-staged batches).
        - ``step_many(x, y, n_steps=K)`` with plain batch shapes: the
          same batch is re-used K times (synthetic-benchmark semantics,
          ref: benchmark_score.py --benchmark 1).

        Numerically identical to K ``step()`` calls — the same PRNG key
        sequence is consumed — but per-dispatch latency is paid once.
        """
        multi = isinstance(x, (tuple, list))
        if multi:
            x = tuple(v._data if isinstance(v, NDArray) else v for v in x)
        elif isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        stacked = n_steps is None
        if stacked:
            n_steps = int((x[0] if multi else x).shape[0])
        if n_steps < 1:
            raise MXNetError(f"step_many needs n_steps >= 1, got {n_steps}")
        # build the single-step path first (shapes from ONE minibatch)
        probe = tuple(v[0] for v in x) if (stacked and multi) else \
            (x[0] if stacked else x)
        self.build(probe)
        fn = self._many_fns.get((n_steps, stacked)) or \
            self._build_step_many(n_steps, stacked)
        data_sh = mesh_mod.batch_sharding(self.mesh)
        from jax.sharding import NamedSharding, PartitionSpec

        if stacked:
            put_sh = NamedSharding(self.mesh,
                                   PartitionSpec(None, *data_sh.spec))
        else:
            put_sh = data_sh
        if multi:
            x = tuple(mesh_mod.global_put(jnp.asarray(v), put_sh)
                      for v in x)
        else:
            x = mesh_mod.global_put(jnp.asarray(x), put_sh)
        y = mesh_mod.global_put(jnp.asarray(y), put_sh)
        # consume the SAME key sequence n individual step() calls would
        keys = jnp.stack([_random.next_key() for _ in range(n_steps)])
        t0 = jnp.asarray(float(self._t + 1), jnp.float32)
        self._t += n_steps
        losses, self._params, self._states = fn(
            self._params, self._states, x, y, keys,
            jnp.asarray(self._lr, jnp.float32), t0)
        return _wrap(losses)

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = float(lr)

    # -- sharded checkpoint/resume ------------------------------------------

    @staticmethod
    def _shard_id(index, shape):
        """Stable on-disk id of one shard: 'start:stop/...' per dim.
        This string is the checkpoint contract — used by both save and
        load."""
        return "/".join(
            f"{sl.start or 0}:{sl.stop if sl.stop is not None else dim}"
            for sl, dim in zip(index, shape)) or "full"

    def _ckpt_tensors(self):
        """Flat {key: jax.Array} over params + optimizer states."""
        out = {}
        for (name, _), raw in zip(self._named, self._params):
            out[f"param::{name}"] = raw
        for i, st in enumerate(self._states):
            if st is None:
                continue
            leaves = st if isinstance(st, tuple) else (st,)
            for j, leaf in enumerate(leaves):
                out[f"state::{i}::{j}"] = leaf
        return out

    def save_states(self, prefix, async_save=False):
        """Sharded SPMD checkpoint (ref: trainer.save_states + Module
        do_checkpoint, SURVEY §5 checkpoint mechanisms).

        Each process writes ONLY its addressable shards — no cross-host
        gather (the round-1 gap: sync_to_block was a full gather and
        optimizer state wasn't saved at all). Layout:
        ``{prefix}-meta.npz`` (step counter, lr, mesh shape) +
        ``{prefix}-shards-p{rank}.npz`` per process.

        ``async_save=True`` snapshots device shards to host memory
        synchronously (cheap; must happen before the next donated step
        invalidates the buffers) and pushes the file write onto the
        engine's host pool so training overlaps the disk IO (orbax-style
        async checkpointing). Returns a future — call ``.result()``
        before relying on the files (it also re-raises any write error).
        """
        if self._step_fn is None:
            raise MXNetError("save_states before the first step: nothing "
                             "to checkpoint yet")
        proc = jax.process_index()
        # D2H snapshot happens NOW in both modes: the step donates param
        # buffers, so device refs must not outlive the next step()
        shard_arrays = {}
        for key, arr in self._ckpt_tensors().items():
            for s in arr.addressable_shards:
                if s.replica_id != 0:
                    continue  # one copy per distinct shard
                sid = self._shard_id(s.index, arr.shape)
                # copy=True: on CPU backends __array__ can be zero-copy,
                # and an aliased view would be clobbered by the next
                # donated step while the async write is in flight
                shard_arrays[f"{key}@@{sid}"] = np.array(s.data,
                                                         copy=True)
        meta = dict(t=np.int64(self._t), lr=np.float64(self._lr),
                    mesh_shape=np.array(
                        [self.mesh.shape[a] for a in self.mesh.axis_names],
                        np.int64),
                    mesh_axes=np.array(list(self.mesh.axis_names)))

        def _write():
            np.savez(f"{prefix}-shards-p{proc}.npz", **shard_arrays)
            if proc == 0:
                np.savez(f"{prefix}-meta.npz", **meta)

        if async_save:
            from .. import engine as _engine

            return _engine.push_host(_write)
        _write()
        return None

    def load_states(self, prefix):
        """Restore a sharded checkpoint onto the SAME mesh topology.

        Each process reads only the shard files covering its addressable
        devices; arrays are rebuilt with
        ``make_array_from_single_device_arrays`` (no host broadcast).
        """
        import glob as _glob

        if self._step_fn is None:
            raise MXNetError("load_states requires a built trainer: call "
                             "trainer.build(example_x) first")
        meta = np.load(f"{prefix}-meta.npz", allow_pickle=False)
        self._t = int(meta["t"])
        self._lr = float(meta["lr"])
        saved_axes = [str(a) for a in meta["mesh_axes"]]
        saved_shape = [int(v) for v in meta["mesh_shape"]]
        cur = [(a, self.mesh.shape[a]) for a in self.mesh.axis_names]
        if list(zip(saved_axes, saved_shape)) != cur:
            raise MXNetError(
                f"checkpoint mesh {list(zip(saved_axes, saved_shape))} != "
                f"current mesh {cur}; resharding on load isn't supported")
        # index shard KEYS across all visible files, but extract payloads
        # LAZILY — each process materializes only the shards covering its
        # own addressable devices (npz members decompress on access)
        files = [np.load(f, allow_pickle=False)
                 for f in sorted(_glob.glob(f"{prefix}-shards-p*.npz"))]
        where = {k: z for z in files for k in z.files}

        def rebuild(key, like):
            pieces = []
            for dev in like.sharding.addressable_devices:
                idx = like.sharding.addressable_devices_indices_map(
                    like.shape)[dev]
                sid = self._shard_id(idx, like.shape)
                z = where.get(f"{key}@@{sid}")
                if z is None:
                    raise MXNetError(
                        f"checkpoint {prefix} missing shard {sid} of {key}")
                pieces.append(jax.device_put(
                    jnp.asarray(z[f"{key}@@{sid}"], like.dtype), dev))
            return jax.make_array_from_single_device_arrays(
                like.shape, like.sharding, pieces)

        new_params = [rebuild(f"param::{name}", raw)
                      for (name, _), raw in zip(self._named, self._params)]
        new_states = []
        for i, st in enumerate(self._states):
            if st is None:
                new_states.append(None)
            elif isinstance(st, tuple):
                new_states.append(tuple(
                    rebuild(f"state::{i}::{j}", leaf)
                    for j, leaf in enumerate(st)))
            else:
                new_states.append(rebuild(f"state::{i}::0", st))
        for z in files:
            z.close()
        self._params = tuple(new_params)
        self._states = tuple(new_states)

    def sync_to_block(self):
        """Write the trained params back into the block's Parameters."""
        if self._named is None:
            return
        for (name, p), raw in zip(self._named, self._params):
            gathered = jax.device_get(raw)
            from ..ndarray import ndarray as _nd

            p.set_data(_nd.array(np.asarray(gathered)))
