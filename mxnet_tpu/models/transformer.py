"""Transformer encoder-decoder (ref workload: BASELINE config
'Transformer-big WMT14 En-De (Sockeye, hybridized encoder/decoder →
XLA)'; structure after the Sockeye/transformer-big recipe built from
the reference's sequence ops — ref: src/operator/contrib/transformer.cc
era building blocks, here fused via scaled_dot_product_attention).
"""
from __future__ import annotations

import math

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock


def positional_encoding(length, dim):
    pos = np.arange(length)[:, None]
    i = np.arange(dim // 2)[None, :]
    angle = pos / np.power(10000.0, 2 * i / dim)
    enc = np.zeros((length, dim), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


class TransformerLayer(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.1,
                 is_decoder=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        self._is_decoder = is_decoder
        self.self_in_weight = self.params.get(
            "self_in_weight", shape=(3 * units, units))
        self.self_in_bias = self.params.get(
            "self_in_bias", shape=(3 * units,), init="zeros")
        self.self_out_weight = self.params.get(
            "self_out_weight", shape=(units, units))
        self.self_out_bias = self.params.get(
            "self_out_bias", shape=(units,), init="zeros")
        self.ln1 = nn.LayerNorm(in_channels=units)
        if is_decoder:
            self.cross_in_weight = self.params.get(
                "cross_in_weight", shape=(3 * units, units))
            self.cross_in_bias = self.params.get(
                "cross_in_bias", shape=(3 * units,), init="zeros")
            self.cross_out_weight = self.params.get(
                "cross_out_weight", shape=(units, units))
            self.cross_out_bias = self.params.get(
                "cross_out_bias", shape=(units,), init="zeros")
            self.ln_cross = nn.LayerNorm(in_channels=units)
        self.ffn1 = nn.Dense(hidden_size, flatten=False, activation="relu")
        self.ffn2 = nn.Dense(units, flatten=False)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, memory=None, self_mask=None,
                       mem_mask=None, **params):
        att = F.multihead_attention(
            x, x, x, params["self_in_weight"], params["self_in_bias"],
            params["self_out_weight"], params["self_out_bias"], self_mask,
            num_heads=self._num_heads, causal=self._is_decoder)
        x = self.ln1(x + self.dropout(att))
        if self._is_decoder and memory is not None:
            catt = F.multihead_attention(
                x, memory, memory, params["cross_in_weight"],
                params["cross_in_bias"], params["cross_out_weight"],
                params["cross_out_bias"], mem_mask,
                num_heads=self._num_heads)
            x = self.ln_cross(x + self.dropout(catt))
        h = self.ffn2(self.ffn1(x))
        return self.ln2(x + self.dropout(h))


class TransformerModel(HybridBlock):
    """Encoder-decoder for seq2seq (WMT-style)."""

    def __init__(self, src_vocab, tgt_vocab, units=512, hidden_size=2048,
                 num_layers=6, num_heads=8, max_length=512, dropout=0.1,
                 tie_embeddings=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self.src_embed = nn.Embedding(src_vocab, units)
        self.tgt_embed = nn.Embedding(tgt_vocab, units)
        self.pos_const = self.params.get_constant(
            "pos_enc", positional_encoding(max_length, units))
        self.enc_layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.enc_layers.add(TransformerLayer(units, hidden_size,
                                                 num_heads, dropout))
        self.dec_layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.dec_layers.add(TransformerLayer(units, hidden_size,
                                                 num_heads, dropout,
                                                 is_decoder=True))
        self.out_proj = nn.Dense(tgt_vocab, flatten=False)
        self.dropout = nn.Dropout(dropout)

    def _mask_from_len(self, F, valid_length, q_len, k_len):
        steps = F.arange(0, k_len, dtype="float32")
        m = F.broadcast_lesser(steps.reshape(1, -1),
                               valid_length.reshape(-1, 1))
        return (m.reshape(m.shape[0], 1, 1, k_len) - 1.0) * 1e9

    def encode(self, F, src, src_valid_len=None):
        s = src.shape[1]
        pos = self.pos_const.data() if not hasattr(src, "_node") else None
        x = self.src_embed(src) * math.sqrt(self._units)
        x = x + pos[:s] if pos is not None else x
        x = self.dropout(x)
        mask = None
        if src_valid_len is not None:
            mask = self._mask_from_len(F, src_valid_len, s, s)
        for layer in self.enc_layers:
            x = layer(x, None, mask, None)
        return x, mask

    def decode(self, F, tgt, memory, mem_mask=None):
        t = tgt.shape[1]
        pos = self.pos_const.data()
        x = self.tgt_embed(tgt) * math.sqrt(self._units)
        x = x + pos[:t]
        x = self.dropout(x)
        for layer in self.dec_layers:
            x = layer(x, memory, None, mem_mask)
        return self.out_proj(x)

    def hybrid_forward(self, F, src, tgt, src_valid_len=None, **params):
        # params carries registered constants (pos_const); accessed via
        # self.pos_const.data() inside encode/decode
        memory, mem_mask = self.encode(F, src, src_valid_len)
        return self.decode(F, tgt, memory, mem_mask)

    def greedy_decode(self, src, max_len=32, bos=1, eos=2,
                      src_valid_len=None):
        """Greedy inference loop (host-side; each step hits the compiled
        decode graph bucketed by length)."""
        from ..ndarray import ndarray as _nd

        b = src.shape[0]
        out = np.full((b, 1), bos, np.int32)
        for _ in range(max_len - 1):
            logits = self(src, _nd.array(out, dtype="int32"),
                          src_valid_len)
            nxt = logits.asnumpy()[:, -1].argmax(-1).astype(np.int32)
            out = np.concatenate([out, nxt[:, None]], axis=1)
            if (nxt == eos).all():
                break
        return out


def transformer_big(src_vocab, tgt_vocab, **kwargs):
    """Transformer-big (the WMT14 BASELINE config): 1024 units, 16 heads,
    4096 ffn, 6+6 layers."""
    return TransformerModel(src_vocab, tgt_vocab, units=1024,
                            hidden_size=4096, num_layers=6, num_heads=16,
                            dropout=0.3, **kwargs)


def transformer_base(src_vocab, tgt_vocab, **kwargs):
    return TransformerModel(src_vocab, tgt_vocab, units=512,
                            hidden_size=2048, num_layers=6, num_heads=8,
                            **kwargs)


def transformer_tiny(src_vocab=100, tgt_vocab=100, **kwargs):
    return TransformerModel(src_vocab, tgt_vocab, units=32,
                            hidden_size=64, num_layers=2, num_heads=4,
                            max_length=64, **kwargs)
