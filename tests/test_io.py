"""IO tests (ref: tests/python/unittest/test_io.py,
test_recordio.py, test_gluon_data.py)."""
import gzip
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import (CSVIter, DataBatch, MNISTIter, NDArrayIter,
                          ImageRecordIter, PrefetchingIter, ResizeIter,
                          recordio)


def test_ndarray_iter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[3].pad == 2
    it.reset()
    assert len(list(it)) == 4
    # discard mode
    it2 = NDArrayIter(data, label, batch_size=3,
                      last_batch_handle="discard")
    assert len(list(it2)) == 3


def test_ndarray_iter_shuffle_and_dict():
    data = {"a": np.random.rand(8, 2).astype(np.float32)}
    label = {"lbl": np.arange(8, dtype=np.float32)}
    it = NDArrayIter(data, label, batch_size=4, shuffle=True)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 2)
    assert it.provide_data[0].name == "a"
    assert it.provide_label[0].name == "lbl"


def _write_mnist(tmp_path, n=64):
    img = tmp_path / "train-images-idx3-ubyte"
    lbl = tmp_path / "train-labels-idx1-ubyte"
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, (n, 28, 28), dtype=np.uint8)
    lbls = rng.randint(0, 10, n).astype(np.uint8)
    with open(img, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(lbls.tobytes())
    return str(img), str(lbl), imgs, lbls


def test_mnist_iter(tmp_path):
    img, lbl, imgs, lbls = _write_mnist(tmp_path)
    it = MNISTIter(image=img, label=lbl, batch_size=16, shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (16, 1, 28, 28)
    assert batch.data[0].asnumpy().max() <= 1.0
    assert np.allclose(batch.label[0].asnumpy(), lbls[:16])
    assert len(list(it)) == 3  # one consumed + 3 remaining of 4


def test_mnist_iter_flat(tmp_path):
    img, lbl, *_ = _write_mnist(tmp_path)
    it = MNISTIter(image=img, label=lbl, batch_size=8, flat=True,
                   shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (8, 784)


def test_csv_iter(tmp_path):
    data_csv = tmp_path / "d.csv"
    np.savetxt(data_csv, np.arange(12).reshape(4, 3), delimiter=",")
    it = CSVIter(data_csv=str(data_csv), data_shape=(3,), batch_size=2)
    b = next(iter(it))
    assert b.data[0].shape == (2, 3)


def test_recordio_roundtrip(tmp_path):
    rec = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(5):
        w.write(f"record{i}".encode())
    w.close()
    r = recordio.MXRecordIO(rec, "r")
    out = []
    while True:
        item = r.read()
        if item is None:
            break
        out.append(item.decode())
    assert out == [f"record{i}" for i in range(5)]


def test_indexed_recordio(tmp_path):
    rec, idx = str(tmp_path / "t.rec"), str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(10):
        w.write_idx(i, f"rec{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(7).decode() == "rec7"
    assert r.read_idx(2).decode() == "rec2"
    assert len(r.keys) == 10


def test_pack_unpack_img(tmp_path):
    img = (np.random.RandomState(0).rand(32, 32, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 3.0, 7, 0), img,
                          img_fmt=".png")
    header, out = recordio.unpack_img(s, iscolor=1)
    assert header.label == 3.0 and header.id == 7
    assert out.shape == (32, 32, 3)
    assert np.array_equal(out, img)  # png lossless


def test_pack_multi_label():
    s = recordio.pack(recordio.IRHeader(3, [1.0, 2.0, 3.0], 0, 0), b"x")
    header, payload = recordio.unpack(s)
    assert np.allclose(header.label, [1, 2, 3])
    assert payload == b"x"


def _make_rec_dataset(tmp_path, n=12, size=40):
    rec, idx = str(tmp_path / "img.rec"), str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(1)
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    w.close()
    return rec


def test_image_record_iter(tmp_path):
    rec = _make_rec_dataset(tmp_path)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                         batch_size=4, shuffle=True, rand_crop=True,
                         rand_mirror=True)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 32, 32)
    labels = batches[0].label[0].asnumpy()
    assert ((labels >= 0) & (labels <= 2)).all()
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter_corrupt_record(tmp_path):
    """A record whose header flag claims a label vector longer than the
    payload must decode as a zero image, not read out of bounds
    (advisor round-2 medium: DecodeOne skip/label bound checks)."""
    from mxnet_tpu.utils import native
    rec, idx = str(tmp_path / "bad.rec"), str(tmp_path / "bad.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(3)
    img = (rng.rand(40, 40, 3) * 255).astype(np.uint8)
    w.write_idx(0, recordio.pack_img(
        recordio.IRHeader(0, 1.0, 0, 0), img, img_fmt=".jpg"))
    # flag=10**6 claims a 4MB label vector inside a ~50-byte payload
    hdr = np.array([10**6], np.uint32).tobytes() + np.array(
        [2.0], np.float32).tobytes() + np.array([1, 0], np.uint64).tobytes()
    w.write_idx(1, hdr + b"\x01\x02\x03")
    # bare header, no payload at all
    w.write_idx(2, np.array([0], np.uint32).tobytes() + np.array(
        [3.0], np.float32).tobytes() + np.array([2, 0], np.uint64).tobytes())
    # flag=10 but only two label floats present: a 4-BYTE-ALIGNED
    # truncation (frombuffer would silently read 2 floats)
    w.write_idx(3, np.array([10], np.uint32).tobytes() + np.array(
        [4.0], np.float32).tobytes() + np.array([3, 0], np.uint64).tobytes()
        + np.array([8.0, 9.0], np.float32).tobytes())
    w.close()
    # the python parse mirrors native DecodeOne's bound checks: cover
    # both when the lib is present, the python half always
    modes = (True, False) if native.load() is not None else (False,)
    for use_native in modes:
        it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                             batch_size=4, shuffle=False,
                             use_native=use_native)
        b = next(iter(it))
        arr = b.data[0].asnumpy()
        assert arr.shape == (4, 3, 32, 32)
        assert np.isfinite(arr).all()
        assert (arr[1] == 0).all() and (arr[2] == 0).all() \
            and (arr[3] == 0).all()
        # label contract, identical native/python: records 1 and 3's
        # label vectors are unreachable/truncated -> 0; record 2's
        # header parses fine (only the image bytes are missing) -> the
        # label survives
        np.testing.assert_allclose(b.label[0].asnumpy(),
                                   [1.0, 0.0, 3.0, 0.0])


def test_prefetching_resize_iter():
    data = np.random.rand(20, 2).astype(np.float32)
    base = NDArrayIter(data, np.arange(20, dtype=np.float32), batch_size=5)
    pre = PrefetchingIter(base)
    assert len(list(pre)) == 4
    base2 = NDArrayIter(data, np.arange(20, dtype=np.float32), batch_size=5)
    rz = ResizeIter(base2, 2)
    assert len(list(rz)) == 2


def test_gluon_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X = np.random.rand(20, 3).astype(np.float32)
    Y = np.arange(20, dtype=np.float32)
    ds = ArrayDataset(X, Y)
    assert len(ds) == 20
    loader = DataLoader(ds, batch_size=6, shuffle=True, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 3)
    # discard
    loader2 = DataLoader(ds, batch_size=6, last_batch="discard")
    assert len(list(loader2)) == 3


def test_gluon_dataset_transform():
    from mxnet_tpu.gluon.data import ArrayDataset

    X = np.ones((4, 2), np.float32)
    Y = np.arange(4, dtype=np.float32)
    ds = ArrayDataset(X, Y).transform_first(lambda x: x * 2)
    x0, y0 = ds[0]
    assert np.allclose(x0, 2.0)
    assert y0 == 0


def test_vision_transforms():
    from mxnet_tpu.gluon.data.vision import transforms as T

    img = nd.array((np.random.rand(40, 40, 3) * 255).astype(np.uint8),
                   dtype=np.uint8)
    t = T.Compose([T.Resize(32), T.ToTensor(),
                   T.Normalize(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))])
    out = t(img)
    assert out.shape == (3, 32, 32)
    assert out.dtype == np.float32
    assert out.asnumpy().min() >= -1.01 and out.asnumpy().max() <= 1.01
    cc = T.CenterCrop(20)(img)
    assert cc.shape == (20, 20, 3)
    rrc = T.RandomResizedCrop(16)(img)
    assert rrc.shape == (16, 16, 3)
    fl = T.RandomFlipLeftRight()(img)
    assert fl.shape == (40, 40, 3)


def test_synthetic_mnist_dataset():
    from mxnet_tpu.gluon.data.vision import MNIST

    ds = MNIST(root="/nonexistent-path-xyz", train=False, synthetic=True)
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) <= 9
    assert len(ds) == 256


def test_recordio_magic_escape_chunking(tmp_path):
    """dmlc recordio escaping: payloads containing the magic word at a
    4-byte boundary split into cflag continuation chunks (0 whole,
    1 begin, 2 middle, 3 end); the reader re-inserts the removed magic
    on reassembly."""
    import struct

    import mxnet_tpu.io.recordio as R

    magic = struct.pack("<I", R.KMAGIC)
    p = str(tmp_path / "escape.rec")
    payloads = [
        b"plain",
        magic + b"lead",                    # magic at offset 0
        b"abcd" + magic + b"tail",          # aligned interior magic
        b"ab" + magic + b"cd",              # UNaligned: no split
        b"wxyz" + magic + magic + b"end",   # consecutive magics
        magic,                              # the whole record IS magic
    ]
    w = recordio.MXRecordIO(p, "w")
    for pay in payloads:
        w.write(pay)
    w.close()
    r = recordio.MXRecordIO(p, "r")
    for pay in payloads:
        assert r.read() == pay
    assert r.read() is None
    r.close()


def test_image_record_iter_num_parts(tmp_path):
    """Dist-worker data sharding (ref: num_parts/part_index on every
    C++ iterator): shards partition the dataset exactly."""
    rec = _make_rec_dataset(tmp_path, n=12)
    seen = []
    for part in range(3):
        it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                             batch_size=2, num_parts=3, part_index=part,
                             use_native=False)
        for b in it:
            seen.extend(b.label[0].asnumpy().tolist())
    assert len(seen) == 12  # every record in exactly one shard
    # labels are i%3 over i=0..11; each shard sees a consistent multiset
    full_it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                              batch_size=2, use_native=False)
    full = []
    for b in full_it:
        full.extend(b.label[0].asnumpy().tolist())
    assert sorted(seen) == sorted(full)
    with pytest.raises(mx.MXNetError, match="part_index"):
        ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                        batch_size=2, num_parts=3, part_index=3)


def test_image_record_iter_num_parts_streaming(tmp_path):
    """The no-.idx streaming path shards by modulo skip."""
    import os

    rec = _make_rec_dataset(tmp_path, n=8)
    os.remove(os.path.splitext(rec)[0] + ".idx")
    counts = 0
    for part in range(2):
        it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                             batch_size=2, num_parts=2, part_index=part,
                             use_native=False)
        n = sum(b.data[0].shape[0] for b in it)
        assert n == 4
        it.reset()  # shard survives reset
        counts += sum(b.data[0].shape[0] for b in it)
    assert counts == 8


def test_mnist_csv_iter_num_parts(tmp_path):
    # contiguous-range split, matching the reference C++ iterators
    # (iter_mnist.cc GetPart): part 1 of 2 over 10 rows = rows 5..10
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    np.savetxt(str(tmp_path / "d.csv"), data, delimiter=",")
    it = CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(4,),
                 batch_size=5, num_parts=2, part_index=1)
    rows = np.concatenate([b.data[0].asnumpy() for b in it])
    np.testing.assert_allclose(rows, data[5:])
    # coverage + disjointness over an uneven split
    data7 = np.arange(21, dtype=np.float32).reshape(7, 3)
    np.savetxt(str(tmp_path / "d7.csv"), data7, delimiter=",")
    seen = []
    for part in range(3):
        it = CSVIter(data_csv=str(tmp_path / "d7.csv"), data_shape=(3,),
                     batch_size=1, round_batch=False,
                     num_parts=3, part_index=part)
        seen.extend(b.data[0].asnumpy()[0, 0] for b in it)
    assert sorted(seen) == [float(r[0]) for r in data7]


def test_csv_iter_label_csv_roundtrip(tmp_path):
    """Review regression: labels from label_csv must survive (the
    sharding insert once stole the else-branch and zeroed them)."""
    data = np.arange(12, dtype=np.float32).reshape(6, 2)
    labels = np.arange(6, dtype=np.float32).reshape(6, 1) + 10
    np.savetxt(str(tmp_path / "d.csv"), data, delimiter=",")
    np.savetxt(str(tmp_path / "l.csv"), labels, delimiter=",")
    it = CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(2,),
                 label_csv=str(tmp_path / "l.csv"), batch_size=3)
    got = np.concatenate([b.label[0].asnumpy() for b in it]).ravel()
    np.testing.assert_allclose(got, labels.ravel())
    # sharded + labeled
    it2 = CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(2,),
                  label_csv=str(tmp_path / "l.csv"), batch_size=3,
                  num_parts=2, part_index=0)
    got2 = np.concatenate([b.label[0].asnumpy() for b in it2]).ravel()
    np.testing.assert_allclose(got2, labels.ravel()[:3])
    # unlabeled default stays a zeros label (not None)
    it3 = CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(2,),
                  batch_size=3, num_parts=2, part_index=1)
    assert (np.concatenate([b.label[0].asnumpy() for b in it3]) == 0).all()


def test_libsvm_iter_num_parts(tmp_path):
    lines = ["1 0:1.0 3:2.0", "0 1:3.0", "1 2:4.0 4:5.0", "0 0:6.0"]
    p = str(tmp_path / "d.svm")
    open(p, "w").write("\n".join(lines) + "\n")
    from mxnet_tpu.io import LibSVMIter

    it = LibSVMIter(data_libsvm=p, data_shape=(5,), batch_size=2,
                    num_parts=2, part_index=1)
    b = next(iter(it))
    # contiguous-range split (matching the reference's InputSplit):
    # part 1 of 2 over 4 rows = rows 2..4
    dense = b.data[0].todense().asnumpy()
    np.testing.assert_allclose(dense[0], [0, 0, 4, 0, 5])  # row 2
    np.testing.assert_allclose(dense[1], [6, 0, 0, 0, 0])  # row 3
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0])


def test_libsvm_label_row_mismatch_raises(tmp_path):
    open(str(tmp_path / "d.svm"), "w").write("1 0:1.0\n0 1:2.0\n")
    open(str(tmp_path / "l.svm"), "w").write("1\n0\n1\n")  # 3 labels, 2 rows
    from mxnet_tpu.io import LibSVMIter

    with pytest.raises(mx.MXNetError, match="mismatch"):
        LibSVMIter(data_libsvm=str(tmp_path / "d.svm"), data_shape=(4,),
                   label_libsvm=str(tmp_path / "l.svm"), batch_size=1)


def test_image_record_iter_prefetch_overlaps_compute(tmp_path):
    """While the consumer 'computes' on batch k, the pipeline's decode
    threads must fill batch k+1 in the background, so the next next()
    is (nearly) free — the H2D/decode overlap contract the ResNet hot
    loop relies on (VERDICT r2 #3; ref iter_image_recordio_2.cc's
    double-buffered parser)."""
    from mxnet_tpu.utils import native
    import time as _time

    rec, idx = str(tmp_path / "ov.rec"), str(tmp_path / "ov.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(5)
    for i in range(48):  # JPEG so the native pipeline engages
        img = (rng.rand(64, 64, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img))
    w.close()
    # the python prefetcher must overlap too; the native half only
    # when the lib is present
    modes = (True, False) if native.load() is not None else (False,)
    for use_native in modes:
        it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 48, 48),
                             batch_size=8, shuffle=False,
                             preprocess_threads=2,
                             use_native=use_native)
        # steady-state decode cost per batch: drain one epoch flat out
        t0 = _time.perf_counter()
        n_batches = len(list(it))
        per_batch = (_time.perf_counter() - t0) / n_batches
        # wall-clock assertion: best-of-3 attempts shrug off scheduler
        # hiccups on loaded/single-core CI hosts; 3 consecutive misses
        # means overlap genuinely broke
        best = None
        for _ in range(3):
            it.reset()
            next(it)
            # "compute": long enough that background decode of the
            # next batch must finish within it
            _time.sleep(max(5 * per_batch, 0.3))
            t0 = _time.perf_counter()
            next(it)
            wait = _time.perf_counter() - t0
            best = wait if best is None or wait < best else best
            if best < max(0.6 * per_batch, 0.08):
                break
        assert best < max(0.6 * per_batch, 0.08), (
            f"{'native' if use_native else 'python'}: next() after "
            f"compute took {best * 1e3:.1f}ms vs {per_batch * 1e3:.1f}"
            f"ms/batch decode — prefetch is not overlapping")
