"""Symbol + Module tests (ref: tests/python/unittest/test_symbol.py,
test_module.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.module import BucketingModule, Module


def _mlp_symbol(hidden=8, classes=3):
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.var("softmax_label"), name="softmax")


def test_symbol_compose_and_arguments():
    s = _mlp_symbol()
    args = s.list_arguments()
    assert "data" in args and "fc1_weight" in args and "fc1_bias" in args
    assert "fc2_weight" in args and "softmax_label" in args


def test_symbol_infer_shape():
    s = _mlp_symbol(hidden=8, classes=3)
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(
        data=(4, 10), softmax_label=(4,))
    args = s.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (8, 10)
    assert d["fc1_bias"] == (8,)
    assert d["fc2_weight"] == (3, 8)
    assert out_shapes == [(4, 3)]


def test_symbol_json_roundtrip(tmp_path):
    s = _mlp_symbol()
    js = s.tojson()
    s2 = sym.fromjson(js)
    assert s2.list_arguments() == s.list_arguments()
    f = str(tmp_path / "m-symbol.json")
    s.save(f)
    s3 = sym.load(f)
    assert s3.list_arguments() == s.list_arguments()


def test_symbol_bind_forward_backward():
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=2, no_bias=True,
                             name="fc")
    x_np = np.random.rand(3, 4).astype(np.float32)
    w_np = np.random.rand(2, 4).astype(np.float32)
    ex = out.bind(mx.cpu(), {"data": nd.array(x_np), "w": nd.array(w_np)},
                  {"data": nd.zeros((3, 4)), "w": nd.zeros((2, 4))})
    (y,) = ex.forward(is_train=True)
    assert np.allclose(y.asnumpy(), x_np @ w_np.T, atol=1e-5)
    ex.backward(nd.ones((3, 2)))
    assert np.allclose(ex.grad_dict["w"].asnumpy(),
                       np.ones((3, 2)).T @ x_np, atol=1e-5)


def test_symbol_simple_bind_and_eval():
    s = _mlp_symbol()
    ex = s.simple_bind(ctx=mx.cpu(), data=(2, 6), softmax_label=(2,))
    assert ex.arg_dict["fc1_weight"].shape == (8, 6)
    ex.arg_dict["data"][:] = 1.0
    outs = ex.forward()
    assert outs[0].shape == (2, 3)
    # softmax outputs sum to 1
    assert np.allclose(outs[0].asnumpy().sum(1), 1.0, atol=1e-5)


def test_symbol_arithmetic():
    a, b = sym.var("a"), sym.var("b")
    c = (a + b) * 2 - a / 2
    ex = c.bind(mx.cpu(), {"a": nd.array([2.0]), "b": nd.array([3.0])})
    (out,) = ex.forward()
    assert np.isclose(out.asscalar(), (2 + 3) * 2 - 1.0)


def test_symbol_internals_getitem():
    s = _mlp_symbol()
    internals = s.get_internals()
    fc1_out = internals["fc1_output"]
    assert fc1_out.name == "fc1"


def test_module_fit_convergence():
    """Train-as-test (ref: tests/python/train/): Module.fit learns."""
    np.random.seed(0)
    mx.random.seed(0)
    n, d = 400, 8
    X = np.random.rand(n, d).astype(np.float32)
    Y = (X.sum(axis=1) > d / 2).astype(np.float32)

    s = _mlp_symbol(hidden=16, classes=2)
    train_iter = NDArrayIter(X, Y, batch_size=40, shuffle=True,
                             label_name="softmax_label")
    mod = Module(s, context=mx.cpu())
    mod.fit(train_iter, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier())
    from mxnet_tpu import metric

    acc_res = mod.score(NDArrayIter(X, Y, batch_size=40), "acc")
    assert acc_res[0][1] > 0.9, acc_res


def test_module_predict_and_checkpoint(tmp_path):
    np.random.seed(1)
    s = _mlp_symbol(hidden=4, classes=2)
    X = np.random.rand(20, 5).astype(np.float32)
    it = NDArrayIter(X, np.zeros(20, np.float32), batch_size=5)
    mod = Module(s, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    preds = mod.predict(it)
    assert preds.shape == (20, 2)

    prefix = str(tmp_path / "model")
    mod.init_optimizer()
    mod.save_checkpoint(prefix, 3)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0003.params")

    mod2 = Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(it.provide_data, it.provide_label, for_training=False)
    preds2 = mod2.predict(it)
    assert np.allclose(preds.asnumpy(), preds2.asnumpy(), atol=1e-5)


def test_bucketing_module():
    """Ref: tests/python/train/test_bucketing.py — shared params across
    sequence-length buckets."""
    np.random.seed(2)

    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="shared_fc",
                                flatten=False)
        pooled = sym.mean(fc, axis=1)
        out = sym.SoftmaxOutput(pooled, sym.var("softmax_label"),
                                name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10, context=mx.cpu())
    from mxnet_tpu.io import DataBatch, DataDesc

    def make_batch(seq_len, bs=4):
        return DataBatch(
            [nd.array(np.random.rand(bs, seq_len, 6))],
            [nd.array(np.random.randint(0, 4, bs))],
            bucket_key=seq_len,
            provide_data=[DataDesc("data", (bs, seq_len, 6))],
            provide_label=[DataDesc("softmax_label", (bs,))])

    mod.bind([DataDesc("data", (4, 10, 6))],
             [DataDesc("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    for seq_len in (10, 5, 20, 10, 5):
        batch = make_batch(seq_len)
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # shared param persisted across buckets
    arg_params, _ = mod.get_params()
    assert "shared_fc_weight" in arg_params
    assert arg_params["shared_fc_weight"].shape == (4, 6)


def test_export_and_symbolblock(tmp_path):
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(5, activation="relu"), nn.Dense(3))
    net.initialize()
    x = nd.random.uniform(shape=(2, 4))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "exported")
    sym_file, param_file = net.export(prefix, epoch=7)
    assert os.path.exists(sym_file) and os.path.exists(param_file)

    # load through the Module path
    from mxnet_tpu.module.module import load_checkpoint

    s, arg_params, aux_params = load_checkpoint(prefix, 7)
    assert "data" in s.list_arguments()
    ex = s.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.copy_params_from(arg_params, aux_params)
    ex.forward(data=x)
    assert np.allclose(ex.outputs[0].asnumpy(), ref, atol=1e-5)


def test_module_multi_context_data_parallel():
    """Ref: Module(context=[...]) — the DataParallelExecutorGroup role:
    batch split across executors, grads summed, params broadcast.
    Multi-ctx training must match single-ctx math exactly."""
    import jax

    rng = np.random.RandomState(0)
    X = rng.rand(128, 10).astype(np.float32)
    W = rng.randn(10, 3).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=8,
                             name="mc_fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=3, name="mc_fc2")
    net = sym.SoftmaxOutput(net, sym.var("softmax_label"),
                            name="softmax")
    ctxs = [mx.Context("cpu", i) for i in range(4)]

    def run(ctx):
        mx.random.seed(5)
        np.random.seed(5)
        it = NDArrayIter(X, Y, batch_size=32, shuffle=False)
        m = Module(net, label_names=("softmax_label",), context=ctx)
        m.fit(it, num_epoch=3, optimizer="sgd",
              optimizer_params={"learning_rate": 0.5})
        return m

    m1, m4 = run(None), run(ctxs)
    a1, a4 = m1.get_params()[0], m4.get_params()[0]
    for k in a1:
        assert np.allclose(a1[k].asnumpy(), a4[k].asnumpy(),
                           atol=1e-4), k
    # merged outputs keep the full batch on the primary context
    from mxnet_tpu.io import DataBatch

    m4.forward(DataBatch([nd.array(X[:32])],
                             [nd.array(Y[:32])]), is_train=False)
    out = m4.get_outputs()[0]
    assert out.shape == (32, 3)
    # per-replica view: list (per output) of lists (per context)
    unmerged = m4.get_outputs(merge_multi_context=False)
    assert len(unmerged) == 1 and len(unmerged[0]) == 4
    assert unmerged[0][0].shape == (8, 3)
    # indivisible batch rejected at bind
    bad = Module(net, label_names=("softmax_label",),
                 context=ctxs[:3])
    with pytest.raises(Exception):
        bad.bind(data_shapes=[("data", (32, 10))],
                 label_shapes=[("softmax_label", (32,))])


def test_bucketing_module_multi_context():
    """BucketingModule passes context through to each bucket's Module,
    so multi-device data parallelism composes with bucketing."""
    from mxnet_tpu.io import DataBatch

    def gen(bucket_key):
        # params (embedding + head) are bucket-independent; only the
        # sequence length varies — the shareable-weights contract
        d = sym.Variable("data")
        emb = sym.Embedding(d, input_dim=10, output_dim=6,
                            name="bk_embed")
        pooled = sym.mean(emb, axis=1)
        net = sym.FullyConnected(pooled, num_hidden=4, name="bk_fc")
        net = sym.SoftmaxOutput(net, sym.var("softmax_label"),
                                name="softmax")
        return net, ("data",), ("softmax_label",)

    ctxs = [mx.Context("cpu", i) for i in range(2)]
    bm = BucketingModule(gen, default_bucket_key=16, context=ctxs)
    bm.bind(data_shapes=[("data", (8, 16))],
            label_shapes=[("softmax_label", (8,))])
    bm.init_params()
    bm.init_optimizer(optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    for key in (16, 8, 16):
        X = rng.randint(0, 10, (8, key)).astype(np.float32)
        Y = rng.randint(0, 4, 8).astype(np.float32)
        bm.switch_bucket(key, [("data", (8, key))],
                         [("softmax_label", (8,))])
        batch = DataBatch([nd.array(X)], [nd.array(Y)],
                          bucket_key=key,
                          provide_data=[("data", (8, key))],
                          provide_label=[("softmax_label", (8,))])
        bm.forward(batch)
        bm.backward()
        bm.update()
        out = bm.get_outputs()[0]
        assert out.shape == (8, 4)
