"""Imperative autograd: tape recording + reverse pass.

Ref: python/mxnet/autograd.py (record/pause/backward/grad/Function) and
src/imperative/imperative.cc (RecordOp / Backward building the grad
graph).

TPU-native design: the tape records (pure-fn, attrs, input buffers,
output NDArrays) per op.  ``backward`` walks the tape in reverse and, for
each node, applies a *cached jitted VJP executable* (jax.vjp of the op's
pure function) — so eager backward is itself a sequence of compiled XLA
executions, and hybridized blocks appear as a single tape node whose VJP
is one whole-graph XLA computation (the CachedOp::Backward equivalent,
ref: src/imperative/cached_op.cc).
"""
from __future__ import annotations

import threading

import jax
import numpy as np

from . import _imperative, engine
from .base import MXNetError

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
    return _state


class _Node:
    __slots__ = ("fn", "kwargs", "in_nds", "in_raws", "out_nds", "custom_vjp",
                 "out_is_tuple")

    def __init__(self, fn, kwargs, in_nds, in_raws, out_nds, custom_vjp=None,
                 out_is_tuple=False):
        self.fn = fn
        self.kwargs = kwargs
        self.in_nds = in_nds      # NDArray inputs (graph edges)
        self.in_raws = in_raws    # raw buffers at record time (version pin)
        self.out_nds = out_nds
        self.custom_vjp = custom_vjp
        self.out_is_tuple = out_is_tuple  # fn returned a tuple (even len 1)


def _record(fn, kwargs, args, raws, out_nds, custom_vjp=None,
            out_is_tuple=False):
    """Record one op.  in_nds is aligned 1:1 with the op's positional args
    (None placeholder for non-NDArray args) so the VJP applier can be
    called with the exact arg list the forward saw."""
    from .ndarray.ndarray import NDArray

    in_nds = [a if isinstance(a, NDArray) else None for a in args]
    in_raws = list(raws)
    for o in out_nds:
        o._in_graph = True
    _st().tape.append(_Node(fn, kwargs, in_nds, in_raws, out_nds, custom_vjp,
                            out_is_tuple))


# ---------------------------------------------------------------------------
# Scopes (ref: python/mxnet/autograd.py record/pause/train_mode/predict_mode)


class _RecordingScope:
    def __init__(self, recording, training):
        self._rec, self._train = recording, training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        st.recording, st.training = self._rec, self._train
        return self

    def __exit__(self, *a):
        st = _st()
        st.recording, st.training = self._old


def record(train_mode=True):
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(_st().recording, True)


def predict_mode():
    return _RecordingScope(_st().recording, False)


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_rec):
    st = _st()
    prev, st.recording = st.recording, is_rec
    return prev


def set_training(train):
    st = _st()
    prev, st.training = st.training, train
    return prev


def mark_variables(variables, gradients, grad_reqs="write"):
    """Ref: autograd.mark_variables — associate grad buffers with vars."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._in_graph = True


# ---------------------------------------------------------------------------
# Backward


def _zeros_like_raw(raw):
    return jax.numpy.zeros(raw.shape, raw.dtype)


def _is_float0(ct):
    return ct is None or (hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the reverse pass from ``heads`` (ref: MXAutogradBackwardEx →
    Imperative::Backward).  Accumulated gradients land in ``x.grad`` for
    every array that called ``attach_grad()``."""
    from .ndarray.ndarray import NDArray, _wrap

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]

    tape = _st().tape
    # cotangent accumulator keyed by NDArray identity
    cts = {}
    for i, h in enumerate(heads):
        if head_grads is None or head_grads[i] is None:
            seed = jax.numpy.ones(h.shape, h.dtype)
        else:
            hg = head_grads[i]
            seed = hg._data if isinstance(hg, NDArray) else jax.numpy.asarray(hg)
        cts[id(h)] = seed

    grads_out = {}

    for node in reversed(tape):
        out_cts = []
        any_needed = False
        for o in node.out_nds:
            c = cts.get(id(o))
            if c is None:
                c = _zeros_like_raw(o._data)
            else:
                any_needed = True
            out_cts.append(c)
        if not any_needed:
            continue
        if node.custom_vjp is not None:
            in_cts = node.custom_vjp(node.in_raws, out_cts)
        else:
            multi = node.out_is_tuple or len(node.out_nds) > 1
            applier = _imperative.get_vjp(node.fn, node.kwargs)
            in_cts = applier(
                tuple(node.in_raws),
                tuple(out_cts) if multi else out_cts[0],
            )
        for nd_in, ct in zip(node.in_nds, in_cts):
            if nd_in is None or _is_float0(ct):
                continue
            prev = cts.get(id(nd_in))
            cts[id(nd_in)] = ct if prev is None else prev + ct

    # write/accumulate into .grad for leaves with attached grads
    for node in tape:
        for nd_in in node.in_nds:
            if nd_in is not None:
                _deposit(nd_in, cts, grads_out)
    for h in heads:
        _deposit(h, cts, grads_out)

    if not retain_graph:
        _st().tape = []
    return


def _deposit(nd, cts, done):
    if nd._grad is None or id(nd) in done:
        return
    ct = cts.get(id(nd))
    if ct is None:
        return
    from .ndarray.ndarray import _wrap

    if nd._grad_req == "add":
        nd._grad = _wrap(engine.track(nd._grad._data + ct))
    else:  # 'write'
        nd._grad = _wrap(engine.track(jax.numpy.asarray(ct, nd._data.dtype)))
    done[id(nd)] = True


def _pure_replay(tape, heads, variables, head_grads):
    """A pure jnp function of the variables' raw buffers that replays
    the recorded tape and returns the head-grad-weighted sum of heads —
    jax.grad of THIS is the higher-order-capable gradient (the tape
    nodes' fns are pure, so the replay is differentiable to any
    order)."""
    import functools

    import jax.numpy as jnp

    def fn(*var_raws):
        env = {id(v): r for v, r in zip(variables, var_raws)}
        for node in tape:
            if node.fn is None:
                raise MXNetError(
                    "create_graph=True cannot differentiate through an "
                    "autograd.Function node (its backward is an opaque "
                    "host callback); express the op with registered "
                    "ops or CustomOp instead")
            args = [env.get(id(nd_in), raw) if nd_in is not None else raw
                    for nd_in, raw in zip(node.in_nds, node.in_raws)]
            f = functools.partial(node.fn, **dict(node.kwargs)) \
                if node.kwargs else node.fn
            out = f(*args)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for o_nd, o_raw in zip(node.out_nds, outs):
                env[id(o_nd)] = o_raw
        total = jnp.float32(0)
        for i, h in enumerate(heads):
            hr = env.get(id(h), h._data)
            if head_grads is None or head_grads[i] is None:
                seed = jnp.ones(hr.shape, jnp.float32)
            else:
                hg = head_grads[i]
                seed = jnp.asarray(getattr(hg, "_data", hg), jnp.float32)
            total = total + (hr.astype(jnp.float32) * seed).sum()
        return total

    return fn


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Ref: autograd.grad — return grads of heads w.r.t. variables without
    touching .grad buffers.

    create_graph=True returns gradients that are THEMSELVES on the
    tape (TPU-native: jax.grad of a pure replay of the recorded ops,
    recorded as one differentiable tape node), so ``.backward()`` or a
    further ``grad(..., create_graph=True)`` over them yields higher
    derivatives to any order — beyond the reference, whose eager
    higher-order support covered only a subset of ops.  Each call
    traces+compiles a fresh replay executable, so keep it out of hot
    loops (the first-order path below is the cached fast path)."""
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    if isinstance(variables, NDArray):
        variables = [variables]

    if create_graph:
        tape = list(_st().tape)
        fn = _pure_replay(tape, heads, variables, head_grads)
        gfn = jax.grad(fn, argnums=tuple(range(len(variables))))
        outs = _imperative.invoke(gfn, *variables)
        return list(outs) if isinstance(outs, tuple) else [outs]
    saved = [(v._grad, v._grad_req) for v in variables]
    for v in variables:
        v._grad = _zeros_ndarray_like(v)
        v._grad_req = "write"
    try:
        backward(heads, head_grads,
                 retain_graph=bool(retain_graph), train_mode=train_mode)
        outs = [v.grad for v in variables]
    finally:
        for v, (g, r) in zip(variables, saved):
            v._grad, v._grad_req = g, r
    return outs


def _zeros_ndarray_like(v):
    from .ndarray.ndarray import _wrap

    return _wrap(jax.numpy.zeros(v.shape, v.dtype))


def get_symbol(x):  # pragma: no cover - legacy API stub
    raise MXNetError("autograd.get_symbol is not supported on the TPU build; "
                     "use HybridBlock.hybridize/export")


# ---------------------------------------------------------------------------
# Custom differentiable functions (ref: autograd.Function)


class Function:
    """User-defined op with custom forward/backward.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` using NDArrays (eager, host side).
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap

        with pause():
            outs = self.forward(*inputs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]

        if is_recording():
            fun = self

            def custom_vjp(in_raws, out_cts):
                with pause():
                    gs = fun.backward(*[_wrap(c) for c in out_cts])
                if isinstance(gs, NDArray):
                    gs = [gs]
                return [g._data if isinstance(g, NDArray) else g for g in gs]

            in_nds = [a for a in inputs if isinstance(a, NDArray)]
            _record(None, {}, in_nds, [a._data for a in in_nds], out_list,
                    custom_vjp=custom_vjp)
        return outs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
