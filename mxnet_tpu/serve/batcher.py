"""Request queue + dynamic batch coalescing.

The batcher is the server's admission controller and shape planner in
one: ``put()`` is the bounded fail-fast edge (overload shows up as an
immediate ``ServerOverloadedError`` at the caller, never as silent
queue bloat), and ``next_group()`` is the coalescing loop — take the
FIFO head, linger briefly for followers, stop at the largest batch
bucket, and drop anything whose deadline already passed.

Grouping is FIFO, not length-sorted: a length-sorted queue would give
better fill ratios but unbounded tail latency for rare lengths.  The
bucket grid bounds padding waste instead (docs/serving.md).
"""
from __future__ import annotations

import collections
import threading
import time

from ..base import MXNetError


class ServerOverloadedError(MXNetError):
    """The bounded request queue is full — shed load upstream."""


class ServerClosedError(MXNetError):
    """submit() after shutdown/drain began."""


class DeadlineExceededError(MXNetError):
    """The request's deadline passed before a batch picked it up."""


class _Request:
    __slots__ = ("example", "length", "future", "deadline", "enqueued_at",
                 "trace_id")

    def __init__(self, example, length, future, deadline_ms=None):
        self.example = example
        self.length = length          # variable-axis size (None if fixed)
        self.future = future
        self.enqueued_at = time.monotonic()
        self.deadline = (self.enqueued_at + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.trace_id = None          # telemetry async-span id (or None)

    def expired(self, now=None):
        return (self.deadline is not None
                and (now or time.monotonic()) > self.deadline)


class Batcher:
    """Bounded FIFO of :class:`_Request` with batch coalescing."""

    def __init__(self, max_queue=256, linger_ms=2.0):
        if max_queue < 1:
            raise MXNetError("max_queue must be >= 1")
        self._max_queue = int(max_queue)
        self._linger_s = float(linger_ms) / 1e3
        self._q = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    def __len__(self):
        with self._lock:
            return len(self._q)

    def put(self, request):
        """Admit a request or fail fast.  Never blocks: backpressure is
        the caller's signal to shed or retry with jitter."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("request queue is closed")
            if len(self._q) >= self._max_queue:
                raise ServerOverloadedError(
                    f"request queue full ({self._max_queue}); retry with "
                    "backoff or raise max_queue")
            self._q.append(request)
            self._not_empty.notify()

    def close(self):
        """Reject further put()s and wake any blocked next_group() call;
        already-queued requests remain collectable (drain semantics)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def reopen(self):
        """Accept put()s again (server restart after drain/shutdown).
        The queue must be empty — both drain and abrupt shutdown leave
        it so; anything else is a lifecycle bug worth failing on."""
        with self._lock:
            if self._q:
                raise MXNetError("cannot reopen a batcher with queued work")
            self._closed = False

    def drained(self):
        """True once closed with nothing left to collect — the batcher
        thread's authoritative exit condition (checked under the queue
        lock so a request admitted before close() is never orphaned)."""
        with self._lock:
            return self._closed and not self._q

    def next_group(self, max_batch, timeout=0.1, on_pop=None):
        """Collect up to ``max_batch`` live requests.

        Blocks (up to ``timeout``) for the first request, then lingers
        ``linger_ms`` so concurrent submitters coalesce into one padded
        batch instead of max_batch singleton batches.  Expired requests
        are failed here — the only dequeue point — and never reach the
        device.  Returns ([], expired) when only expired work was found
        and (None, []) on timeout with an empty queue.

        ``on_pop(n_live)`` runs under the queue lock before the group is
        returned, so a caller's in-flight gauge can pick the requests up
        in the same critical section that removes them from the queue.
        """
        with self._not_empty:
            if not self._q and not self._closed:
                self._not_empty.wait(timeout)
            if not self._q:
                return None, []
        if self._linger_s > 0:
            deadline = time.monotonic() + self._linger_s
            while time.monotonic() < deadline:
                with self._lock:
                    # once closed no new submitter can arrive — lingering
                    # would only slow the drain/shutdown sweep down
                    if len(self._q) >= max_batch or self._closed:
                        break
                time.sleep(self._linger_s / 8)
        group, expired = [], []
        now = time.monotonic()
        with self._lock:
            while self._q and len(group) < max_batch:
                req = self._q.popleft()
                (expired if req.expired(now) else group).append(req)
            if group and on_pop is not None:
                on_pop(len(group))
        return group, expired
