"""Recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py).

Explicit per-step cells for custom unrolling (the un-fused fallback the
reference keeps beside the cuDNN layer).  ``unroll`` runs the python
loop; hybridize captures it into one XLA graph (XLA unrolls it).
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ...ndarray import ndarray as _nd

        return [_nd.zeros(info["shape"])
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Ref: RecurrentCell.unroll."""
        from ... import ndarray as F

        axis = 1 if layout == "NTC" else 0
        if begin_state is None:
            bs = inputs.shape[1 - axis] if axis == 1 else inputs.shape[1]
            bs = inputs.shape[0] if layout == "NTC" else inputs.shape[1]
            begin_state = self.begin_state(bs)
        states = begin_state
        outputs = []
        for t in range(length):
            x_t = inputs[:, t] if layout == "NTC" else inputs[t]
            out, states = self(x_t, states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        if valid_length is not None:
            outputs = F.SequenceMask(
                outputs if layout == "TNC" else outputs.swapaxes(0, 1),
                valid_length, use_sequence_length=True)
            if layout == "NTC":
                outputs = outputs.swapaxes(0, 1)
        return outputs, states

    def __call__(self, x, states=None, **kwargs):
        if states is None:
            states = self.begin_state(x.shape[0])
        return super().__call__(x, *states)


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    """Gate order (i, f, g, o) — matches ops/rnn.py."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        h = hidden_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * h, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * h, h),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * h,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * h,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, c, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        gates = F.FullyConnected(x, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(h, h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        c_new = F.sigmoid(f) * c + F.sigmoid(i) * F.tanh(g)
        h_new = F.sigmoid(o) * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(RecurrentCell):
    """Gate order (r, z, n) — matches ops/rnn.py."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        h = hidden_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * h, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * h, h),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * h,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * h,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        gi = F.FullyConnected(x, i2h_weight, i2h_bias,
                              num_hidden=3 * self._hidden_size)
        gh = F.FullyConnected(h, h2h_weight, h2h_bias,
                              num_hidden=3 * self._hidden_size)
        ir, iz, inn = F.split(gi, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(gh, num_outputs=3, axis=-1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = F.tanh(inn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (ref: SequentialRNNCell)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cells = []

    def add(self, cell):
        self.register_child(cell, str(len(self._cells)))
        self._cells.append(cell)

    def state_info(self, batch_size=0):
        out = []
        for c in self._cells:
            out.extend(c.state_info(batch_size))
        return out

    def __call__(self, x, states=None, **kwargs):
        if states is None:
            states = self.begin_state(x.shape[0])
        next_states = []
        i = 0
        for cell in self._cells:
            n = len(cell.state_info())
            x, cell_states = cell(x, states[i:i + n])
            next_states.extend(cell_states)
            i += n
        return x, next_states

    def forward(self, x, *states):
        return self.__call__(x, list(states) if states else None)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def __call__(self, x, states=None, **kwargs):
        from ... import ndarray as F

        return F.Dropout(x, p=self._rate), states or []


class ModifierCell(RecurrentCell):
    """Base for cells wrapping another cell (ref: rnn_cell.ModifierCell)."""

    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def reset(self):
        pass


class ResidualCell(ModifierCell):
    def __call__(self, x, states=None, **kwargs):
        out, states = self.base_cell(x, states)
        return out + x, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization: randomly keep previous states
    (ref: rnn_cell.ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        self._prev_output = None

    def __call__(self, x, states=None, **kwargs):
        from ... import autograd
        from ... import ndarray as F

        out, next_states = self.base_cell(x, states)
        if not autograd.is_training():
            return out, next_states

        def zone(p, new, old):
            if p == 0.0 or old is None:
                return new
            mask = F.random.uniform(shape=new.shape) < p
            return F.where(mask.astype(new.dtype) > 0, old, new)

        prev = self._prev_output
        if prev is None:
            from ...ndarray import ndarray as _nd

            prev = _nd.zeros(out.shape)
        out = zone(self.zoneout_outputs, out, prev)
        self._prev_output = out
        if states is not None:
            next_states = [zone(self.zoneout_states, n, o)
                           for n, o in zip(next_states, states)]
        return out, next_states


class BidirectionalCell(RecurrentCell):
    """Run one cell forward and another backward over the sequence,
    concatenating outputs per step (ref: rnn_cell.BidirectionalCell —
    unroll-only, like the reference)."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        # a plain list bypasses Block.__setattr__ auto-registration, so
        # each cell registers exactly once under the reference's child
        # names (l_cell/r_cell) — checkpoint keys stay compatible
        self._cells = [l_cell, r_cell]
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    @property
    def _l(self):
        return self._cells[0]

    @property
    def _r(self):
        return self._cells[1]

    def state_info(self, batch_size=0):
        return self._l.state_info(batch_size) + \
            self._r.state_info(batch_size)

    def __call__(self, x, states=None, **kwargs):
        raise NotImplementedError(
            "BidirectionalCell cannot step one timestep at a time "
            "(the backward direction needs the full sequence); "
            "call unroll() (reference behavior)")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F

        axis = 1 if layout == "NTC" else 0

        def _rev(seq):
            """Time-reverse, honoring valid_length padding."""
            if valid_length is None:
                return F.reverse(seq, axis=axis)
            out = F.SequenceReverse(
                seq if layout == "TNC" else seq.swapaxes(0, 1),
                valid_length, use_sequence_length=True)
            return out.swapaxes(0, 1) if layout == "NTC" else out

        nl = len(self._l.state_info())
        if begin_state is None:
            bs = inputs.shape[0] if layout == "NTC" else inputs.shape[1]
            begin_state = self.begin_state(bs)
        l_out, l_states = self._l.unroll(
            length, inputs, begin_state[:nl], layout=layout,
            merge_outputs=True, valid_length=valid_length)
        r_out, r_states = self._r.unroll(
            length, _rev(inputs), begin_state[nl:], layout=layout,
            merge_outputs=True, valid_length=valid_length)
        out = F.concat(l_out, _rev(r_out), dim=2)
        states = l_states + r_states
        if merge_outputs is False:
            steps = [out[:, t] if layout == "NTC" else out[t]
                     for t in range(length)]
            return steps, states
        return out, states


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybridizable stacked cells (ref: HybridSequentialRNNCell — same
    stacking semantics; hybridization happens through the containing
    block here)."""
