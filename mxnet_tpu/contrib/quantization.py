"""Model quantization: calibration + INT8 graph rewrite.

Ref: python/mxnet/contrib/quantization.py (quantize_model, quantize_net,
_LayerOutputCollector, _get_optimal_threshold / KL calibration) and
src/operator/quantization/calibrate.cc — the fork owner's upstream
specialty (MKL-DNN INT8); here the int8 compute runs on the TPU MXU.

Two entry points, mirroring the reference:
  * ``quantize_model(sym, arg_params, aux_params, ...)`` — rewrites a
    symbolic graph: every FullyConnected/Convolution (unless excluded)
    becomes quantize→quantized_op→dequantize with weights quantized
    offline into the returned qarg_params.
  * ``quantize_net(net, ...)`` — replaces Dense/Conv2D children of a
    Gluon block with int8 wrappers in place.

Calibration modes: 'none' (dynamic per-batch ranges), 'naive' (min/max
over calibration data), 'entropy' (KL-divergence-optimal thresholds).

The gluon path (``quantize_net``) is COMPILE-NATIVE: Dense/Conv2D
layers become real HybridBlocks (:class:`QuantizedDense` /
:class:`QuantizedConv`) whose quantize → int8 matmul/conv →
requantize/bias → dequantize chain traces through
``gluon.block.traced_apply`` into one CachedOp executable — quantized
weights, per-output-channel scales, and calibrated ranges are proper
Parameters (runtime graph inputs), so the whole net hybridizes,
AOT-warms through ModelServer/DecodeServer, checkpoints, and
hot-reloads like any other block.  A range-fusion pass folds adjacent
``dequantize → quantize`` boundaries in calibrated chains into one
``requantize`` so activations stay int8 between quantized layers
(docs/quantization.md).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import ndarray as nd
from .. import profiler
from .. import symbol as sym
from ..base import MXNetError
from ..gluon import block as _gluon_block
from ..ndarray.ndarray import NDArray
from ..symbol.symbol import Group, Symbol, _make_op_symbol, _topo_order

_QUANTIZABLE = ("FullyConnected", "Convolution")

_NUM_BINS = 8001


# ---------------------------------------------------------------------------
# window-scoped module counters: the profiler's `quantize` section
# (provider: profiler._quantize_counters; exported to /metrics as
# mxtpu_quantize_* gauges by the section collector)

_sec_lock = threading.Lock()
_sec = {"layers_quantized": 0, "calib_batches": 0, "calib_ms": 0.0,
        "requant_folds": 0, "int8_serve_batches": 0}


def _sec_bump(**deltas):
    with _sec_lock:
        for k, n in deltas.items():
            _sec[k] += n


def quantize_stats():
    """Window snapshot of the INT8 quantization counters (layers
    quantized, calibration batches + wall time, requantize folds, and
    compiled int8 batch executions through the serve tier)."""
    with _sec_lock:
        d = dict(_sec)
    d["calib_ms"] = round(d["calib_ms"], 3)
    return d


def reset_quantize_stats():
    with _sec_lock:
        for k in _sec:
            _sec[k] = 0.0 if k == "calib_ms" else 0


def note_int8_serve_batch(n=1):
    """Book ``n`` compiled int8 batch executions (ModelServer batches,
    DecodeServer prefill groups and token steps through a quantized
    net) — called by the serve tier, outside any trace."""
    _sec_bump(int8_serve_batches=n)


# ---------------------------------------------------------------------------
# Calibration


def _get_optimal_threshold(arr, num_bins=8001, num_quantized_bins=255):
    """KL-divergence-optimal |x| clipping threshold (ref:
    _get_optimal_threshold in python/mxnet/contrib/quantization.py —
    the TensorRT-style entropy calibration).
    """
    a = np.abs(np.asarray(arr, np.float64).ravel())
    amax = float(a.max()) if a.size else 0.0
    if amax == 0.0:
        return 1e-8
    hist, edges = np.histogram(a, bins=num_bins, range=(0.0, amax))
    return _optimal_threshold_from_hist(hist, edges, num_quantized_bins)


def _optimal_threshold_from_hist(hist, edges, num_quantized_bins=255):
    """Histogram-based core of the KL search: the calibration collector
    feeds an incrementally-built |x| histogram (fixed memory per tensor,
    ref: calibrate.cc keeps histograms, never raw samples)."""
    num_bins = len(hist)
    amax = float(edges[-1])
    if amax <= 0.0 or hist.sum() == 0:
        return 1e-8

    def smooth(d, eps=1e-4):
        # redistribute eps mass onto zero bins (ref: _smooth_distribution)
        nz = d > 0
        if not nz.any():
            return None
        out = d.astype(np.float64).copy()
        n_zero = d.size - nz.sum()
        if n_zero:
            take = eps * n_zero / nz.sum()
            out[nz] -= take * out[nz] / out[nz].max()
            out[~nz] = eps
        return out / out.sum()

    best_kl, best_t = np.inf, amax
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, num_bins // 200)):
        sliced = hist[:i].astype(np.float64)
        # P includes the clipped tail mass in its edge bin; Q is built
        # from the histogram WITHOUT that mass — an aggressive threshold
        # gives P an edge spike Q cannot represent, which is exactly
        # what penalizes over-clipping.
        p = sliced.copy()
        p[-1] += hist[i:].sum()
        if p.sum() == 0:
            continue
        nm = i // num_quantized_bins
        q = np.zeros(i, np.float64)
        for j in range(num_quantized_bins):
            lo = j * nm
            hi = i if j == num_quantized_bins - 1 else lo + nm
            seg = sliced[lo:hi]
            nz = np.count_nonzero(seg)
            if nz:
                q[lo:hi] = seg.sum() / nz
        q[sliced == 0] = 0
        pn, qn = smooth(p), smooth(q)
        if pn is None or qn is None:
            continue
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(pn[mask] / qn[mask])))
        if kl < best_kl:
            best_kl = kl
            best_t = float(edges[i if i < len(edges) else -1])
    return max(best_t, 1e-8)


def _k_calib_stats(x, *, entropy=False, bins=_NUM_BINS):
    """Device-side calibration statistics for one batch: min/max, and in
    entropy mode the batch's |x| max plus a fixed-bin |x| histogram over
    [0, batch amax] — ONE device dispatch per (tensor, batch), with the
    host sync deferred to ``_Stats.finalize()``.  The old hook path
    called ``.asnumpy()`` on every layer's input AND output per batch
    (2·L blocking syncs per calibration batch)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mn = jnp.min(xf)
    mx = jnp.max(xf)
    if not entropy:
        return mn, mx
    ab = jnp.abs(xf).ravel()
    amax = jnp.max(ab)
    idx = jnp.clip((ab * (bins / jnp.maximum(amax, 1e-30)))
                   .astype(jnp.int32), 0, bins - 1)
    hist = jnp.zeros((bins,), jnp.float32).at[idx].add(1.0)
    return mn, mx, amax, hist


class _Stats:
    """Running calibration statistics for one tensor.

    Entropy mode keeps one fixed-size |x| histogram per tensor, updated
    batch-by-batch (ref: calibrate.cc accumulates histograms, never raw
    activations) — host memory is O(num_bins) regardless of how much
    calibration data flows through.

    Two update paths: ``update(numpy)`` accumulates on the host;
    ``update_nd(NDArray)`` accumulates per-batch partials ON DEVICE
    (min, max, |x| histogram against the batch's own amax) and defers
    the host transfer to ``finalize()`` — one sync per tensor per
    ``DRAIN_EVERY`` batches (one total for typical calibration sets),
    and device memory stays bounded at ``DRAIN_EVERY`` histograms per
    tensor however much data flows through."""

    NUM_BINS = _NUM_BINS
    #: auto-finalize threshold: caps device-resident partials at
    #: DRAIN_EVERY x (NUM_BINS+3) floats per tensor (~2 MB) so a huge
    #: calibration sweep cannot accumulate per-batch histograms without
    #: bound — the sync amortizes 1/DRAIN_EVERY per batch instead of
    #: the old path's 2 blocking syncs per (tensor, batch)
    DRAIN_EVERY = 64

    def __init__(self, mode):
        self.mode = mode
        self.mn = np.inf
        self.mx = -np.inf
        self.hist = None
        self.amax = 0.0
        self._dev = []  # per-batch device partials, drained by finalize

    def update_nd(self, arr):
        from .._imperative import invoke

        outs = invoke(_k_calib_stats, arr, nondiff=True,
                      entropy=self.mode == "entropy")
        self._dev.append(outs)
        if len(self._dev) >= self.DRAIN_EVERY:
            self.finalize()

    def finalize(self):
        """Pull every device partial in ONE host sync and merge."""
        if not self._dev:
            return
        import jax.numpy as jnp

        parts = []
        for outs in self._dev:
            parts.extend(o._data.reshape(-1).astype(jnp.float32)
                         for o in outs)
        host = np.asarray(jnp.concatenate(parts))  # the one sync
        pos = 0
        rows = []
        for _ in self._dev:
            mn, mx = host[pos], host[pos + 1]
            pos += 2
            row = [float(mn), float(mx)]
            if self.mode == "entropy":
                amax = float(host[pos])
                pos += 1
                hist = host[pos:pos + self.NUM_BINS]
                pos += self.NUM_BINS
                row += [amax, hist]
            rows.append(row)
        self._dev = []
        self.mn = min([self.mn] + [r[0] for r in rows])
        self.mx = max([self.mx] + [r[1] for r in rows])
        if self.mode != "entropy":
            return
        gmax = max([self.amax] + [r[2] for r in rows])
        if gmax <= 0.0:
            return
        if self.hist is not None and gmax > self.amax:
            self.hist = self._rebin(self.hist, self.amax, gmax)
        merged = self.hist.astype(np.float64) if self.hist is not None \
            else np.zeros(self.NUM_BINS, np.float64)
        for _mn, _mx, amax, hist in rows:
            if amax <= 0.0:
                continue
            merged += self._rebin(hist.astype(np.float64), amax, gmax)
        self.hist = merged
        self.amax = gmax

    @classmethod
    def _rebin(cls, hist, from_amax, to_amax):
        """Map a histogram over [0, from_amax] onto [0, to_amax] by bin
        center (one-bin blur at worst) — the widening rule the host
        update path applies incrementally, reused for the batched
        device partials."""
        if from_amax == to_amax:
            return hist
        centers = (np.arange(cls.NUM_BINS) + 0.5) * (from_amax
                                                     / cls.NUM_BINS)
        new_idx = np.minimum(
            (centers / to_amax * cls.NUM_BINS).astype(np.int64),
            cls.NUM_BINS - 1)
        widened = np.zeros(cls.NUM_BINS, hist.dtype)
        np.add.at(widened, new_idx, hist)
        return widened

    def update(self, a):
        a = np.asarray(a)
        self.mn = min(self.mn, float(a.min()))
        self.mx = max(self.mx, float(a.max()))
        if self.mode != "entropy":
            return
        ab = np.abs(a.ravel().astype(np.float64))
        bmax = float(ab.max()) if ab.size else 0.0
        if self.hist is None:
            self.amax = max(bmax, 1e-12)
            self.hist = np.histogram(
                ab, bins=self.NUM_BINS, range=(0.0, self.amax))[0]
            return
        if bmax > self.amax:
            # widen: rebin the existing histogram onto the larger range
            # by bin center (one-bin blur at worst)
            self.hist = self._rebin(self.hist, self.amax, bmax)
            self.amax = bmax
        self.hist = self.hist + np.histogram(
            ab, bins=self.NUM_BINS, range=(0.0, self.amax))[0]

    def range(self):
        self.finalize()
        if self.mode == "entropy" and self.hist is not None:
            edges = np.linspace(0.0, self.amax, self.NUM_BINS + 1)
            t = _optimal_threshold_from_hist(self.hist, edges)
            return -t, t
        return self.mn, self.mx


def _iter_calib_batches(calib_data, num_calib_examples=None):
    """Yield numpy data batches from an iterator / NDArray / ndarray."""
    if isinstance(calib_data, (NDArray, np.ndarray)):
        yield np.asarray(calib_data.asnumpy() if isinstance(
            calib_data, NDArray) else calib_data)
        return
    seen = 0
    if hasattr(calib_data, "reset"):
        calib_data.reset()
    for batch in calib_data:
        # DataBatch duck-typing must not trip over numpy's .data
        # memoryview attribute
        data = batch.data[0] if (hasattr(batch, "data") and
                                 not isinstance(batch,
                                                (np.ndarray, NDArray))) \
            else batch
        if isinstance(data, (list, tuple)):
            data = data[0]
        arr = data.asnumpy() if isinstance(data, NDArray) else np.asarray(data)
        yield arr
        seen += arr.shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            return


def _collect_layer_stats(symbol, arg_params, aux_params, targets, calib_data,
                         calib_mode, data_name, num_calib_examples, ctx):
    """Forward calibration batches through the fp32 graph, recording
    stats for each target node's data input and output (ref:
    _LayerOutputCollector / collect_quantized_stat)."""
    handles = []
    keys = []
    for node in targets:
        src, oi = node.inputs[0]
        handles.append(Symbol(src, oi))
        keys.append((node.name, "data"))
        handles.append(Symbol(node, 0))
        keys.append((node.name, "out"))
    group = Group(handles)
    stats = {k: _Stats(calib_mode) for k in keys}
    # materialize batches once: calib_data may be a non-resettable
    # generator, and the first batch is needed for binding anyway
    batches = list(_iter_calib_batches(calib_data, num_calib_examples))
    if not batches:
        raise MXNetError("calibration data yielded no batches")
    args = dict(arg_params)
    args[data_name] = nd.array(batches[0], ctx=ctx)
    ex = group.bind(ctx, args, grad_req="null",
                    aux_states=dict(aux_params) if aux_params else None)
    t0 = time.monotonic()
    with profiler.op_scope("quantize.calibrate", cat="quantize"):
        for arr in batches:
            outs = ex.forward(is_train=False,
                              **{data_name: nd.array(arr, ctx=ctx)})
            # stats accumulate on device; range() below syncs each
            # tensor's partials exactly once
            for k, o in zip(keys, outs):
                stats[k].update_nd(o)
            _sec_bump(calib_batches=1)
        ranges = {k: s.range() for k, s in stats.items()}
    _sec_bump(calib_ms=(time.monotonic() - t0) * 1e3)
    return ranges


# ---------------------------------------------------------------------------
# Symbolic graph rewrite


def _offline_quantize(name, arr, qarg_params):
    """Quantize a parameter offline; store q/min/max (ref: the reference
    stores `<param>_quantize` plus range params in qarg_params)."""
    a = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
    q, qmin, qmax = _np_quantize(a)
    qarg_params[name + "_quantize"] = q
    qarg_params[name + "_min"] = qmin
    qarg_params[name + "_max"] = qmax
    return (sym.var(name + "_quantize"), sym.var(name + "_min"),
            sym.var(name + "_max"))


def quantize_model(symbol, arg_params, aux_params=None, data_names=("data",),
                   excluded_sym_names=(), calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   ctx=None, logger=None):
    """Quantize a symbolic model to INT8 (ref: quantize_model in
    python/mxnet/contrib/quantization.py).

    Returns ``(qsym, qarg_params, aux_params)``.  FullyConnected and
    Convolution nodes are rewritten to int8 kernels; everything else
    stays fp32, with dequantize stitching the boundaries.
    """
    from ..context import current_context

    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError(f"unsupported quantized_dtype {quantized_dtype!r}"
                         " (TPU build quantizes to signed int8)")
    ctx = ctx or current_context()
    aux_params = aux_params or {}
    nodes = _topo_order([symbol._node])
    targets = [n for n in nodes if n.op in _QUANTIZABLE
               and n.name not in set(excluded_sym_names)
               and n.inputs[1][0].op is None]  # weight must be a variable

    calib_tbl = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        calib_tbl = _collect_layer_stats(
            symbol, arg_params, aux_params, targets, calib_data, calib_mode,
            data_names[0], num_calib_examples, ctx)
        if logger:
            for k, v in calib_tbl.items():
                logger.info("calib %s: [%g, %g]", k, *v)

    qarg_params = {}
    target_ids = {id(n) for n in targets}
    rewritten = {}  # id(node) -> new node (for Symbol(node, idx) handles)

    def handle(src, oi):
        return Symbol(rewritten[id(src)], oi)

    for n in nodes:
        if n.op is None:
            rewritten[id(n)] = sym.var(n.name)._node
            continue
        ins = [handle(s, oi) for s, oi in n.inputs]
        if id(n) not in target_ids:
            rewritten[id(n)] = _make_op_symbol(n.op, ins, dict(n.attrs),
                                               name=n.name)._node
            continue
        # --- the quantized replacement -----------------------------------
        data_in = ins[0]
        dr = calib_tbl.get((n.name, "data"))
        qattrs = {"out_type": "int8"}
        if dr is not None:
            qattrs.update(min_calib_range=dr[0], max_calib_range=dr[1])
        qd = _make_op_symbol("_contrib_quantize_v2", [data_in], qattrs,
                             name=n.name + "_quantize")
        wname = n.inputs[1][0].name
        qw, wmin, wmax = _offline_quantize(wname, arg_params[wname],
                                           qarg_params)
        no_bias = len(n.inputs) < 3 or bool(n.attrs.get("no_bias", False))
        if not no_bias:
            bname = n.inputs[2][0].name
            qb, bmin, bmax = _offline_quantize(bname, arg_params[bname],
                                               qarg_params)
            q_ins = [qd[0], qw, qb, qd[1], qd[2], wmin, wmax, bmin, bmax]
        else:
            q_ins = [qd[0], qw, None, qd[1], qd[2], wmin, wmax]
            q_ins = [x for x in q_ins if x is not None]
        qop = ("_contrib_quantized_fully_connected"
               if n.op == "FullyConnected" else "_contrib_quantized_conv")
        attrs = dict(n.attrs)
        attrs.pop("cudnn_tune", None), attrs.pop("cudnn_off", None)
        attrs.pop("workspace", None)
        attrs["no_bias"] = no_bias
        qnode = _make_op_symbol(qop, q_ins, attrs, name=n.name + "_int8")
        out, omin, omax = qnode[0], qnode[1], qnode[2]
        orr = calib_tbl.get((n.name, "out"))
        if orr is not None:
            rq = _make_op_symbol(
                "_contrib_requantize", [out, omin, omax],
                {"min_calib_range": orr[0], "max_calib_range": orr[1]},
                name=n.name + "_requantize")
            out, omin, omax = rq[0], rq[1], rq[2]
        deq = _make_op_symbol("_contrib_dequantize", [out, omin, omax], {},
                              name=n.name + "_dequantize")
        rewritten[id(n)] = deq._node

    qsym = Symbol(rewritten[id(symbol._node)], symbol._index)
    # carry over the fp32 params the rewritten graph still references
    # (replaced weights drop out of list_arguments automatically)
    for name in qsym.list_arguments():
        if name not in qarg_params and name in arg_params:
            qarg_params[name] = arg_params[name]
    return qsym, qarg_params, dict(aux_params)


# ---------------------------------------------------------------------------
# Gluon net quantization


def _np_quantize(a):
    r = float(np.max(np.abs(a))) or 1e-8
    q = np.clip(np.round(a * (127.0 / r)), -127, 127).astype(np.int8)
    return nd.array(q), nd.array(np.float32(-r).reshape(())), \
        nd.array(np.float32(r).reshape(()))


def _np_quantize_per_channel(a, per_channel=True):
    """Offline symmetric int8 weight quantization with PER-OUTPUT-CHANNEL
    ranges (axis 0 for both Dense ``(U, I)`` and Conv ``(O, I, *k)``
    weights).  Per-tensor mode returns a length-1 range vector so the
    per-channel kernels serve both without a second code path."""
    a = np.asarray(a, np.float32)
    if per_channel and a.ndim >= 2:
        r = np.abs(a.reshape(a.shape[0], -1)).max(axis=1)
    else:
        r = np.abs(a).max().reshape(1)
    r = np.maximum(r, 1e-8).astype(np.float32)
    scale = 127.0 / r.reshape((-1,) + (1,) * (a.ndim - 1))
    q = np.clip(np.round(a * scale), -127, 127).astype(np.int8)
    return q, r


def _quantized_dense_forward(F, x, qweight, wscale, bias, in_min, in_max,
                             out_min, out_max, *, units, flatten, act,
                             calibrated, out_int8):
    """The compiled int8 Dense chain: quantize → int8×int8→int32 matmul
    (per-channel scales, bias folded into the int32 accumulator) →
    requantize → dequantize.  Runs identically eager and under graph
    capture; an int8 input (a folded upstream boundary) skips the
    quantize stage and is interpreted at the in_min/in_max range.
    Everything after ``*`` is a STATIC structural attribute (the
    kw-only convention the trace-safety lints key on)."""
    if str(x.dtype) == "int8":
        if not calibrated:
            raise MXNetError(
                "an int8 input needs calibrated ranges to interpret "
                "it: this quantized layer was built without "
                "calibration (dynamic ranges) — quantize the whole "
                "chain with calib_data= so the boundary range is known")
        qx, dmn, dmx = x, in_min, in_max
    elif calibrated:
        qx, dmn, dmx = F.contrib.quantize(x, in_min, in_max)
    else:
        qx, dmn, dmx = F.contrib.quantize_v2(x)
    if bias is None:
        acc, omn, omx = F.contrib.quantized_dense_pc(
            qx, qweight, wscale, dmn, dmx, num_hidden=units,
            no_bias=True, flatten=flatten)
    else:
        acc, omn, omx = F.contrib.quantized_dense_pc(
            qx, qweight, wscale, bias, dmn, dmx, num_hidden=units,
            flatten=flatten)
    return _finish_quantized(F, acc, omn, omx, out_min, out_max,
                             act=act, calibrated=calibrated,
                             out_int8=out_int8)


def _quantized_conv_forward(F, x, qweight, wscale, bias, in_min, in_max,
                            out_min, out_max, *, conv_kwargs, act,
                            calibrated, out_int8):
    """The compiled int8 Convolution chain (see
    ``_quantized_dense_forward``)."""
    if str(x.dtype) == "int8":
        if not calibrated:
            raise MXNetError(
                "an int8 input needs calibrated ranges to interpret "
                "it: this quantized layer was built without "
                "calibration (dynamic ranges) — quantize the whole "
                "chain with calib_data= so the boundary range is known")
        qx, dmn, dmx = x, in_min, in_max
    elif calibrated:
        qx, dmn, dmx = F.contrib.quantize(x, in_min, in_max)
    else:
        qx, dmn, dmx = F.contrib.quantize_v2(x)
    if bias is None:
        acc, omn, omx = F.contrib.quantized_conv_pc(
            qx, qweight, wscale, dmn, dmx, no_bias=True, **conv_kwargs)
    else:
        acc, omn, omx = F.contrib.quantized_conv_pc(
            qx, qweight, wscale, bias, dmn, dmx, **conv_kwargs)
    return _finish_quantized(F, acc, omn, omx, out_min, out_max,
                             act=act, calibrated=calibrated,
                             out_int8=out_int8)


def _finish_quantized(F, acc, omn, omx, out_min, out_max, *, act,
                      calibrated, out_int8):
    """Close the chain: calibrated relu/linear layers requantize the
    int32 accumulator to the calibrated int8 range (relu applied in
    int8 — symmetric scaling commutes with it), then either hand the
    int8 tensor straight to a folded consumer or dequantize to fp32.
    Other activations dequantize first (requantizing a pre-activation
    accumulator to a post-activation range would clip wrongly)."""
    if calibrated and act in (None, "relu"):
        q8, rmn, rmx = F.contrib.requantize_v2(acc, omn, omx, out_min,
                                               out_max, act=act)
        if out_int8:
            return q8
        return F.contrib.dequantize(q8, rmn, rmx)
    out = F.contrib.dequantize(acc, omn, omx)
    if act:
        out = F.Activation(out, act_type=act)
    return out


class _QuantizedBase:
    """Shared machinery of the int8 wrapper blocks: parameter creation
    from concrete host arrays, calibrated-range parameters, and hot
    re-quantization for fp32 weight reloads."""

    def _adopt_params(self, layer, data_range, out_range, per_channel):
        self._per_channel = bool(per_channel)
        self._calibrated = data_range is not None
        self._out_int8 = False
        ctxs = layer.weight.list_ctx()
        q, r = _np_quantize_per_channel(layer.weight.data().asnumpy(),
                                        self._per_channel)
        self.qweight = self._make_param("qweight", q, ctxs)
        self.wscale = self._make_param("wscale", r, ctxs)
        self.bias = (self._make_param(
            "bias", layer.bias.data().asnumpy(), ctxs)
            if layer.bias is not None else None)
        if self._calibrated:
            self.in_min = self._make_param(
                "in_min", np.float32(data_range[0]), ctxs)
            self.in_max = self._make_param(
                "in_max", np.float32(data_range[1]), ctxs)
            orr = out_range if out_range is not None else data_range
            self.out_min = self._make_param(
                "out_min", np.float32(orr[0]), ctxs)
            self.out_max = self._make_param(
                "out_max", np.float32(orr[1]), ctxs)

    def _make_param(self, name, arr, ctxs):
        arr = np.asarray(arr)
        p = self.params.get(name, shape=arr.shape, dtype=str(arr.dtype),
                            differentiable=False)
        p._data = {c: nd.array(arr, ctx=c, dtype=str(arr.dtype))
                   for c in ctxs}
        return p

    def requantize_from(self, weight, bias=None):
        """Re-quantize this layer from fresh fp32 weights AGAINST THE
        STORED per-channel scales (and keep the calibrated activation
        ranges) — the hot-reload contract: every range/scale is a
        runtime graph input, so a reload swaps numbers without a single
        recompile.  Weights that drifted beyond the stored scale clip;
        re-run ``quantize_net`` on a fresh twin if calibration is
        stale."""
        w = weight.asnumpy() if isinstance(weight, NDArray) \
            else np.asarray(weight, np.float32)
        r = self.wscale.data().asnumpy()
        scale = 127.0 / r.reshape((-1,) + (1,) * (w.ndim - 1))
        q = np.clip(np.round(w * scale), -127, 127).astype(np.int8)
        self.qweight.set_data(nd.array(q))
        if self.bias is not None:
            if bias is None:
                raise MXNetError(
                    f"quantized layer {self.name!r} has a bias but the "
                    "reload supplied none")
            b = bias if isinstance(bias, NDArray) else nd.array(
                np.asarray(bias, np.float32))
            self.bias.set_data(b)


def _check_nd_input(x):
    if not isinstance(x, NDArray):
        raise MXNetError(
            "quantized blocks do not support symbolic export; serve "
            "them directly through ModelServer/DecodeServer (the "
            "compiled path) instead")


class QuantizedDense(_QuantizedBase, _gluon_block.HybridBlock):
    """Compile-native int8 replacement for ``nn.Dense``.

    A REAL HybridBlock: the quantize → int8 matmul → requantize/bias →
    dequantize chain re-traces through ``traced_apply`` into whatever
    graph contains it (a hybridized net's CachedOp, a DecodeServer
    CachedStepOp), and the quantized weight, per-channel scale vector,
    fp32 bias, and calibrated ranges are Parameters — runtime inputs of
    the compiled graph, so checkpointing, ``save_parameters`` and hot
    weight reloads all work with zero recompiles."""

    def __init__(self, layer, data_range=None, out_range=None,
                 per_channel=True):
        super().__init__(prefix=layer._prefix, params=None)
        self._units = layer._units
        self._flatten = layer._flatten
        self._activation = layer._activation
        self._adopt_params(layer, data_range, out_range, per_channel)

    def hybrid_forward(self, F, x, qweight, wscale, bias=None,
                       in_min=None, in_max=None, out_min=None,
                       out_max=None):
        _check_nd_input(x)
        return _quantized_dense_forward(
            F, x, qweight, wscale, bias, in_min, in_max, out_min,
            out_max, units=self._units, flatten=self._flatten,
            act=self._activation, calibrated=self._calibrated,
            out_int8=self._out_int8)


class QuantizedConv(_QuantizedBase, _gluon_block.HybridBlock):
    """Compile-native int8 replacement for ``nn.Conv2D`` (NCHW-layout
    forward convolutions; see :class:`QuantizedDense`)."""

    def __init__(self, layer, data_range=None, out_range=None,
                 per_channel=True):
        super().__init__(prefix=layer._prefix, params=None)
        kw = dict(layer._kwargs)
        for drop in ("layout", "no_bias", "adj"):
            kw.pop(drop, None)
        self._kwargs = kw
        self._activation = layer._activation
        self._adopt_params(layer, data_range, out_range, per_channel)

    def hybrid_forward(self, F, x, qweight, wscale, bias=None,
                       in_min=None, in_max=None, out_min=None,
                       out_max=None):
        _check_nd_input(x)
        return _quantized_conv_forward(
            F, x, qweight, wscale, bias, in_min, in_max, out_min,
            out_max, conv_kwargs=self._kwargs, act=self._activation,
            calibrated=self._calibrated, out_int8=self._out_int8)


def _quantizable(child, exclude):
    """Dense, or a forward NC*-layout Convolution block (the transpose
    and channel-last variants stay fp32 — the bypass matrix in
    docs/quantization.md)."""
    from ..gluon import nn as gnn
    from ..gluon.nn.conv_layers import _Conv

    if child.name in exclude:
        return False
    if isinstance(child, gnn.Dense):
        return True
    return (isinstance(child, _Conv)
            and getattr(child, "_op_name", None) == "Convolution"
            and not getattr(child, "_channel_last", False))


def _release_stale_caches(block):
    """Drop compiled fp32 graphs after the rewrite — a hybridized
    ancestor would otherwise keep serving the ORIGINAL layers out of
    its CachedOp.  Hybridization itself stays active: the next call
    re-captures through the int8 wrappers into a fresh executable."""
    op = getattr(block, "_cached_op", None)
    if op is not None:
        op.release()
        block._cached_op = None
    for child in getattr(block, "_children", {}).values():
        _release_stale_caches(child)


def _calibrate_gluon(network, targets, calib_data, calib_mode,
                     num_calib_examples, calib_forward):
    """Forward calibration batches through the fp32 net with hooks on
    every target layer accumulating min/max (and entropy histograms)
    ON DEVICE — one host sync per (layer, tensor) at the end, not
    2·L syncs per batch."""
    stats = {id(t[2]): (_Stats(calib_mode), _Stats(calib_mode))
             for t in targets}
    hooks = []
    for _, _, layer in targets:
        def hook(block, inputs, output, _s=stats):
            s_in, s_out = _s[id(block)]
            s_in.update_nd(inputs[0])
            out = output[0] if isinstance(output, (tuple, list)) \
                else output
            s_out.update_nd(out)
        hooks.append(layer.register_forward_hook(hook))
    # calibration needs EAGER child forwards (hooks fire per batch with
    # concrete tensors); temporarily deactivate any hybridized block so
    # a CachedOp can't swallow the layer calls, restore after
    deactivated = []

    def _deact(b):
        if getattr(b, "_active", False):
            deactivated.append(b)
            b._active = False
        for c in getattr(b, "_children", {}).values():
            _deact(c)

    _deact(network)
    t0 = time.monotonic()
    try:
        with profiler.op_scope("quantize.calibrate", cat="quantize"):
            n = 0
            for arr in _iter_calib_batches(calib_data,
                                           num_calib_examples):
                x = nd.array(arr)
                if calib_forward is not None:
                    calib_forward(network, x)
                else:
                    network(x)
                n += 1
                _sec_bump(calib_batches=1)
            if n == 0:
                raise MXNetError("calibration data yielded no batches")
            ranges = {}
            uncovered = []
            for _, _, layer in targets:
                s_in, s_out = stats[id(layer)]
                # range() drains each tensor's device partials in one
                # sync
                r_in, r_out = s_in.range(), s_out.range()
                # a layer the calibration forward never exercised has
                # (inf, -inf) stats; silently installing those as
                # calibrated ranges would serve NaNs with no error
                if not np.isfinite(r_in).all() \
                        or not np.isfinite(r_out).all():
                    uncovered.append(layer.name)
                    continue
                ranges[id(layer)] = (r_in, r_out)
            if uncovered:
                raise MXNetError(
                    f"calibration never exercised quantizable layer(s) "
                    f"{uncovered}: the calibration forward "
                    f"({'calib_forward' if calib_forward is not None else 'network(x)'}) "
                    "must run every layer being quantized — cover the "
                    "missing path or list the layer in exclude_layers")
    finally:
        for h in hooks:
            h.detach()
        for b in deactivated:
            b._active = True
    _sec_bump(calib_ms=(time.monotonic() - t0) * 1e3)
    return ranges


def _fold_requantize(network):
    """Range-propagation fusion: for consecutive calibrated quantized
    layers inside a Sequential/HybridSequential, fold the producer's
    ``requantize → dequantize`` + the consumer's ``quantize`` boundary
    into the producer's single requantize — the producer emits int8 at
    its calibrated output range and the consumer consumes it at that
    exact range (both hooks saw the same tensor, so the dequantize →
    quantize round trip this removes was the identity up to fp32
    rounding).  Only linear/relu producers fold: symmetric int8
    commutes with relu, not with other activations."""
    folds = 0

    def walk(block):
        nonlocal folds
        layers = getattr(block, "_layers", None)
        if layers:
            for a, b in zip(layers, layers[1:]):
                if (isinstance(a, (QuantizedDense, QuantizedConv))
                        and isinstance(b, (QuantizedDense,
                                           QuantizedConv))
                        and a._calibrated and b._calibrated
                        and a._activation in (None, "relu")):
                    a._out_int8 = True
                    # the int8 boundary travels at the PRODUCER's
                    # calibrated output range
                    b.in_min.set_data(a.out_min.data())
                    b.in_max.set_data(a.out_max.data())
                    folds += 1
        for child in getattr(block, "_children", {}).values():
            walk(child)

    walk(network)
    return folds


def quantize_net(network, calib_data=None, calib_mode="naive",
                 exclude_layers=None, num_calib_examples=None,
                 quantized_dtype="int8", per_channel=True, fold=True,
                 calib_forward=None):
    """Quantize a Gluon network's Dense/Conv2D layers to INT8 in place
    (ref: quantize_net in python/mxnet/contrib/quantization.py) — the
    result is a COMPILABLE net: it hybridizes into one XLA executable
    whose int8×int8→int32 matmuls/convs hit the MXU natively, serves
    through ModelServer/DecodeServer with zero post-warmup compiles,
    checkpoints through CheckpointManager, and hot-reloads fp32
    training weights via re-quantization.

    With ``calib_data``, activation ranges are calibrated ('naive'
    min/max or 'entropy' KL) by device-side hooks (one host sync per
    layer); without, ranges are computed per batch inside the compiled
    graph.  ``per_channel`` uses per-output-channel weight scales
    (default; per-tensor otherwise); ``fold`` keeps activations int8
    across adjacent calibrated layers; ``calib_forward(net, batch)``
    overrides the calibration forward for models without a plain
    ``__call__`` (e.g. decode models: ``lambda m, x: m.prefill(...)``).
    """
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError(f"unsupported quantized_dtype {quantized_dtype!r}"
                         " (TPU build quantizes to signed int8)")
    exclude = set(exclude_layers or ())
    targets = []  # (parent, child_key, layer)

    def walk(block):
        for key, child in list(block._children.items()):
            if _quantizable(child, exclude):
                targets.append((block, key, child))
            else:
                walk(child)

    walk(network)
    ranges = {}
    if calib_data is not None and calib_mode != "none":
        ranges = _calibrate_gluon(network, targets, calib_data,
                                  calib_mode, num_calib_examples,
                                  calib_forward)

    from ..gluon import nn as gnn

    for parent, key, layer in targets:
        dr, orr = ranges.get(id(layer), (None, None))
        wrapper_cls = (QuantizedDense if isinstance(layer, gnn.Dense)
                       else QuantizedConv)
        wrapper = wrapper_cls(layer, data_range=dr, out_range=orr,
                              per_channel=per_channel)
        parent._children[key] = wrapper
        # Sequential/HybridSequential iterate _layers, not _children
        layers = getattr(parent, "_layers", None)
        if layers is not None:
            for i, l in enumerate(layers):
                if l is layer:
                    layers[i] = wrapper
        # keep attribute access (net.fc1) pointing at the wrapper too
        for attr, val in list(vars(parent).items()):
            if val is layer:
                object.__setattr__(parent, attr, wrapper)
    _sec_bump(layers_quantized=len(targets))

    if fold and ranges:
        folds = _fold_requantize(network)
        _sec_bump(requant_folds=folds)

    _release_stale_caches(network)
    network._int8_quantized = True
    return network


# ---------------------------------------------------------------------------
# serving-tier reload: fp32 training checkpoints into a quantized net


def _iter_quantized(block, prefix=""):
    for name, child in getattr(block, "_children", {}).items():
        p = prefix + name + "."
        if isinstance(child, (QuantizedDense, QuantizedConv)):
            yield p, child
        else:
            yield from _iter_quantized(child, p)


def apply_fp32_params(qnet, loaded):
    """Re-quantize a quantized net in place from an fp32 twin's
    structural ``name -> NDArray`` dict (what a training checkpoint or
    ``save_parameters`` of the un-quantized architecture holds): each
    quantized layer's weight is re-quantized against its STORED
    per-channel scales, biases are copied, calibrated activation
    ranges are kept, and every non-quantized parameter lands directly.
    Loud on any structural mismatch."""
    loaded = dict(loaded)
    wrappers = dict(_iter_quantized(qnet))
    if not wrappers:
        raise MXNetError(
            "apply_fp32_params: network has no quantized layers — run "
            "contrib.quantization.quantize_net first")
    for path, wrapper in wrappers.items():
        wkey = path + "weight"
        if wkey not in loaded:
            raise MXNetError(
                f"fp32 reload: checkpoint is missing {wkey!r} for "
                f"quantized layer {wrapper.name!r} — was it saved from "
                "a different architecture?")
        w = loaded.pop(wkey)
        b = loaded.pop(path + "bias", None)
        wrapper.requantize_from(w, b)
    rest = {k: v for k, v in
            qnet._collect_params_with_prefix().items()
            if not any(k.startswith(p) for p in wrappers)}
    extra = sorted(set(loaded) - set(rest))
    missing = sorted(set(rest) - set(loaded))
    if extra or missing:
        raise MXNetError(
            "fp32 reload: parameter names do not line up with the "
            f"quantized net (extra in checkpoint: {extra}; missing "
            f"from checkpoint: {missing})")
    for k, v in loaded.items():
        rest[k].set_data(v)


def load_serving_params(net, loaded):
    """Hot-reload dispatch for quantized serving nets: an int8-native
    dict (saved FROM the quantized net) restores directly; an fp32
    dict (the training twin's checkpoint) re-quantizes through
    :func:`apply_fp32_params`.  ModelServer/DecodeServer
    ``reload_weights()`` route here when the served net is quantized."""
    if not loaded:
        raise MXNetError(
            "reload: checkpoint holds no parameters (saved without "
            "params=?)")
    own = net._collect_params_with_prefix()
    if any(k.endswith("qweight") for k in loaded):
        extra = sorted(set(loaded) - set(own))
        missing = sorted(set(own) - set(loaded))
        if extra or missing:
            raise MXNetError(
                "int8 reload: parameter names do not line up with the "
                f"quantized net (extra in checkpoint: {extra}; missing "
                f"from checkpoint: {missing})")
        for k, p in own.items():
            p.set_data(loaded[k])
    else:
        apply_fp32_params(net, loaded)
