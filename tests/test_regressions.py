"""Regression tests for review findings (round 1 code review)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.base import MXNetError


def test_rnn_interlayer_dropout_active():
    from mxnet_tpu.ops.rnn import rnn_param_size

    T, N, I, H, L = 6, 4, 8, 16, 2
    psize = rnn_param_size(L, I, H, "lstm")
    params = nd.random.uniform(-0.5, 0.5, shape=(psize,))
    x = nd.random.uniform(shape=(T, N, I))
    h0, c0 = nd.zeros((L, N, H)), nd.zeros((L, N, H))
    with autograd.record():
        a, _, _ = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L,
                         mode="lstm", p=0.9)
        b, _, _ = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L,
                         mode="lstm", p=0.9)
    assert not np.allclose(a.asnumpy(), b.asnumpy()), \
        "inter-layer dropout must be stochastic under training"
    # and without dropout it is deterministic
    c, _, _ = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L,
                     mode="lstm")
    d, _, _ = nd.RNN(x, params, h0, c0, state_size=H, num_layers=L,
                     mode="lstm")
    assert np.allclose(c.asnumpy(), d.asnumpy())


def test_newaxis_with_array_index():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    out = x[None, nd.array([0, 1], dtype="int32")]
    assert out.shape == (1, 2, 4)
    assert np.allclose(out.asnumpy()[0], np.arange(8).reshape(2, 4))


def test_dropout_mode_always_outside_training():
    x = nd.ones((64, 64))
    y = nd.Dropout(x, p=0.5, mode="always")
    frac_zero = (y.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7, "mode='always' must drop outside training"


def test_sequence_mask_flag_false():
    x = nd.ones((3, 2))
    out = nd.SequenceMask(x, nd.array([1, 1]), use_sequence_length=False)
    assert np.isclose(out.asnumpy().sum(), 6.0)


def test_zeros_like_preserves_context():
    a = nd.ones((2, 2), ctx=mx.xla(3))
    z = nd.zeros_like(a)
    assert z.context.device_id == 3
    o = nd.ones_like(a)
    assert o.context.device_id == 3


def test_bool_scalar_index():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert x[True].shape == (1, 3, 4)
    assert x[False].shape == (0, 3, 4)


def test_take_mode_raise():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    with pytest.raises(MXNetError):
        nd.take(x, nd.array([5], dtype="int32"), axis=0, mode="raise")
    ok = nd.take(x, nd.array([2], dtype="int32"), axis=0, mode="raise")
    assert np.allclose(ok.asnumpy()[0], [8, 9, 10, 11])


def test_setitem_newaxis_array_mix():
    x = nd.zeros((3, 4))
    x[nd.array([0, 2], dtype="int32")] = 5.0
    assert np.allclose(x.asnumpy()[[0, 2]], 5)
    assert np.allclose(x.asnumpy()[1], 0)


def test_signum_descends():
    """Review finding: Signum must perform gradient DEscent."""
    from mxnet_tpu import optimizer as opt

    o = opt.create("signum", learning_rate=0.01)
    w = nd.array([1.0])
    state = o.create_state(0, w)
    for _ in range(20):
        g = 2 * w  # grad of w^2
        o.update(0, w, g, state)
    assert abs(w.asscalar()) < 1.0, w.asscalar()


def test_accuracy_2d_label():
    from mxnet_tpu import metric

    acc = metric.Accuracy()
    acc.update(nd.array([[1], [0]]), nd.array([[0.1, 0.9], [0.8, 0.2]]))
    assert acc.get()[1] == 1.0


def test_sigmoid_bce_pos_weight():
    from mxnet_tpu.gluon.loss import SigmoidBinaryCrossEntropyLoss

    loss_fn = SigmoidBinaryCrossEntropyLoss()
    pred = nd.array([[0.5]])
    label = nd.array([[1.0]])
    base = loss_fn(pred, label).asscalar()
    weighted = loss_fn(pred, label, None, nd.array([10.0])).asscalar()
    assert np.isclose(weighted, 10 * base, atol=1e-5)


def test_rmsprop_centered_state():
    from mxnet_tpu import optimizer as opt

    o = opt.create("rmsprop", centered=True, learning_rate=0.01)
    w = nd.array([1.0])
    state = o.create_state(0, w)
    assert isinstance(state, tuple) and len(state) == 3
    for _ in range(30):
        o.update(0, w, 2 * w, state)
    assert abs(w.asscalar()) < 1.0


def test_trainer_num_update_once_per_step_multictx():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    net = nn.Dense(1, in_units=2)
    ctxs = [mx.xla(0), mx.xla(1)]
    net.initialize(ctx=ctxs)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    from mxnet_tpu import autograd as ag

    for step in range(3):
        for ctx in ctxs:
            x = nd.ones((2, 2), ctx=ctx)
            with ag.record():
                loss = net(x).sum()
            loss.backward()
        trainer.step(4)
    assert trainer._optimizer.num_update == 3
    # replicas stay in sync
    w0 = net.weight.data(ctxs[0]).asnumpy()
    w1 = net.weight.data(ctxs[1]).asnumpy()
    assert np.allclose(w0, w1)


def test_kvstore_dist_single_process_fallback():
    from mxnet_tpu import kvstore

    kv = kvstore.create("dist_sync")
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init("w", nd.ones((2,)))
    kv.push("w", [nd.ones((2,)) * 3])
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 3.0)
    kv.barrier()


def test_cached_op_eviction():
    from mxnet_tpu import _imperative
    from mxnet_tpu.gluon import nn

    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.hybridize()
    net(nd.ones((1, 2)))
    size_before = len(_imperative._jit_cache)
    net.hybridize(False)  # clears + evicts
    assert len(_imperative._jit_cache) < size_before


def test_tree_reduce_multi_device():
    """Eager kvstore reduce is a pairwise tree (ref: comm_tree.h
    CommDeviceTree) — sums from many devices must match numpy exactly
    regardless of the reduction shape."""
    import jax

    from mxnet_tpu.kvstore import _reduce_sum

    devs = jax.devices()
    for n in (2, 3, 5, 8):
        vals = [nd.array(np.full((4, 3), float(i + 1)),
                         ctx=mx.Context("cpu", i % len(devs)))
                for i in range(n)]
        out = _reduce_sum(vals, mx.Context("cpu", 0))
        expect = np.full((4, 3), sum(range(1, n + 1)), np.float32)
        assert np.allclose(out.asnumpy(), expect)
        assert out.context.device_id == 0


def test_eager_dispatch_overhead_bounded():
    """SURVEY §3.1 names the per-op eager path THE overhead risk; the
    executable cache must keep cached dispatch under a loose wall-clock
    bound (bench.py reports the precise figure per round)."""
    import time

    a, b = nd.ones((8, 8)), nd.ones((8, 8))
    (a + b).wait_to_read()  # populate the executable cache
    n = 200
    best = None
    for _ in range(3):  # best-of-3 windows: min() shrugs off CI load
        t0 = time.perf_counter()
        for _ in range(n):
            c = a + b
        c.wait_to_read()
        w = (time.perf_counter() - t0) / n * 1e6
        best = w if best is None or w < best else best
    # measured ~9us/op after the r5 fast path (hand-inlined invoke +
    # list-based buffer tracking — at this box's raw jit-call floor);
    # ~4-5x headroom catches a regression toward retrace-per-call
    # (~ms) while absorbing normal machine variance
    # (VERDICT r2 weak #7: the old 1000us bound only caught 70x)
    assert best < 40, f"eager dispatch {best:.0f}us/op (bound 40)"


def test_every_registered_op_renders_docs():
    """help(mx.nd.X) must work for the whole registry: build_doc and
    param introspection cannot crash for any op (the dmlc parameter.h
    self-documentation contract)."""
    from mxnet_tpu.ops import registry

    n = 0
    for name, entry in registry.canonical_items():
        doc = entry.build_doc()
        assert isinstance(doc, str) and doc, f"{name} doc is {doc!r}"
        entry.param_descriptors()
        n += 1
    assert n > 250, f"registry shrank? {n} canonical ops"


def test_generated_wrappers_importable_and_named():
    """Every generated nd.* wrapper carries its op name (stable repr
    for tooling and error messages)."""
    import mxnet_tpu.ndarray.ops as gen
    from mxnet_tpu.ops import registry

    for name, entry in registry.canonical_items():
        w = getattr(gen, name, None)
        if w is None:
            # internal scalar ops (_plus_scalar...) register lazily
            # during hybridize tracing — no public wrapper by design
            assert name.startswith("_"), f"{name} missing from nd.*"
            continue
        assert callable(w)
        if entry.wrapper is None:
            assert w.__name__ == name


def test_seeded_training_is_bitwise_reproducible():
    """Two identically-seeded hybridized training runs (with dropout)
    produce identical loss trajectories — the MXNET_TEST_SEED
    reproducibility convention (ref: test_utils.with_seed)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    def run():
        from mxnet_tpu.gluon.block import _BlockScope

        _BlockScope._counters.clear()
        mx.random.seed(42)
        np.random.seed(42)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dropout(0.5), gluon.nn.Dense(3))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        X = nd.array(np.random.RandomState(1).rand(32, 8)
                     .astype(np.float32))
        Y = nd.array((np.random.RandomState(2).rand(32) * 3)
                     .astype(np.float32))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        out = []
        for _ in range(6):
            with autograd.record():
                loss = loss_fn(net(X), Y)
            loss.backward()
            tr.step(32)
            out.append(float(loss.mean().asscalar()))
        return out

    assert run() == run()


def test_bucketing_repeat_bucket_no_recompile():
    """Same bucket key + same shapes => ZERO new XLA executables
    (VERDICT r2 #7: the per-bucket executable cache is the long-context
    scaling story; a silent retrace-per-batch would destroy it)."""
    from mxnet_tpu import _imperative, sym
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.module import BucketingModule

    np.random.seed(5)

    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="shared_fc",
                                flatten=False)
        pooled = sym.mean(fc, axis=1)
        out = sym.SoftmaxOutput(pooled, sym.var("softmax_label"),
                                name="softmax")
        return out, ("data",), ("softmax_label",)

    def make_batch(seq_len, bs=4):
        return DataBatch(
            [nd.array(np.random.rand(bs, seq_len, 6))],
            [nd.array(np.random.randint(0, 4, bs))],
            bucket_key=seq_len,
            provide_data=[DataDesc("data", (bs, seq_len, 6))],
            provide_label=[DataDesc("softmax_label", (bs,))])

    mod = BucketingModule(sym_gen, default_bucket_key=10, context=mx.cpu())
    mod.bind([DataDesc("data", (4, 10, 6))],
             [DataDesc("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    def step(seq_len):
        batch = make_batch(seq_len)
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()

    for seq_len in (10, 5, 20):  # populate each bucket's executables
        step(seq_len)
    baseline = _imperative.compiled_executable_count()
    assert baseline > 0  # the counter actually sees the executables
    for seq_len in (10, 5, 20, 20, 5, 10):  # warm buckets only
        step(seq_len)
    after = _imperative.compiled_executable_count()
    assert after == baseline, (
        f"revisiting warm buckets compiled {after - baseline} new "
        f"executables (cache keying broke)")


def test_bench_roofline_bound_computed():
    """bench.py's roofline_mfu_bound must be COMPUTED from the step's
    cost analysis (VERDICT r2 weak #3: the hardcoded 0.20 was silently
    None for any other config and wrong if the model changed)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    class Dev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    # v5e: 819e9 B/s, 197e12 FLOP/s. AI = flops/bytes.
    # flops=1.57e12, bytes=32e9 -> AI~49 -> bound ~49*819e9/197e12 ~ 0.204
    b = bench._roofline_bound(1.57e12, 32e9, Dev())
    assert b is not None and abs(b - 0.2040) < 0.002, b
    # compute-bound case caps at 1.0
    assert bench._roofline_bound(1e15, 1e9, Dev()) == 1.0
    # CPU or unknown chip -> None

    class Cpu:
        platform = "cpu"
        device_kind = "cpu"

    assert bench._roofline_bound(1e12, 1e9, Cpu()) is None
    assert bench._roofline_bound(None, 1e9, Dev()) is None


def test_deferred_init_multictx_uses_input_context():
    """The deferred-init retry in _eager_forward must refetch params on
    the INPUT's context: with multi-context init and the input on a
    non-first context, a bare p.data() mixed device copies (r3 review
    find while wiring the fused conv path)."""
    from mxnet_tpu.gluon import nn

    c = nn.Conv2D(8, 3, padding=1, layout="NHWC")
    c.initialize(mx.init.Xavier(), ctx=[mx.xla(0), mx.xla(1)])
    x = nd.random.uniform(shape=(1, 5, 5, 4), ctx=mx.xla(1))
    out = c(x)  # first call: deferred-shape retry path
    assert out.context.device_id == 1
    assert out.shape == (1, 5, 5, 8)
