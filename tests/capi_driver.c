/* C frontend driver for the flat C ABI (tests/test_capi.py compiles and
 * runs this against lib/libmxtpu_capi.so).
 *
 * Ref: the role of cpp-package/ — a non-Python frontend exercising the
 * same flat C API the Python frontend rides (include/mxnet/c_api.h).
 * Exercises: init, op listing, NDArray round-trip, imperative invoke
 * with tensor + string + literal kwargs, error protocol, waitall.
 */
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

typedef void* NDArrayHandle;

extern const char* MXTPUGetLastError(void);
extern int MXTPUCAPIInit(const char* platform);
extern int MXTPUListAllOpNames(int* out_size, const char*** out_array);
extern int MXTPUNDArrayCreate(const void* data, const int64_t* shape,
                              int ndim, int dtype, const char* ctx,
                              NDArrayHandle* out);
extern int MXTPUNDArrayFree(NDArrayHandle h);
extern int MXTPUNDArrayGetShape(NDArrayHandle h, int* out_ndim,
                                int64_t* out_shape);
extern int MXTPUNDArrayGetDType(NDArrayHandle h, int* out_dtype);
extern int MXTPUNDArraySyncCopyToCPU(NDArrayHandle h, void* out,
                                     int64_t nbytes);
extern int MXTPUImperativeInvoke(const char* op_name, NDArrayHandle* in,
                                 int num_in, const char** keys,
                                 const char** vals, int num_kwargs,
                                 NDArrayHandle* out, int* num_out);
extern int MXTPUWaitAll(void);
extern int MXTPUNDArraySave(const char* fname, NDArrayHandle* handles,
                            const char** keys, int num);
extern int MXTPUNDArrayLoad(const char* fname, int* out_size,
                            NDArrayHandle** out_handles,
                            int* out_name_size, const char*** out_names);
extern int MXTPUOpGetDoc(const char* op_name, const char** out_doc);
extern int MXTPUGetVersion(const char** out);
extern int MXTPUNDArrayReshape(NDArrayHandle h, int ndim,
                               const int64_t* shape, NDArrayHandle* out);
extern int MXTPUNDArraySlice(NDArrayHandle h, int64_t begin, int64_t end,
                             NDArrayHandle* out);

#define CHECK(cond, msg)                                            \
  do {                                                              \
    if (!(cond)) {                                                  \
      fprintf(stderr, "FAIL %s: %s\n", msg, MXTPUGetLastError());   \
      return 1;                                                     \
    }                                                               \
  } while (0)

static void* thread_invoke(void* arg) {
  int* rc = (int*)arg;
  float d[4] = {1, 2, 3, 4};
  int64_t shp[1] = {4};
  NDArrayHandle x = NULL, outs[2];
  int n_out = 2;
  if (MXTPUNDArrayCreate(d, shp, 1, 0, "", &x) != 0) return NULL;
  if (MXTPUImperativeInvoke("relu", &x, 1, NULL, NULL, 0, outs,
                            &n_out) != 0) {
    MXTPUNDArrayFree(x);
    return NULL;
  }
  float out[4];
  if (MXTPUNDArraySyncCopyToCPU(outs[0], out, sizeof(out)) == 0 &&
      out[3] == 4.0f)
    *rc = 0;
  MXTPUNDArrayFree(outs[0]);
  MXTPUNDArrayFree(x);
  return NULL;
}

int main(int argc, char** argv) {
  const char* save_path = argc > 1 ? argv[1] : "/tmp/capi_saved.params";
  CHECK(MXTPUCAPIInit("cpu") == 0, "init");

  int n_ops = 0;
  const char** names = NULL;
  CHECK(MXTPUListAllOpNames(&n_ops, &names) == 0, "list ops");
  CHECK(n_ops > 200, "op registry size");
  int has_conv = 0;
  for (int i = 0; i < n_ops; ++i)
    if (strcmp(names[i], "Convolution") == 0) has_conv = 1;
  CHECK(has_conv, "Convolution registered");

  /* a 2x3 fp32 array, element-wise ops, reduce */
  float data[6] = {1, 2, 3, 4, 5, 6};
  int64_t shape[2] = {2, 3};
  NDArrayHandle a = NULL, b = NULL;
  CHECK(MXTPUNDArrayCreate(data, shape, 2, 0, "cpu(0)", &a) == 0,
        "create a");
  CHECK(MXTPUNDArrayCreate(data, shape, 2, 0, "", &b) == 0, "create b");

  int ndim = 0;
  int64_t got_shape[16];
  CHECK(MXTPUNDArrayGetShape(a, &ndim, got_shape) == 0, "get shape");
  CHECK(ndim == 2 && got_shape[0] == 2 && got_shape[1] == 3, "shape vals");
  int dt = -1;
  CHECK(MXTPUNDArrayGetDType(a, &dt) == 0 && dt == 0, "dtype f32");

  /* broadcast_add(a, b) -> 2a */
  NDArrayHandle outs[4];
  int n_out = 4;
  NDArrayHandle ins[2] = {a, b};
  CHECK(MXTPUImperativeInvoke("broadcast_add", ins, 2, NULL, NULL, 0,
                              outs, &n_out) == 0, "broadcast_add");
  CHECK(n_out == 1, "one output");
  float sum[6];
  CHECK(MXTPUNDArraySyncCopyToCPU(outs[0], sum, sizeof(sum)) == 0,
        "copy out");
  for (int i = 0; i < 6; ++i)
    CHECK(sum[i] == 2 * data[i], "broadcast_add values");
  MXTPUNDArrayFree(outs[0]);

  /* kwargs: literal tuple + plain string (sum over axis as a tuple,
   * Activation's act_type as a raw string) */
  const char* k1[] = {"axis", "keepdims"};
  const char* v1[] = {"(1,)", "False"};
  n_out = 4;
  CHECK(MXTPUImperativeInvoke("sum", ins, 1, k1, v1, 2, outs, &n_out)
            == 0, "sum axis=(1,)");
  float rowsum[2];
  CHECK(MXTPUNDArraySyncCopyToCPU(outs[0], rowsum, sizeof(rowsum)) == 0,
        "copy rowsum");
  CHECK(rowsum[0] == 6 && rowsum[1] == 15, "rowsum values");
  MXTPUNDArrayFree(outs[0]);

  const char* k2[] = {"act_type"};
  const char* v2[] = {"relu"};
  n_out = 4;
  CHECK(MXTPUImperativeInvoke("Activation", ins, 1, k2, v2, 1, outs,
                              &n_out) == 0, "Activation relu");
  MXTPUNDArrayFree(outs[0]);

  /* error protocol: bad op name must fail with a message, not crash */
  n_out = 4;
  CHECK(MXTPUImperativeInvoke("NoSuchOp__", ins, 1, NULL, NULL, 0, outs,
                              &n_out) != 0, "bad op rejected");
  CHECK(strlen(MXTPUGetLastError()) > 0, "error message set");

  /* bad kwarg value must fail cleanly too */
  const char* k3[] = {"act_type"};
  const char* v3[] = {"bogus_activation"};
  n_out = 4;
  CHECK(MXTPUImperativeInvoke("Activation", ins, 1, k3, v3, 1, outs,
                              &n_out) != 0, "bad act_type rejected");

  CHECK(MXTPUWaitAll() == 0, "waitall");

  /* save in the reference-compatible .params container */
  const char* save_keys[] = {"weight_a", "weight_b"};
  NDArrayHandle pair[] = {a, b};
  CHECK(MXTPUNDArraySave(save_path, pair, save_keys, 2) == 0,
        "ndarray save");

  /* load the artifact back through the C boundary (ref: MXNDArrayLoad) */
  int ld_n = 0, ld_names_n = 0;
  NDArrayHandle* ld = NULL;
  const char** ld_names = NULL;
  CHECK(MXTPUNDArrayLoad(save_path, &ld_n, &ld, &ld_names_n, &ld_names)
            == 0, "ndarray load");
  CHECK(ld_n == 2 && ld_names_n == 2, "load count");
  int saw_a = 0;
  for (int i = 0; i < ld_n; ++i) {
    if (strcmp(ld_names[i], "weight_a") == 0) {
      float back[6];
      CHECK(MXTPUNDArraySyncCopyToCPU(ld[i], back, sizeof(back)) == 0,
            "copy loaded");
      for (int j = 0; j < 6; ++j)
        CHECK(back[j] == data[j], "loaded values");
      saw_a = 1;
    }
  }
  CHECK(saw_a, "weight_a present after load");
  for (int i = 0; i < ld_n; ++i)  /* caller-owned handles */
    MXTPUNDArrayFree(ld[i]);
  CHECK(MXTPUNDArrayLoad("/nonexistent/x.params", &ld_n, &ld,
                         &ld_names_n, &ld_names) != 0, "bad load rejected");

  /* version + view ops (MXGetVersion / MXNDArrayReshape64 / Slice) */
  {
    const char* ver = NULL;
    CHECK(MXTPUGetVersion(&ver) == 0 && ver && strlen(ver) > 0,
          "get version");
    NDArrayHandle r = NULL, s = NULL;
    int64_t new_shape[2] = {3, 2};
    CHECK(MXTPUNDArrayReshape(a, 2, new_shape, &r) == 0, "reshape");
    int nd2 = 0;
    int64_t d2[16];
    CHECK(MXTPUNDArrayGetShape(r, &nd2, d2) == 0 && nd2 == 2 &&
              d2[0] == 3 && d2[1] == 2, "reshaped dims");
    CHECK(MXTPUNDArraySlice(r, 1, 3, &s) == 0, "slice");
    float sl[4];
    CHECK(MXTPUNDArraySyncCopyToCPU(s, sl, sizeof(sl)) == 0,
          "copy slice");
    CHECK(sl[0] == 3 && sl[3] == 6, "slice values");
    int64_t bad_shape[1] = {7};
    NDArrayHandle t = NULL;
    CHECK(MXTPUNDArrayReshape(a, 1, bad_shape, &t) != 0,
          "bad reshape rejected");
    MXTPUNDArrayFree(r);
    MXTPUNDArrayFree(s);
  }

  /* op self-documentation crosses the ABI (dmlc parameter.h role) */
  const char* doc = NULL;
  CHECK(MXTPUOpGetDoc("Convolution", &doc) == 0 && doc &&
        strstr(doc, "kernel") != NULL, "Convolution doc has params");
  CHECK(MXTPUOpGetDoc("NoSuchOp__", &doc) != 0, "bad op doc rejected");

  /* any-thread contract: a second OS thread must be able to call in
   * (the embedded interpreter's GIL is released between calls) */
  pthread_t th;
  int thread_rc = -1;
  pthread_create(&th, NULL, thread_invoke, &thread_rc);
  pthread_join(th, NULL);
  CHECK(thread_rc == 0, "second-thread invoke");

  MXTPUNDArrayFree(a);
  MXTPUNDArrayFree(b);
  printf("CAPI_DRIVER_OK ops=%d\n", n_ops);
  return 0;
}
