"""Logging helpers (ref: python/mxnet/log.py).

`get_logger(name)` returns a configured `logging.Logger` with the
reference's level constants re-exported.
"""
from __future__ import annotations

import logging
import sys

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Return a logger wired to stderr (or `filename`) at `level`."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_configured", False):
        if filename is None:
            logger.setLevel(level)
            return logger
        # re-route to a file: drop the handler we installed earlier
        for h in list(logger.handlers):
            logger.removeHandler(h)
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False  # root may have its own handler (absl)
    logger._mxtpu_configured = True
    return logger
