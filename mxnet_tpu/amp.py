"""Automatic mixed precision (ref: python/mxnet/contrib/amp/ — fp16
cast lists + dynamic loss scaling).

TPU-native: the preferred low-precision dtype is bfloat16, which shares
float32's exponent range — under bf16 the dynamic scaler idles at
scale 1.  fp16 mode gets the reference's REAL dynamic loss scaling
(2^16 start, halve on overflow + skip update, double after a clean
scale_window).  ``init()`` records the policy; ``convert_model`` casts
a Gluon block (norm params stay fp32).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

_initialized = False
_target_dtype = "bfloat16"

# ops that benefit from low precision (MXU-bound) — ref: amp FP16_FUNCS
TARGET_DTYPE_OPS = ["FullyConnected", "Convolution", "Deconvolution",
                    "batch_dot", "dot", "RNN",
                    "scaled_dot_product_attention",
                    "multihead_attention"]
# ops that must stay fp32 (ref: FP32_FUNCS)
FP32_OPS = ["softmax", "log_softmax", "BatchNorm", "LayerNorm", "norm",
            "mean", "sum", "SoftmaxOutput", "exp", "log"]


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Ref: amp.init() — on TPU this records the policy; casting happens
    per-model via convert_model/convert_hybrid_block."""
    global _initialized, _target_dtype
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _target_dtype = target_dtype
    _initialized = True


def convert_model(block, target_dtype=None):
    """Cast a Gluon block's parameters to the AMP dtype, keeping
    normalization params in fp32 (the reference's cast-list split)."""
    dt = target_dtype or _target_dtype
    for name, p in block.collect_params().items():
        stem = name.rsplit("_", 1)[-1]
        if stem in ("gamma", "beta", "running_mean", "running_var",
                    "moving_mean", "moving_var"):
            continue
        p.cast(dt)
    if hasattr(block, "_clear_cache"):
        block._clear_cache()
    return block


convert_hybrid_block = convert_model


class LossScaler:
    """Dynamic loss scaler (ref: contrib/amp/loss_scaler.py).

    fp16's 5-bit exponent underflows small gradients; scaling the loss
    by ``loss_scale`` shifts gradients into range, and the optimizer
    divides it back out.  On overflow (non-finite grads) the scale
    halves and the update is skipped; after ``scale_window`` clean steps
    it doubles.  bf16 shares fp32's exponent range and needs none of
    this — pass ``init_scale=1`` (the bf16 default in ``scale_loss``).
    """

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_scale=1.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._min_scale = float(min_scale)
        self._unskipped = 0
        # armed iff constructed with a real scale; stays armed even if
        # overflows decay loss_scale down to 1.0 (the dynamics must keep
        # running so the scale can recover and overflows keep skipping)
        self.enabled = self.loss_scale != 1.0

    def scale(self, loss):
        if self.loss_scale == 1.0:
            return loss
        return loss * self.loss_scale

    def unscale(self, grads):
        if self.loss_scale == 1.0:
            return grads
        inv = 1.0 / self.loss_scale
        if isinstance(grads, (list, tuple)):
            return type(grads)(g * inv for g in grads)
        return grads * inv

    def has_overflow(self, grads):
        """True if any gradient contains a non-finite value.

        Device-side: one fused all-finite reduction per grad and a
        SINGLE scalar readback (ref: multi_all_finite), not a full
        D2H pull of every gradient.
        """
        import jax.numpy as jnp

        flag = None
        for g in grads:
            if g is None:
                continue
            raw = g._data if hasattr(g, "_data") else jnp.asarray(g)
            ok = jnp.all(jnp.isfinite(raw))
            flag = ok if flag is None else jnp.logical_and(flag, ok)
        return bool(not flag) if flag is not None else False

    def update(self, overflow):
        """Adjust the scale after a step; returns True iff the step
        must be skipped (ref: LossScaler.update_scale)."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor,
                                  self._min_scale)
            self._unskipped = 0
            return True
        self._unskipped += 1
        if self._unskipped >= self._scale_window:
            self.loss_scale *= self._scale_factor
            self._unskipped = 0
        return False


class _ScaleLossCtx:
    def __init__(self, loss, trainer):
        self._loss = loss
        self._trainer = trainer

    def __enter__(self):
        tr = self._trainer
        if getattr(tr, "_amp_loss_scaler", None) is None:
            # bf16 needs no scaling; fp16 gets the reference's 2^16 start
            init = 1.0 if _target_dtype == "bfloat16" else 2.0 ** 16
            tr._amp_loss_scaler = LossScaler(init_scale=init)
        scaler = tr._amp_loss_scaler
        # the optimizer divides the scale back out via rescale_grad
        tr._scale = tr._amp_original_scale / scaler.loss_scale
        if isinstance(self._loss, (list, tuple)):
            return type(self._loss)(scaler.scale(l) for l in self._loss)
        return scaler.scale(self._loss)

    def __exit__(self, *exc):
        return False


def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``
    (ref: amp.scale_loss) — scales the loss, points the trainer's
    rescale_grad at 1/scale, and arms the overflow-skip check in
    ``Trainer._update``."""
    if not hasattr(trainer, "_amp_original_scale"):
        trainer._amp_original_scale = trainer._scale
    return _ScaleLossCtx(loss, trainer)
