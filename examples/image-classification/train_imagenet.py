"""ResNet-50 on ImageNet — BASELINE config #2.

Ref: example/image-classification/train_imagenet.py +
benchmark_score.py --benchmark 1. Data comes from a RecordIO pack
(tools/im2rec.py) through ImageRecordIter's threaded decode pipeline;
--benchmark 1 switches to synthetic device-resident data to isolate
compute, exactly like the reference's benchmark mode.

Training runs on the compiled SPMD path (DataParallelTrainer): ONE XLA
computation per step containing forward, backward, the gradient
all-reduce over the ICI mesh ('dp' axis) and the SGD update with
parameter donation — the north-star translation of
kvstore('device') push/pull.

  # synthetic compute benchmark (single host, all local devices):
  python examples/image-classification/train_imagenet.py --benchmark 1

  # real data:
  python examples/image-classification/train_imagenet.py \
      --data-train ~/imagenet_train.rec --epochs 90
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import add_cpu_flag, apply_backend  # noqa: E402

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.parallel import data_parallel, mesh as mesh_mod


def build_net(args):
    builder = {"resnet18": vision.resnet18_v1,
               "resnet34": vision.resnet34_v1,
               "resnet50": vision.resnet50_v1,
               "resnet101": vision.resnet101_v1,
               "resnet50_v2": vision.resnet50_v2}[args.network]
    net = builder(classes=args.num_classes, layout=args.layout)
    net.initialize(mx.init.Xavier())
    return net


def data_source(args):
    """Yields (x, y) numpy batches; synthetic or ImageRecordIter."""
    c, h, w = (int(v) for v in args.image_shape.split(","))
    if args.benchmark:
        rng = np.random.RandomState(0)
        shape = (args.batch_size, h, w, c) if args.layout == "NHWC" \
            else (args.batch_size, c, h, w)
        x = rng.rand(*shape).astype(np.float32)
        y = rng.randint(0, args.num_classes,
                        args.batch_size).astype(np.float32)
        while True:
            yield x, y
    else:
        # dist workers read disjoint shards (the kv.num_workers/kv.rank
        # pattern; the launcher exports the DMLC_* env these default to)
        # same env chain as parallel/dist.py: MXTPU_* preferred, DMLC_*
        # (launcher protocol) as the fallback
        num_parts = args.num_parts or int(os.environ.get(
            "MXTPU_NUM_WORKER", os.environ.get("DMLC_NUM_WORKER", 1)))
        part_index = args.part_index if args.part_index >= 0 else int(
            os.environ.get("MXTPU_WORKER_ID",
                           os.environ.get("DMLC_WORKER_ID", 0)))
        it = mx.io.ImageRecordIter(
            path_imgrec=args.data_train, data_shape=(c, h, w),
            batch_size=args.batch_size, shuffle=True,
            num_parts=num_parts, part_index=part_index,
            rand_mirror=True,
            # the standard ImageNet recipe: area/aspect-sampled crops
            # + color jitter (ref: image_aug_default.cc defaults used by
            # example/image-classification)
            random_resized_crop=True, min_random_area=0.08,
            max_random_area=1.0, min_aspect_ratio=0.75,
            max_aspect_ratio=1.333, brightness=0.4, contrast=0.4,
            saturation=0.4,
            mean_r=123.68, mean_g=116.779, mean_b=103.939,
            std_r=58.393, std_g=57.12, std_b=57.375,
            preprocess_threads=args.data_nthreads)
        while True:
            it.reset()
            for batch in it:
                x = batch.data[0]
                if args.layout == "NHWC":
                    x = x.transpose((0, 2, 3, 1))
                yield x, batch.label[0]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50")
    p.add_argument("--data-train", default="")
    p.add_argument("--benchmark", type=int, default=0)
    p.add_argument("--batch-size", type=int, default=256,
                   help="global batch (split over the dp mesh axis)")
    p.add_argument("--image-shape", default="3,224,224")
    p.add_argument("--layout", default="NHWC",
                   choices=["NCHW", "NHWC"],
                   help="NHWC puts channels on the TPU's minormost "
                        "tile dim (fastest); NCHW matches the "
                        "reference default")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"],
                   help="compute dtype; master params stay fp32")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--lr-step-epochs", default="30,60,80")
    p.add_argument("--data-nthreads", type=int, default=8)
    p.add_argument("--num-parts", type=int, default=0,
                   help="dist data shards (0 = DMLC_NUM_WORKER env)")
    p.add_argument("--part-index", type=int, default=-1,
                   help="this worker's shard (-1 = DMLC_WORKER_ID env)")
    p.add_argument("--disp-batches", type=int, default=20)
    p.add_argument("--bulk-steps", type=int, default=1,
                   help="run K steps per dispatch as one XLA "
                        "computation (lax.scan bulk execution; the "
                        "MXNET_EXEC_BULK_EXEC_TRAIN equivalent) — "
                        "amortizes host dispatch latency")
    p.add_argument("--model-prefix", default="")
    add_cpu_flag(p)
    args = p.parse_args()
    apply_backend(args)
    if not args.benchmark and not args.data_train:
        p.error("--data-train is required unless --benchmark 1")

    mx.random.seed(0)
    mesh = mesh_mod.make_mesh()  # all local devices on the 'dp' axis
    net = build_net(args)
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4},
        mesh=mesh,
        compute_dtype=None if args.dtype == "float32" else args.dtype)
    lr_steps = [int(e) for e in args.lr_step_epochs.split(",") if e]

    src = data_source(args)
    step = 0
    for epoch in range(args.epochs):
        if epoch in lr_steps:
            trainer.set_learning_rate(trainer.learning_rate * 0.1)
        tic, tic_n = time.time(), 0
        i = 0
        while i < args.steps_per_epoch:
            k = min(args.bulk_steps, args.steps_per_epoch - i)
            if k > 1 and args.benchmark:
                # synthetic batch: repeat mode transfers ONE batch
                x, y = next(src)
                loss = trainer.step_many(x, y, n_steps=k)[-1]
            elif k > 1:
                pairs = [next(src) for _ in range(k)]
                xs = np.stack([p[0].asnumpy() if hasattr(p[0], "asnumpy")
                               else np.asarray(p[0]) for p in pairs])
                ys = np.stack([p[1].asnumpy() if hasattr(p[1], "asnumpy")
                               else np.asarray(p[1]) for p in pairs])
                loss = trainer.step_many(xs, ys)[-1]
            else:
                x, y = next(src)
                loss = trainer.step(x, y)
            prev = i
            i += k
            step += k
            tic_n += args.batch_size * k
            if i // args.disp_batches > prev // args.disp_batches:
                loss.wait_to_read()
                ips = tic_n / (time.time() - tic)
                print(f"epoch {epoch} batch {i} loss "
                      f"{float(loss.asscalar()):.4f} {ips:.1f} images/s")
                tic, tic_n = time.time(), 0
        if args.model_prefix:
            trainer.sync_to_block()
            net.export(args.model_prefix, epoch=epoch)
    loss.wait_to_read()
    print(f"done: {step} steps, final loss {float(loss.asscalar()):.4f}")


if __name__ == "__main__":
    main()
